"""Benchmark matrix: ETL→train end-to-end plus flagship-kernel throughput.

Prints ONE JSON line (primary metric = the BASELINE.json headline config:
NYCTaxi ETL→train samples/sec/chip) with the other configs under ``extra``:

- ``nyctaxi``      CSV → distributed feature ETL → pjit MLP (FlaxEstimator)
- ``gbdt``         XLA histogram-GBDT on the NYCTaxi shape (xgboost parity)
- ``keras``        the TFEstimator-parity path (Keras 3 on JAX)
- ``gang``         1/2/4-rank jax.distributed DP gang (raytrain-8-worker /
                   horovod BASELINE configs; CPU ranks, labeled as such)
- ``transformer``  TransformerLM fwd+bwd tokens/s + MFU at long context,
                   flash (Pallas) vs fused-jnp fallback
- ``dlrm``         Criteo-format TSV → dictionary/log preprocess → DLRM
                   (reference examples/pytorch_dlrm.ipynb workload shape)

Budget discipline (the round-3 failure was a driver timeout that recorded
NOTHING): every config runs in its own subprocess under a hard per-config
wall cap, a global ``BENCH_BUDGET_S`` skips whatever does not fit (with an
explicit ``skipped`` marker), and on a CPU platform every config scales
itself down to CPU-feasible shapes. The parent process never imports jax, so
the final JSON line is emitted no matter what any config does.

``vs_baseline`` compares against the self-measured reference workload: the
reference publishes no numbers (BASELINE.md), so round 2 measured its
examples/pytorch_nyctaxi.py pipeline — same data, same preprocessing, same
5-layer BatchNorm MLP, torch CPU (the reference's own CI hardware class) via
benchmarks/reference_nyctaxi_torch.py. Select configs with e.g.
``BENCH_CONFIGS=nyctaxi,transformer``; force the CPU path with
``BENCH_FORCE_CPU=1`` (the wedged-tunnel drill).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from functools import partial
from typing import Optional

# Self-measured reference numbers (benchmarks/reference_nyctaxi_torch.py,
# 400k rows, torch 2.13 CPU, 2026-07-29; see BASELINE.md):
REF_NYCTAXI_B8192 = 69_924.2   # samples/s, batch 8192 (apples-to-apples)
REF_NYCTAXI_B64 = 26_456.9     # samples/s, batch 64 (as the reference ships)

ROWS = int(os.environ.get("BENCH_ROWS", "400000"))
EPOCHS = int(os.environ.get("BENCH_EPOCHS", "5"))
BATCH = int(os.environ.get("BENCH_BATCH", "8192"))
DLRM_ROWS = int(os.environ.get("BENCH_DLRM_ROWS", "120000"))
SEQ_LEN = int(os.environ.get("BENCH_SEQ_LEN", "8192"))
# train steps chained per dispatch (lax.scan): divides the ~64 ms
# remote-tunnel round trip per dispatch by this factor; numerically identical
# to per-batch dispatch (tests/test_train.py chain parity)
CHAIN = int(os.environ.get("BENCH_CHAIN", "8"))

# priority order on a live TPU: the headline and the MFU flagship claim the
# FIRST device window (three rounds lost their TPU numbers to wedges that
# fired after the early budget was spent elsewhere — VERDICT r4 #1)
CONFIG_ORDER = ["nyctaxi", "transformer", "gbdt", "dlrm", "dlrm_stream",
                "keras", "gang"]
#: configs that never touch the TPU (gang pins its ranks to CPU devices two
#: processes cannot share the one chip) — always safe to run while wedged
CPU_NATIVE = {"gang"}
#: the must-record-on-TPU configs: while the tunnel is wedged these are
#: DEFERRED (other configs run on the labeled CPU fallback in the meantime,
#: with a re-probe between each) in the hope a later probe passes; they drop
#: to the CPU fallback only when the remaining budget would otherwise expire
TPU_PRIORITY = ("nyctaxi", "transformer")
#: planning estimate for one scaled-down CPU-fallback run of a deferred
#: config (r04's full CPU matrix ran ~385 s; individual configs 60-150 s)
CPU_FALLBACK_EST_S = 150.0
#: hard per-config wall caps (seconds) — a config that blows its cap is
#: killed and recorded as a timeout; the matrix continues. TPU-priority
#: configs get one requeue after a timeout (a cold remote-tunnel compile can
#: eat most of a cap; the persistent compile cache makes the retry cheaper).
CONFIG_CAPS_S = {"nyctaxi": 300, "gbdt": 300, "keras": 240, "gang": 480,
                 "transformer": 390, "dlrm": 330, "dlrm_stream": 330}
#: total wall target; configs that do not fit inside it are skipped with an
#: explicit marker (default chosen so the full matrix + startup stays well
#: under the driver's budget: the round-2 matrix ran ~700 s on TPU)
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1260"))
#: do not even start a config with less than this much budget left
MIN_CONFIG_S = 60.0

RESULT_MARK = "##BENCH_RESULT## "


def _on_cpu() -> bool:
    return os.environ.get("RDT_BENCH_PLATFORM", "default").startswith("cpu")


def _tabular_dtype():
    """Compute dtype for the MLP/DLRM estimator configs: bf16 feeds the MXU
    on TPU; the CPU fallback emulates bf16 slowly (measured on this host:
    f32 lifted the nyctaxi floor 122k -> 180.2k samples/s, the frozen
    BENCH_LOCAL_R5_CPU.json record, and the torch-CPU baseline is f32
    anyway, so f32-vs-f32 is the fairer comparison). The
    transformer keeps bf16 on every platform — its CPU run got SLOWER in
    f32 (flash 641 -> 553 tok/s: twice the bytes through the [B,T,V] logits
    and GEMMs outweigh the emulation cost at that shape)."""
    import jax.numpy as jnp
    return jnp.float32 if _on_cpu() else jnp.bfloat16


def _apply_cpu_scaledown() -> None:
    """Shrink every knob to CPU-feasible shapes (round 3 died running the
    T=8192 transformer on the CPU fallback — a shape only a TPU can finish)."""
    global ROWS, EPOCHS, DLRM_ROWS, SEQ_LEN, BATCH
    ROWS = min(ROWS, 100_000)
    DLRM_ROWS = min(DLRM_ROWS, 30_000)
    SEQ_LEN = min(SEQ_LEN, 1024)
    BATCH = min(BATCH, 4096)
    env = os.environ
    env["BENCH_LM_DIM"] = str(min(int(env.get("BENCH_LM_DIM", "256")), 256))
    env["BENCH_LM_HEAD_DIM"] = "64"
    env["BENCH_LM_LAYERS"] = str(min(int(env.get("BENCH_LM_LAYERS", "2")), 2))
    env["BENCH_LM_STEPS"] = str(min(int(env.get("BENCH_LM_STEPS", "2")), 2))
    env["BENCH_LM_BATCH"] = "1"
    env["BENCH_GBDT_ROUNDS"] = str(
        min(int(env.get("BENCH_GBDT_ROUNDS", "5")), 5))


def _num_chips() -> int:
    import jax
    return max(1, len(jax.devices()))


def _probe_devices(timeout_s: Optional[float] = None) -> Optional[str]:
    """What platform can a fresh process actually COMPUTE on? Returns the
    platform name ("tpu", "cpu", ...) or None when device init or a tiny
    jitted matmul hangs. Enumeration alone is not enough: a wedged remote
    tunnel has been observed to list the chip and then hang on the first
    executable (r04), which would pass an enumerate-only probe and burn
    every per-config wall cap. Runs in a subprocess so a hung init cannot
    take this process with it. Note: the probe itself briefly claims the
    chip, so never run bench concurrently with another TPU job (which would
    be wrong anyway — one process owns the chip). Tune the deadline with
    BENCH_TPU_PROBE_S.
    """
    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_TPU_PROBE_S", "240"))
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax, jax.numpy as jnp; "
         "x = jnp.ones((128, 128)); "
         "jax.jit(lambda a: a @ a)(x).block_until_ready(); "
         "print('ok', jax.devices()[0].platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        return None
    for line in (out or "").splitlines():
        if line.startswith("ok "):
            return line.split()[1].strip().lower()
    return None


def _kill_group(proc: subprocess.Popen) -> None:
    """Terminate a config subprocess AND everything it spawned (executor
    actors, gang ranks). No unbounded wait: a child stuck in an
    uninterruptible device ioctl is unreapable, and waiting on it would
    recreate the hang here."""
    for sig in (signal.SIGTERM, signal.SIGKILL):
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError):
            return
        try:
            proc.wait(timeout=5)
            return
        except subprocess.TimeoutExpired:
            continue


def _steady(history):
    """Steady-state samples/s: total samples over total wall across epochs
    after the first (compile epoch). One long window is far more stable than
    averaging per-epoch rates — per-epoch numbers swing with host/tunnel load
    (round-3 verdict: dlrm varied 214k–949k between runs)."""
    rows = history[1:] or history
    wall = sum(r.get("epoch_time_s", 0.0) for r in rows)
    if wall <= 0:
        return sum(r["samples_per_s"] for r in rows) / max(len(rows), 1)
    samples = sum(r["samples_per_s"] * r.get("epoch_time_s", 0.0) for r in rows)
    return samples / wall


def _feed_split(history) -> dict:
    """Aggregate the feed/dispatch/sync wall split the estimator records per
    epoch (host-boundness evidence, round-3 verdict Weak #2), plus the
    pipeline's thread-side decode/stage/h2d phase split (ISSUE 1: the
    measured attribution of host staging vs device time; phase walls overlap
    dispatch by design, so they attribute the epoch, they don't sum to it)."""
    rows = [r for r in history[1:] if "feed_time_s" in r]
    if not rows:
        return {}
    out = {
        "feed_s": round(sum(r["feed_time_s"] for r in rows), 2),
        "dispatch_s": round(sum(r["dispatch_time_s"] for r in rows), 2),
        "device_sync_s": round(sum(r["sync_time_s"] for r in rows), 2),
    }
    if any(r.get("h2d_time_s") is not None for r in rows):
        out.update(
            decode_s=round(sum(r.get("decode_time_s", 0.0) for r in rows), 2),
            stage_s=round(sum(r.get("stage_time_s", 0.0) for r in rows), 2),
            h2d_s=round(sum(r.get("h2d_time_s", 0.0) for r in rows), 2),
        )
    return out


# steady-state averages over epochs[1:]: anything fewer than 3 epochs leaves
# a single-epoch window
STEADY_EPOCHS = max(3, EPOCHS // 2 + 1)


# --------------------------------------------------------------------- nyctaxi
def bench_nyctaxi() -> dict:
    import optax

    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.models import NYCTaxiModel
    from raydp_tpu.train import FlaxEstimator
    import jax.numpy as jnp

    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(ROWS).to_csv(csv_path, index=False)

    session = raydp_tpu.init("bench", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)
        est = FlaxEstimator(
            model=NYCTaxiModel(dtype=_tabular_dtype()),
            optimizer=optax.adam(1e-3),
            loss="smooth_l1",
            feature_columns=features,
            label_column=LABEL,
            batch_size=BATCH,
            num_epochs=EPOCHS,
            shuffle=True,
            steps_per_dispatch=CHAIN,
        )
        t0 = time.perf_counter()
        result = est.fit_on_frame(data)
        wall = time.perf_counter() - t0
        out = {"samples_per_s_per_chip": _steady(result.history) / _num_chips(),
               "wall_s": round(wall, 1), "rows": ROWS, "batch": BATCH}
        out.update(_feed_split(result.history))
        return out
    finally:
        raydp_tpu.stop()


# ----------------------------------------------------------------------- dlrm
def bench_dlrm() -> dict:
    import numpy as np
    import optax

    import raydp_tpu
    from dlrm_criteo import (
        CAT_COLS, DENSE_COLS, LABEL, NUM_DENSE, generate_criteo, pre_process,
    )
    from raydp_tpu.models import DLRM, criteo_batch_preprocessor
    from raydp_tpu.train import FlaxEstimator
    import jax.numpy as jnp

    tsv = os.path.join(tempfile.mkdtemp(prefix="rdt-bench-"), "criteo.tsv")
    generate_criteo(DLRM_ROWS, tsv)
    session = raydp_tpu.init("bench-dlrm", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        names = [LABEL] + DENSE_COLS + CAT_COLS
        df = session.read.csv(tsv, num_partitions=4,
                              options={"delimiter": "\t",
                                       "column_names": names})
        t_etl = time.perf_counter()
        df, cat_sizes = pre_process(session, df)
        est = FlaxEstimator(
            model=DLRM(categorical_sizes=cat_sizes, num_dense=NUM_DENSE,
                       embedding_dim=32, bottom_mlp=(512, 128, 32),
                       top_mlp=(1024, 1024, 512, 256, 1),
                       dtype=_tabular_dtype()),
            optimizer=optax.adagrad(1e-2),
            loss="bce_with_logits",
            feature_columns=DENSE_COLS + CAT_COLS,
            label_column=LABEL,
            feature_dtype=np.float64,
            batch_size=min(4096, BATCH),
            num_epochs=max(STEADY_EPOCHS, 4),
            batch_preprocessor=criteo_batch_preprocessor(NUM_DENSE),
            steps_per_dispatch=CHAIN,
        )
        result = est.fit_on_frame(df)
        wall = time.perf_counter() - t_etl
        out = {"samples_per_s_per_chip": _steady(result.history) / _num_chips(),
               "wall_s": round(wall, 1), "rows": DLRM_ROWS}
        out.update(_feed_split(result.history))
        return out
    finally:
        raydp_tpu.stop()


# ---------------------------------------------------------------- dlrm_stream
def bench_dlrm_stream() -> dict:
    """The HBM-overflow regime: the residency gate forced off, so training
    runs through the streaming DeviceFeed (background host decode + chained
    per-dispatch transfers) instead of the resident epoch cache — the
    realistic Criteo-at-scale case where the dataset cannot live in HBM
    (reference examples/pytorch_dlrm.ipynb; VERDICT r4 Weak #5). The
    feed/dispatch/sync split in the entry is the host-boundness evidence."""
    os.environ["RDT_DEVICE_CACHE"] = "0"
    out = bench_dlrm()
    out["streaming_forced"] = True
    return out


# ---------------------------------------------------------------------- keras
def bench_keras() -> dict:
    os.environ.setdefault("KERAS_BACKEND", "jax")
    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.train import KerasEstimator

    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(min(ROWS, 200_000)).to_csv(csv_path, index=False)
    session = raydp_tpu.init("bench-keras", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)

        def build():
            import keras
            # the NYCTaxiModel shape (256-128-64-32-1 + BatchNorm), so the
            # keras and flax paths train the same model and their numbers
            # isolate estimator overhead, not model size (round-3 Weak #6)
            model = keras.Sequential([keras.layers.Input(shape=(len(features),))])
            for width in (256, 128, 64, 32):
                model.add(keras.layers.Dense(width, activation="relu"))
                model.add(keras.layers.BatchNormalization())
            model.add(keras.layers.Dense(1))
            return model

        epochs = STEADY_EPOCHS
        est = KerasEstimator(
            model_builder=build, optimizer="adam", loss="mse",
            feature_columns=features, label_column=LABEL,
            batch_size=min(BATCH, 4096), num_epochs=epochs,
            data_parallel=_num_chips() > 1,
            steps_per_dispatch=CHAIN)
        t0 = time.perf_counter()
        result = est.fit_on_frame(data)
        wall = time.perf_counter() - t0
        return {"samples_per_s_per_chip": _steady(result.history) / _num_chips(),
                "final_loss": result.history[-1].get("loss"),
                "model": "nyctaxi-mlp-bn", "wall_s": round(wall, 1)}
    finally:
        raydp_tpu.stop()


# ----------------------------------------------------------------------- gbdt
def bench_gbdt() -> dict:
    """GBDT training on the NYCTaxi shape (BASELINE workload
    examples/xgboost_ray_nyctaxi.py:60-75: hist trees, 90/10 split,
    fare_amount label, num_boost_round=10, per-round eval). Throughput =
    training rows × boosting rounds / fit wall — each round is one full
    histogram pass over every row, the hist-method unit of work."""
    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.train import GBDTEstimator
    from raydp_tpu.utils import random_split

    rows = min(ROWS, 200_000)
    rounds = int(os.environ.get("BENCH_GBDT_ROUNDS", "10"))
    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(rows).to_csv(csv_path, index=False)
    session = raydp_tpu.init("bench-gbdt", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)
        train_df, test_df = random_split(data, [0.9, 0.1], 0)
        est = GBDTEstimator(
            params={"tree_method": "hist", "max_depth": 6},
            feature_columns=features, label_column=LABEL,
            num_boost_round=rounds)
        t_etl = time.perf_counter()
        train_ds, eval_ds = est._convert_frames(train_df, test_df)
        t0 = time.perf_counter()
        result = est.fit(train_ds, eval_ds)
        wall = time.perf_counter() - t0
        n_train = int(rows * 0.9)
        report = result.history[-1]
        return {"samples_per_s_per_chip":
                round(n_train * rounds / wall / _num_chips(), 1),
                "throughput_def": "train_rows*rounds/fit_wall",
                "rows": rows, "rounds": rounds,
                "train_rmse": report.get("train_rmse"),
                "eval_rmse": report.get("eval_rmse"),
                "fit_wall_s": round(wall, 1),
                "wall_s": round(time.perf_counter() - t_etl, 1)}
    finally:
        raydp_tpu.stop()


# ----------------------------------------------------------------------- gang
def bench_gang() -> dict:
    """Multi-worker data-parallel gang (BASELINE.json configs: "NYCTaxi MLP
    via raytrain_nyctaxi.py (Ray Train data-parallel, 8 workers)" and the
    Horovod-allreduce→psum port), swept at 1/2/4 rank processes over a FIXED
    8-virtual-CPU-device global mesh (8/4/2 devices per rank): same global
    batch and model at every width, so the curve isolates gang-orchestration
    cost — process fan-out, per-rank host feed, cross-process collectives —
    from compute. Ranks are pinned to CPU (two processes cannot share the one
    physical TPU chip), labeled cpu-gang; ``scaling`` is throughput relative
    to the 1-worker gang.

    What this sweep can and cannot show: this host exposes ONE schedulable
    CPU core (``os.sched_getaffinity`` = {0}), so every rank process
    timeshares that core and aggregate compute is constant at any width —
    rank scaling >1.0 is physically impossible here. The r4 sweep recorded
    ~0.5 at 2 ranks and the r5 diagnosis isolated ONE mechanism
    (benchmarks/gang_collective_microbench.py): the per-step XLA-inserted
    gradient all-reduces cost ~90 ms/step in-process and ~192 ms/step the
    moment they cross a process boundary on this host's loopback distributed
    backend, amplified by the ranks timesharing one core. The r5 record
    itself showed that mechanism accounts for roughly HALF the observed
    train-loop delta (``collective_mechanism_ratio`` ≈ 1.9-2.0, VERDICT r5
    Weak #2) — so the in-run microbench now measures 1/2/4 ranks (the
    4-rank leg replaces the old extrapolation) and the per-rank histories
    carry the feed pipeline's decode/stage/h2d split, so the residual
    half is attributed by measurement (host-side staging/dispatch
    serialization vs collective latency) instead of narrated away. It is
    NOT duplicated per-rank decode: the steady clock excludes the compile
    epoch, and ``feed_s`` stays ~0.01 s/epoch at every width (the
    decoded-block cache works). On a real multi-host TPU mesh the same
    all-reduces ride ICI at hardware bandwidth and overlap compute, so this
    loopback cost does not transfer. Per-width entries carry
    ``first_epoch_wall_s`` (compile) vs ``steady_epoch_wall_s`` and the
    feed split so the reader can audit the clock.
    """
    import optax

    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.data import from_frame_recoverable
    from raydp_tpu.models import NYCTaxiModel
    from raydp_tpu.train import FlaxEstimator

    rows = min(ROWS, 120_000)
    host_cpus = len(os.sched_getaffinity(0))
    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(rows).to_csv(csv_path, index=False)
    # a wide virtual node: the widest gang's 4 rank bundles must fit beside
    # the 2 executors regardless of the host's advertised core count
    session = raydp_tpu.init("bench-gang", num_executors=2, executor_cores=1,
                             executor_memory="2GB",
                             virtual_nodes=[{"CPU": 16.0,
                                             "memory": float(8 << 30)}])
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)
        ds = from_frame_recoverable(data)

        sweep = {}
        for workers in (1, 2, 4):
            est = FlaxEstimator(
                model=NYCTaxiModel(),
                optimizer=optax.adam(1e-3),
                loss="smooth_l1",
                feature_columns=features,
                label_column=LABEL,
                batch_size=min(BATCH, 4096),
                num_epochs=3,
                shuffle=False,
                steps_per_dispatch=CHAIN,
            )
            t0 = time.perf_counter()
            result = est.fit_gang(
                ds, num_workers=workers, run_timeout=1800.0,
                worker_env={
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count="
                                 f"{8 // workers}",
                    # keep ranks off the TPU tunnel
                    "PALLAS_AXON_POOL_IPS": None,
                })
            hist = result.history
            steady = hist[1:] or hist
            entry = {
                "samples_per_s": round(_steady(hist), 1),
                "final_loss": hist[-1].get("train_loss"),
                "wall_s": round(time.perf_counter() - t0, 1),
                # compile vs steady separation (VERDICT r4 #2): the first
                # epoch carries each rank's jit compile; the steady clock
                # never includes it
                "first_epoch_wall_s": round(hist[0]["epoch_time_s"], 2),
                "steady_epoch_wall_s": round(
                    sum(r["epoch_time_s"] for r in steady) / len(steady), 2),
                "steps_per_epoch": hist[-1].get("steps"),
            }
            entry.update(_feed_split(hist))
            sweep[workers] = entry
        base = sweep[1]["samples_per_s"] or 1.0
        steps = float(sweep[1].get("steps_per_epoch") or 1)
        base_step_ms = sweep[1]["steady_epoch_wall_s"] / steps * 1e3
        # per-step cost each width's cross-process all-reduces added to the
        # TRAIN loop (derived from the steady epoch walls) ...
        collective_delta_ms = {
            str(w): round(
                (v["steady_epoch_wall_s"] - sweep[1]["steady_epoch_wall_s"])
                / steps * 1e3, 1)
            for w, v in sweep.items()}
        out = {"samples_per_s_gang": sweep[2]["samples_per_s"],
               "devices": 8, "platform": "cpu-gang", "rows": rows,
               "host_cpus": host_cpus,
               "sweep": {str(w): v for w, v in sweep.items()},
               "scaling": {str(w): round(v["samples_per_s"] / base, 3)
                           for w, v in sweep.items()},
               "collective_delta_ms_per_step": collective_delta_ms}
        # checkpoint the completed sweep before the optional microbench: a
        # microbench stall at the cap must not erase the measured sweep
        print(RESULT_MARK + json.dumps(out), flush=True)
        # ... versus the INDEPENDENT measurement: the same gradient-leaf psum
        # pattern with zero model compute (benchmarks/
        # gang_collective_microbench.py), run fresh here at 1 and 2 ranks.
        # The non-circular criterion: the train loop's 2-rank delta should
        # match the pure-collective delta — overhead beyond it would be real
        # gang-machinery waste (duplicated feed/decode/compile work), which
        # feed_s and the first_epoch/steady split also rule out directly.
        try:
            import importlib.util as _ilu
            spec = _ilu.spec_from_file_location(
                "gang_collective_microbench",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "benchmarks", "gang_collective_microbench.py"))
            micro = _ilu.module_from_spec(spec)
            spec.loader.exec_module(micro)
            ms1, ms2 = micro.measure(1, timeout=180), \
                micro.measure(2, timeout=180)
            psum_delta = max(ms2 - ms1, 1e-6)
            out["psum_microbench_ms_per_step"] = {
                "1": round(ms1, 1), "2": round(ms2, 1)}
            out["scaling_predicted_by_collective_latency"] = round(
                base_step_ms / (base_step_ms + psum_delta), 3)
            # train-loop delta vs pure-collective delta at 2 ranks: ~1 means
            # the scaling loss IS collective latency; r5 recorded ~2 — half
            # the loss sits OUTSIDE the collective mechanism, which is what
            # the per-phase feed split in the sweep entries now attributes
            out["collective_mechanism_ratio"] = round(
                float(collective_delta_ms["2"]) / psum_delta, 2)
            # checkpoint before the 4-rank leg: it is the longest and a
            # stall there must not erase the 1/2-rank measurements
            print(RESULT_MARK + json.dumps(out), flush=True)
            ms4 = micro.measure(4, timeout=240)
            out["psum_microbench_ms_per_step"]["4"] = round(ms4, 1)
            # the 4-rank mechanism ratio was EXTRAPOLATED in r5 (VERDICT
            # missing #4); now it is measured in-run like the 2-rank one
            out["collective_mechanism_ratio_4"] = round(
                float(collective_delta_ms["4"]) / max(ms4 - ms1, 1e-6), 2)
        except Exception as e:  # noqa: BLE001 - the sweep stands alone
            out["psum_microbench_error"] = f"{type(e).__name__}: {e}"[:200]
        out["scaling_note"] = (
            "single-core host: ranks timeshare one CPU, so >1.0 scaling is "
            "impossible. 'collective_mechanism_ratio' (train-loop 2-rank "
            "delta / pure-psum delta, microbench in-run at 1/2/4 ranks — "
            "benchmarks/gang_collective_microbench.py) near 1 means the "
            "loss IS cross-process all-reduce latency; r5 recorded ~2, i.e. "
            "half the loss sits outside the collective mechanism — the "
            "per-width decode/stage/h2d/dispatch/sync split in 'sweep' "
            "attributes that residual (duplicated decode would show in "
            "decode_s, host dispatch serialization in dispatch_s). feed_s "
            "~0 and the first_epoch/steady split rule out re-decode and "
            "compile as causes"
            if host_cpus <= 1 else "")
        return out
    finally:
        raydp_tpu.stop()


# ---------------------------------------------------------------- transformer
_PEAK_BF16 = {  # per-chip peak bf16 FLOP/s by device kind substring
    "v6": 918e12, "v5p": 459e12, "v5": 197e12, "v4": 275e12, "v3": 123e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_BF16.items():
        if key in kind:
            return val
    return 0.0


def _lm_mode_run(mode: str, T: int) -> dict:
    """One TransformerLM fwd+bwd timing at sequence length ``T``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from raydp_tpu.models import TransformerLM, lm_loss
    from raydp_tpu.models.transformer import lm_loss_fused

    # flagship shape (ROOFLINE_LM.md): dim=1024 deepens every dense GEMM's
    # contraction (K=1024 = 8 MXU passes) and head_dim=128 feeds the MXU
    # full 128-lanes inside the flash kernel (~60% vs ~51% at head_dim=64)
    dim = int(os.environ.get("BENCH_LM_DIM", "1024"))
    head_dim = int(os.environ.get("BENCH_LM_HEAD_DIM", "128"))
    if dim % head_dim:
        raise SystemExit("BENCH_LM_DIM must be a multiple of "
                         "BENCH_LM_HEAD_DIM")
    layers = int(os.environ.get("BENCH_LM_LAYERS", "8"))
    heads, vocab = dim // head_dim, 32768
    B = int(os.environ.get("BENCH_LM_BATCH", "2"))
    steps = int(os.environ.get("BENCH_LM_STEPS", "8"))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, vocab, size=(B, T)), jnp.int32)

    model = TransformerLM(vocab_size=vocab, dim=dim, num_heads=heads,
                          num_layers=layers, attention=mode,
                          # bf16 on EVERY platform: the CPU completeness run
                          # measured slower in f32 (see _tabular_dtype)
                          dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    # all `steps` train steps are CHAINED on device inside one executable and
    # the final loss is fetched as a host float: one dispatch, one real
    # round-trip. Anything finer is untrustworthy on a remote-tunnel backend —
    # measured here: ~64 ms RTT per dispatch+fetch, and block_until_ready
    # returning without a true sync (a per-call timing once reported 26M
    # tok/s ≈ 40x peak FLOPs).
    from jax import lax

    # BENCH_LM_FUSED: 0 = materialized [B,T,V] f32 logits, 1 = chunked fused
    # CE with remat (smallest memory), 2 = chunked fused CE without remat
    # (bf16 chunk logits stored; no head recompute). Measured on v5e at
    # dim=512/T=8192 the three are within ~10% — see bench notes.
    fused = os.environ.get("BENCH_LM_FUSED", "0")

    def step_loss(p, tokens):
        if fused in ("1", "2"):
            hidden = model.apply({"params": p}, tokens, return_hidden=True)
            return lm_loss_fused(hidden, p["lm_head"]["kernel"], tokens,
                                 remat=fused == "1")
        return lm_loss(model.apply({"params": p}, tokens), tokens)

    @partial(jax.jit, donate_argnums=(0, 1))
    def run_steps(params, opt, tokens):
        def body(carry, _):
            params, opt = carry
            loss, grads = jax.value_and_grad(
                lambda p: step_loss(p, tokens))(params)
            upd, opt = tx.update(grads, opt, params)
            return (optax.apply_updates(params, upd), opt), loss

        (params, opt), losses = lax.scan(body, (params, opt), None,
                                         length=steps)
        return params, opt, losses[-1]

    params, opt, loss = run_steps(params, opt, tokens)  # compile + warm
    float(loss)
    t0 = time.perf_counter()
    params, opt, loss = run_steps(params, opt, tokens)
    loss = float(loss)
    dt = time.perf_counter() - t0
    tok_s = B * T * steps / dt

    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    # train FLOPs/token ≈ 6·(P − embed) + 6·L·d·T: the embedding table is
    # a gather, not a matmul (the lm_head, same size, IS one and stays in
    # P); attention is causal, hence T/2 effective keys per query
    matmul_params = n_params - vocab * dim
    flops_per_tok = 6 * matmul_params + 6 * layers * dim * T
    peak = _peak_flops(jax.devices()[0])
    entry = {"tokens_per_s": round(tok_s, 1), "seq_len": T,
             "loss": round(float(loss), 3), "dim": dim,
             "head_dim": head_dim, "layers": layers,
             "params_m": round(n_params / 1e6, 1)}
    if peak:
        entry["mfu"] = round(tok_s * flops_per_tok / peak, 4)
    return entry


def bench_transformer() -> dict:
    """TransformerLM fwd+bwd at long context: tokens/s and MFU, Pallas flash
    vs the dense fallback (VERDICT round 1: no recorded kernel perf).

    Per-mode isolation: dense attention materializes the full T×T score
    matrix and OOMs HBM at long context on a single chip (observed: 20.25G
    needed vs 15.75G on v5e at T=8192) — that failure must not discard the
    flash number, and dense retries at T/2 until it fits, recording where it
    first OOM'd. The gap IS the point: flash runs contexts dense cannot.
    Transient (non-OOM) failures retry once: the remote compile helper is
    known to flake (HTTP 500 / truncated body).
    """
    t_start = time.perf_counter()
    cap = float(os.environ.get("RDT_BENCH_CAP_S", "0") or 0)

    def _one(mode: str, fused: Optional[str] = None) -> dict:
        t_mode = SEQ_LEN
        transient_retries = 1
        # OOM backoffs are recorded under the ENTRY's key, so a fused2 OOM
        # can neither masquerade as a plain-flash backoff nor be swallowed
        # by one (code-review r5)
        oom_key = (f"{mode}_oom_at_seq_len" if fused is None
                   else f"{mode}_fused{fused}_oom_at_seq_len")
        prev = os.environ.get("BENCH_LM_FUSED")
        if fused is not None:
            os.environ["BENCH_LM_FUSED"] = fused
        try:
            while True:
                try:
                    entry = _lm_mode_run(mode, t_mode)
                    if fused is not None:
                        entry["fused_ce"] = fused
                    return entry
                except Exception as e:  # noqa: BLE001 - per-mode isolation
                    msg = str(e)
                    oom = ("RESOURCE_EXHAUSTED" in msg or "hbm" in msg
                           or "out of memory" in msg.lower()
                           or "Ran out of memory" in msg)
                    if oom and t_mode > 1024:
                        out.setdefault(oom_key, t_mode)
                        t_mode //= 2
                        continue
                    if not oom and transient_retries > 0:
                        transient_retries -= 1
                        continue
                    return {"error": f"{type(e).__name__}: {msg[:300]}",
                            "seq_len": t_mode}
        finally:
            if fused is not None:
                if prev is None:
                    os.environ.pop("BENCH_LM_FUSED", None)
                else:
                    os.environ["BENCH_LM_FUSED"] = prev

    out = {}
    for mode in ("flash", "dense"):
        out[mode] = _one(mode)
        # checkpoint the measured-so-far matrix: the parent keeps the LAST
        # marker line, and salvages it from partial stdout on a cap kill —
        # a later mode's compile stall can no longer cost these entries
        print(RESULT_MARK + json.dumps(out), flush=True)
    # the named open item from ROOFLINE_LM.md: chunked fused CE WITHOUT remat
    # (bf16 chunk logits kept for backward — no lm_head recompute), never yet
    # measured because its cold compile outlived the r4 tunnel. Run it last
    # (the checkpoint line above protects flash/dense) and only with at
    # least ~240s of cap left — the observed cold-compile ceiling on the
    # remote compile service; skip on the CPU fallback (its scaled-down
    # shape says nothing about the HBM/FLOPs trade).
    if not _on_cpu():
        if cap and cap - (time.perf_counter() - t_start) < 240.0:
            out["flash_fused2"] = {"skipped": "under 240s of cap left for a "
                                              "possibly-cold compile"}
        else:
            out["flash_fused2"] = _one("flash", fused="2")
    return out


# ------------------------------------------------------------ child execution
CONFIG_FNS = {"nyctaxi": bench_nyctaxi, "dlrm": bench_dlrm,
              "dlrm_stream": bench_dlrm_stream, "keras": bench_keras,
              "transformer": bench_transformer, "gbdt": bench_gbdt,
              "gang": bench_gang}


def _run_config_child(name: str) -> None:
    """Entry point of a per-config subprocess: run one config, print the
    result JSON on the marker line. The platform decision arrives via
    RDT_BENCH_PLATFORM (an env var alone does not override a
    sitecustomize-registered plugin — the in-process config.update does)."""
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "examples"))
    sys.path.insert(0, here)
    if _on_cpu():
        import jax
        jax.config.update("jax_platforms", "cpu")
        _apply_cpu_scaledown()
    try:
        result = CONFIG_FNS[name]()
    except Exception as e:  # noqa: BLE001 - the parent records the failure
        result = {"error": f"{type(e).__name__}: {str(e)[:500]}"}
    print(RESULT_MARK + json.dumps(result), flush=True)


def _spawn_config(name: str, cap_s: float, platform: str) -> dict:
    """Run one config in its own process group under a hard wall cap."""
    env = dict(os.environ)
    env["RDT_BENCH_PLATFORM"] = platform
    env["RDT_BENCH_CAP_S"] = str(cap_s)  # children pace optional extras by it
    if platform != "default":
        # belt and braces beside the child's in-process config.update; also
        # keep the TPU plugin from even loading (a plugin touch can hang on
        # wedged tunnel state, which is exactly the fallback scenario)
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_PLATFORM_NAME"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("TPU_NAME", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--config", name],
        stdout=subprocess.PIPE, stderr=None, text=True, env=env,
        start_new_session=True)
    timed_out = False
    try:
        out, _ = proc.communicate(timeout=cap_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        _kill_group(proc)
        try:  # collect what the child printed before the kill: configs
            # checkpoint partial results on marker lines as they measure
            out, _ = proc.communicate(timeout=5)
        except Exception:  # noqa: BLE001 - unreapable child
            out = ""
    result = None
    for line in (out or "").splitlines():
        if line.startswith(RESULT_MARK):
            try:  # LAST marker line wins (incremental checkpoints)
                result = json.loads(line[len(RESULT_MARK):])
            except ValueError:
                continue
    if timed_out:
        timeout_info = {"timeout_s": cap_s,
                        "error": f"config exceeded its {cap_s:.0f}s wall cap"}
        if result is not None:
            result.update(timeout_info, partial=True)
            return result
        return timeout_info
    if result is not None:
        if proc.returncode:
            # the child died AFTER a checkpoint marker (segfault/OOM-kill
            # mid-mode): the salvaged entries are real but the run is NOT
            # complete — tag it so the scheduler treats it like a failure
            # (requeue/prior_attempt) instead of a clean result
            result.update(partial=True,
                          error=f"config subprocess died rc={proc.returncode} "
                                "after a partial result")
        return result
    return {"error": f"config subprocess rc={proc.returncode}, "
                     "no result line"}


# ----------------------------------------------------------------------- main
def main():
    t_start = time.perf_counter()
    # persistent XLA compile cache, shared by every config child (and by
    # later rounds: the dir lives in the repo): r04 diagnosis showed the same
    # config compiling in 85 s warm vs >190 s cold on the remote-tunnel
    # compile service — cold compiles were what blew the gbdt/keras caps
    cache_dir = os.environ.get(
        "RDT_JAX_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
    os.makedirs(cache_dir, exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

    # tpu_expected: this host SHOULD have an accelerator (the axon plugin
    # env is present), so a failed probe means a wedged tunnel that may heal
    # within the budget — worth re-probing — rather than hardware that will
    # never appear
    tpu_expected = bool(os.environ.get("PALLAS_AXON_POOL_IPS")
                        or os.environ.get("TPU_NAME"))
    alive = False
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        cpu_label = "cpu(forced)"
        tpu_expected = False
    else:
        probed = _probe_devices()
        if probed is not None and probed != "cpu":
            alive = True
            tpu_expected = True
            cpu_label = "cpu(tpu-wedged-midrun-fallback)"  # used only post-wedge
        elif probed == "cpu" and not tpu_expected:
            # a genuinely CPU-only host (no accelerator plugin): label it
            # honestly, scale configs down, and do not chase a TPU window
            cpu_label = "cpu(host-default)"
        else:
            cpu_label = "cpu(tpu-unavailable-fallback)"
            print("# TPU device probe failed at startup; deferring "
                  "TPU-priority configs and re-probing", file=sys.stderr)

    selected = [c.strip() for c in os.environ.get(
        "BENCH_CONFIGS", ",".join(CONFIG_ORDER)).split(",") if c.strip()]
    pending = ([c for c in CONFIG_ORDER if c in selected]
               + [c for c in selected if c not in CONFIG_ORDER])
    # probe time counts against the budget (a slow-but-alive tunnel must not
    # push the matrix past the driver's wall)
    deadline = t_start + BUDGET_S
    probe_idle_s = float(os.environ.get("BENCH_PROBE_IDLE_S", "30"))

    extra = {}
    primary = None
    attempts = {}
    platform0 = "default" if alive else cpu_label  # the startup decision
    midrun_fallback = midrun_promoted = False

    def _run(name, platform):
        nonlocal primary
        attempts[name] = attempts.get(name, 0) + 1
        cap = min(float(CONFIG_CAPS_S.get(name, 300)),
                  deadline - time.perf_counter())
        t0 = time.perf_counter()
        result = _spawn_config(name, cap, platform)
        result["config_wall_s"] = round(time.perf_counter() - t0, 1)
        result.setdefault("platform", platform)
        prev = extra.get(name)
        if prev is not None and ("timeout_s" in prev or "error" in prev):
            # a fallback rerun after a failed TPU attempt keeps the failed
            # attempt on the record instead of silently replacing it — and a
            # salvaged PARTIAL attempt (e.g. TPU flash/dense measured before
            # a fused2 compile stall) is kept whole: a CPU-fallback rerun
            # must not erase real device numbers
            result.setdefault(
                "prior_attempt",
                prev if prev.get("partial") else {
                    k: prev[k] for k in ("timeout_s", "error", "platform")
                    if k in prev})
        extra[name] = result
        if name == "nyctaxi":
            primary = result
        print(f"# {name}: {result}", file=sys.stderr)
        return result

    def _reprobe(timeout_s):
        nonlocal alive, cpu_label, midrun_fallback, midrun_promoted
        was = alive
        probed = _probe_devices(timeout_s=timeout_s)
        alive = probed is not None and probed != "cpu"
        if was and not alive:
            # the tunnel can wedge MID-matrix (observed r04: configs after
            # the wedge hang at first device touch and burn their caps one
            # after another); run what remains on the labeled CPU fallback
            cpu_label = "cpu(tpu-wedged-midrun-fallback)"
            midrun_fallback = True
            print("# TPU stopped computing mid-matrix; falling back to CPU",
                  file=sys.stderr)
        elif alive and not was:
            midrun_promoted = True
            print("# TPU probe passed; promoting remaining configs to TPU",
                  file=sys.stderr)

    while pending:
        remaining = deadline - time.perf_counter()
        if remaining < MIN_CONFIG_S:
            for name in pending:
                skip = {"skipped": "budget",
                        "remaining_s": round(max(remaining, 0.0), 1)}
                # keep a recorded failed attempt over a bare skip marker
                extra.setdefault(name, skip)
                if name == "nyctaxi" and primary is None:
                    primary = extra[name]  # budget-dropped primary = 0.0
                print(f"# {name}: skipped (budget exhausted, "
                      f"{remaining:.0f}s left)", file=sys.stderr)
            break
        if alive:
            name = pending.pop(0)
            result = _run(name, "default")
            remaining = deadline - time.perf_counter()
            if "timeout_s" in result and remaining > MIN_CONFIG_S + 30.0:
                if pending:
                    _reprobe(min(90.0, remaining - 30.0))
                if name in TPU_PRIORITY and attempts.get(name, 0) < 2:
                    # one requeue: on a live TPU the retry rides the compile
                    # cache the killed attempt already warmed; after a wedge
                    # it gets the CPU fallback so the record isn't empty
                    pending.append(name)
            continue
        if not tpu_expected:
            _run(pending.pop(0), cpu_label)
            continue
        # wedged, but the tunnel may heal: run the CPU-useful configs now
        # (re-probing between them) and spend idle budget waiting before
        # surrendering the TPU-priority configs to the CPU fallback
        prio = [c for c in pending if c in TPU_PRIORITY]
        reserve = CPU_FALLBACK_EST_S * len(prio) + 90.0
        # CPU-native configs first (they lose nothing to the fallback), then
        # the remaining non-priority configs
        idx = next((i for i, c in enumerate(pending) if c in CPU_NATIVE),
                   next((i for i, c in enumerate(pending)
                         if c not in TPU_PRIORITY), None))
        cap_next = (min(float(CONFIG_CAPS_S.get(pending[idx], 300)), remaining)
                    if idx is not None else 0.0)
        if idx is not None and remaining - cap_next >= reserve:
            _run(pending.pop(idx), cpu_label)
            if prio and deadline - time.perf_counter() > MIN_CONFIG_S + 60.0:
                _reprobe(60.0)
        elif prio and remaining >= reserve + 120.0:
            # nothing CPU-useful fits beside the reserve: wait on the tunnel
            _reprobe(90.0)
            if not alive:
                time.sleep(probe_idle_s)
        else:
            _run(pending.pop(0), cpu_label)

    out = {
        "metric": "nyctaxi_e2e_train_samples_per_sec_per_chip",
        "unit": "samples/s/chip",
        # what the HEADLINE config actually ran on (ordering-proof: taken
        # from its own entry, so a mid-run wedge fallback neither relabels
        # an already-measured TPU number nor hides that the headline itself
        # ran on the CPU fallback); per-entry "platform" fields carry the
        # rest of the matrix
        "platform": (primary or {}).get("platform", platform0),
        "total_wall_s": round(time.perf_counter() - t_start, 1),
        "budget_s": BUDGET_S,
        "baseline_note": "self-measured reference workload, torch CPU "
                         f"batch 8192 ({REF_NYCTAXI_B8192:.0f} samples/s; "
                         f"batch-64-as-shipped: {REF_NYCTAXI_B64:.0f})",
        **({"platform_midrun_fallback": cpu_label} if midrun_fallback
           else {}),
        **({"platform_midrun_promoted": "default"} if midrun_promoted
           else {}),
        "extra": extra,
    }
    if primary is None:
        # headline config not selected: null, not a fake measured 0.0
        out.update(value=None, vs_baseline=None, skipped_primary=True)
    elif "error" in primary or "skipped" in primary:
        out.update(value=0.0, vs_baseline=0.0,
                   error=primary.get("error", primary.get("skipped")))
    else:
        value = round(primary["samples_per_s_per_chip"], 1)
        out.update(value=value,
                   vs_baseline=round(value / REF_NYCTAXI_B8192, 3))
    # The FULL record goes to a file; stdout gets a line the driver can
    # actually keep. r04's lesson: the driver stores only the last 2000
    # chars of stdout and parses the final line out of THAT — r04's rich
    # ~3.5k-char line was head-truncated and recorded as parsed:None, losing
    # the round's numbers. BENCH_DETAIL.json carries everything; the stdout
    # line carries the contract keys + a one-number-per-config digest.
    detail_path = os.environ.get("RDT_BENCH_DETAIL_PATH") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json")
    try:
        with open(detail_path, "w") as fh:
            json.dump(out, fh, indent=1)
    except OSError as e:
        print(f"# could not write {detail_path}: {e}", file=sys.stderr)
    compact = {k: out[k] for k in ("metric", "unit", "platform", "value",
                                   "vs_baseline", "total_wall_s")
               if k in out}
    if "error" in out:
        compact["error"] = str(out["error"])[:200]
    compact["detail"] = "BENCH_DETAIL.json"
    compact["extra"] = _digest(extra)
    line = json.dumps(compact)
    if len(line) > 1900:  # belt and braces: the digest must never trip the
        compact.pop("extra", None)  # same truncation the detail file avoids
        line = json.dumps(compact)
    print(line)


def _digest(extra: dict) -> dict:
    """One headline number per config — small enough that the driver's
    2000-char stdout tail always keeps the whole line. Failure status is
    NEVER masked by a value: a timed-out/partial/crashed entry carries its
    marker alongside whatever was salvaged, because when BENCH_DETAIL.json
    is lost this digest is the round's only surviving record."""
    dig = {}
    for name, e in extra.items():
        if not isinstance(e, dict):
            continue
        if "skipped" in e:
            dig[name] = "skipped"
            continue
        if "samples_per_s_per_chip" in e:
            val = round(e["samples_per_s_per_chip"], 1)
        elif name == "transformer":
            t = {}
            for mode in ("flash", "dense", "flash_fused2"):
                m = e.get(mode)
                if isinstance(m, dict) and "tokens_per_s" in m:
                    t[mode] = {"tok_s": m["tokens_per_s"],
                               "seq_len": m.get("seq_len")}
                    if "mfu" in m:
                        t[mode]["mfu"] = m["mfu"]
            val = t or None
        elif name == "gang":
            val = {"scaling": e.get("scaling"),
                   "mechanism_ratio": e.get("collective_mechanism_ratio")}
            if all(v is None for v in val.values()):
                val = None
        else:
            val = None
        status = ("timeout" if "timeout_s" in e
                  else "error" if "error" in e else None)
        if status is None:
            dig[name] = val if val is not None else "no-result"
        elif val is None:
            dig[name] = (status if status == "timeout"
                         else str(e["error"])[:60])
        else:
            marker = "partial" if e.get("partial") else status
            dig[name] = {"status": marker, "salvaged": val}
    return dig


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--config":
        _run_config_child(sys.argv[2])
    else:
        main()
