"""End-to-end benchmark: NYCTaxi CSV → distributed feature ETL → TPU MLP training.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: training samples/sec/chip for the Spark-ETL→train pipeline (BASELINE.md).
The reference publishes no numbers (BASELINE.md: self-measured); ``REF_BASELINE``
holds our recorded reference-equivalent throughput once measured — until then
``vs_baseline`` is reported against the first recorded run of this bench.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

# Reference-equivalent baseline (samples/sec/chip) for this exact workload.
# The reference repo publishes none (BASELINE.md); this constant records the
# first stable measurement of this pipeline (round 1, v5e-1, bf16, batch 8192:
# 498k samples/s/chip) so later rounds track speedups against it.
REF_BASELINE = 498_000.0

ROWS = int(os.environ.get("BENCH_ROWS", "400000"))
EPOCHS = int(os.environ.get("BENCH_EPOCHS", "4"))
BATCH = int(os.environ.get("BENCH_BATCH", "8192"))


def main():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "examples"))
    import optax

    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.models import NYCTaxiModel
    from raydp_tpu.train import FlaxEstimator

    import jax
    num_chips = max(1, len(jax.devices()))

    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(ROWS).to_csv(csv_path, index=False)

    session = raydp_tpu.init("bench", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)

        import jax.numpy as jnp
        est = FlaxEstimator(
            model=NYCTaxiModel(dtype=jnp.bfloat16),
            optimizer=optax.adam(1e-3),
            loss="smooth_l1",
            feature_columns=features,
            label_column=LABEL,
            batch_size=BATCH,
            num_epochs=EPOCHS,
            shuffle=True,
        )
        t0 = time.perf_counter()
        result = est.fit_on_frame(data)
        total_s = time.perf_counter() - t0

        # steady-state throughput: skip epoch 0 (compile)
        steady = result.history[1:] or result.history
        sps = sum(r["samples_per_s"] for r in steady) / len(steady)
        sps_per_chip = sps / num_chips
        print(json.dumps({
            "metric": "nyctaxi_e2e_train_samples_per_sec_per_chip",
            "value": round(sps_per_chip, 1),
            "unit": "samples/s/chip",
            "vs_baseline": round(sps_per_chip / REF_BASELINE, 3),
        }))
        print(f"# rows={ROWS} epochs={EPOCHS} batch={BATCH} chips={num_chips} "
              f"total_wall_s={total_s:.1f}", file=sys.stderr)
    finally:
        raydp_tpu.stop()


if __name__ == "__main__":
    main()
