"""Benchmark matrix: ETL→train end-to-end plus flagship-kernel throughput.

Prints ONE JSON line (primary metric = the BASELINE.json headline config:
NYCTaxi ETL→train samples/sec/chip) with the other configs under ``extra``:

- ``nyctaxi``      CSV → distributed feature ETL → pjit MLP (FlaxEstimator)
- ``dlrm``         Criteo-format TSV → dictionary/log preprocess → DLRM
                   (reference examples/pytorch_dlrm.ipynb workload shape)
- ``keras``        the TFEstimator-parity path (Keras 3 on JAX)
- ``transformer``  TransformerLM fwd+bwd tokens/s + MFU at long context,
                   flash (Pallas) vs fused-jnp fallback

``vs_baseline`` compares against the self-measured reference workload: the
reference publishes no numbers (BASELINE.md), so round 2 measured its
examples/pytorch_nyctaxi.py pipeline — same data, same preprocessing, same
5-layer BatchNorm MLP, torch CPU (the reference's own CI hardware class) via
benchmarks/reference_nyctaxi_torch.py. Select configs with e.g.
``BENCH_CONFIGS=nyctaxi,transformer``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from typing import Optional

# Self-measured reference numbers (benchmarks/reference_nyctaxi_torch.py,
# 400k rows, torch 2.13 CPU, 2026-07-29; see BASELINE.md):
REF_NYCTAXI_B8192 = 69_924.2   # samples/s, batch 8192 (apples-to-apples)
REF_NYCTAXI_B64 = 26_456.9     # samples/s, batch 64 (as the reference ships)

ROWS = int(os.environ.get("BENCH_ROWS", "400000"))
EPOCHS = int(os.environ.get("BENCH_EPOCHS", "4"))
BATCH = int(os.environ.get("BENCH_BATCH", "8192"))
DLRM_ROWS = int(os.environ.get("BENCH_DLRM_ROWS", "120000"))
SEQ_LEN = int(os.environ.get("BENCH_SEQ_LEN", "8192"))


def _num_chips() -> int:
    import jax
    return max(1, len(jax.devices()))


def _probe_devices(timeout_s: Optional[float] = None) -> bool:
    """Can a fresh process enumerate devices? Run in a subprocess so a hung
    init cannot take this process with it. Note: the probe itself briefly
    claims the chip, so never run bench concurrently with another TPU job
    (which would be wrong anyway — one process owns the chip). Tune the
    deadline with BENCH_TPU_PROBE_S.
    """
    import subprocess
    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_TPU_PROBE_S", "300"))
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return proc.returncode == 0 and "ok" in (out or "")
    except subprocess.TimeoutExpired:
        proc.kill()
        # no further wait: a child stuck in an uninterruptible device ioctl
        # is unreapable, and waiting on it would recreate the hang here
        return False


def _steady(history):
    rows = history[1:] or history
    return sum(r["samples_per_s"] for r in rows) / len(rows)


# --------------------------------------------------------------------- nyctaxi
def bench_nyctaxi() -> dict:
    import optax

    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.models import NYCTaxiModel
    from raydp_tpu.train import FlaxEstimator
    import jax.numpy as jnp

    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(ROWS).to_csv(csv_path, index=False)

    session = raydp_tpu.init("bench", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)
        est = FlaxEstimator(
            model=NYCTaxiModel(dtype=jnp.bfloat16),
            optimizer=optax.adam(1e-3),
            loss="smooth_l1",
            feature_columns=features,
            label_column=LABEL,
            batch_size=BATCH,
            num_epochs=EPOCHS,
            shuffle=True,
        )
        t0 = time.perf_counter()
        result = est.fit_on_frame(data)
        wall = time.perf_counter() - t0
        return {"samples_per_s_per_chip": _steady(result.history) / _num_chips(),
                "wall_s": round(wall, 1), "rows": ROWS, "batch": BATCH}
    finally:
        raydp_tpu.stop()


# ----------------------------------------------------------------------- dlrm
def bench_dlrm() -> dict:
    import numpy as np
    import optax

    import raydp_tpu
    from dlrm_criteo import (
        CAT_COLS, DENSE_COLS, LABEL, NUM_DENSE, generate_criteo, pre_process,
    )
    from raydp_tpu.models import DLRM, criteo_batch_preprocessor
    from raydp_tpu.train import FlaxEstimator
    import jax.numpy as jnp

    tsv = os.path.join(tempfile.mkdtemp(prefix="rdt-bench-"), "criteo.tsv")
    generate_criteo(DLRM_ROWS, tsv)
    session = raydp_tpu.init("bench-dlrm", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        names = [LABEL] + DENSE_COLS + CAT_COLS
        df = session.read.csv(tsv, num_partitions=4,
                              options={"delimiter": "\t",
                                       "column_names": names})
        t_etl = time.perf_counter()
        df, cat_sizes = pre_process(session, df)
        est = FlaxEstimator(
            model=DLRM(categorical_sizes=cat_sizes, num_dense=NUM_DENSE,
                       embedding_dim=32, bottom_mlp=(512, 128, 32),
                       top_mlp=(1024, 1024, 512, 256, 1),
                       dtype=jnp.bfloat16),
            optimizer=optax.adagrad(1e-2),
            loss="bce_with_logits",
            feature_columns=DENSE_COLS + CAT_COLS,
            label_column=LABEL,
            feature_dtype=np.float64,
            batch_size=min(4096, BATCH),
            num_epochs=max(2, EPOCHS // 2),
            batch_preprocessor=criteo_batch_preprocessor(NUM_DENSE),
        )
        result = est.fit_on_frame(df)
        wall = time.perf_counter() - t_etl
        return {"samples_per_s_per_chip": _steady(result.history) / _num_chips(),
                "wall_s": round(wall, 1), "rows": DLRM_ROWS}
    finally:
        raydp_tpu.stop()


# ---------------------------------------------------------------------- keras
def bench_keras() -> dict:
    os.environ.setdefault("KERAS_BACKEND", "jax")
    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.train import KerasEstimator

    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(min(ROWS, 200_000)).to_csv(csv_path, index=False)
    session = raydp_tpu.init("bench-keras", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)

        def build():
            import keras
            return keras.Sequential([
                keras.layers.Input(shape=(len(features),)),
                keras.layers.Dense(256, activation="relu"),
                keras.layers.BatchNormalization(),
                keras.layers.Dense(128, activation="relu"),
                keras.layers.Dense(1),
            ])

        epochs = max(3, EPOCHS // 2 + 1)
        est = KerasEstimator(
            model_builder=build, optimizer="adam", loss="mse",
            feature_columns=features, label_column=LABEL,
            batch_size=min(BATCH, 4096), num_epochs=epochs,
            data_parallel=_num_chips() > 1)
        t0 = time.perf_counter()
        result = est.fit_on_frame(data)
        wall = time.perf_counter() - t0
        return {"samples_per_s_per_chip": _steady(result.history) / _num_chips(),
                "final_loss": result.history[-1].get("loss"),
                "wall_s": round(wall, 1)}
    finally:
        raydp_tpu.stop()


# ---------------------------------------------------------------- transformer
_PEAK_BF16 = {  # per-chip peak bf16 FLOP/s by device kind substring
    "v6": 918e12, "v5p": 459e12, "v5": 197e12, "v4": 275e12, "v3": 123e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_BF16.items():
        if key in kind:
            return val
    return 0.0


def bench_transformer() -> dict:
    """TransformerLM fwd+bwd at long context: tokens/s and MFU, Pallas flash
    vs the fused-jnp fallback (VERDICT round 1: no recorded kernel perf)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from raydp_tpu.models import TransformerLM, lm_loss

    dim, heads, layers, vocab = 512, 8, 4, 32768
    B, T = int(os.environ.get("BENCH_LM_BATCH", "2")), SEQ_LEN
    steps = int(os.environ.get("BENCH_LM_STEPS", "8"))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, vocab, size=(B, T)), jnp.int32)

    out = {}
    for mode in ("flash", "dense"):
        model = TransformerLM(vocab_size=vocab, dim=dim, num_heads=heads,
                              num_layers=layers, attention=mode,
                              dtype=jnp.bfloat16)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        tx = optax.adam(1e-3)
        opt = tx.init(params)

        @jax.jit
        def step(params, opt, tokens):
            loss, grads = jax.value_and_grad(
                lambda p: lm_loss(model.apply({"params": p}, tokens), tokens)
            )(params)
            upd, opt = tx.update(grads, opt, params)
            return optax.apply_updates(params, upd), opt, loss

        params, opt, loss = step(params, opt, tokens)  # compile
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt, loss = step(params, opt, tokens)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        tok_s = B * T * steps / dt

        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree.leaves(params))
        # train FLOPs/token ≈ 6·(P − embed) + 6·L·d·T: the embedding table is
        # a gather, not a matmul (the lm_head, same size, IS one and stays in
        # P); attention is causal, hence T/2 effective keys per query
        matmul_params = n_params - vocab * dim
        flops_per_tok = 6 * matmul_params + 6 * layers * dim * T
        peak = _peak_flops(jax.devices()[0])
        entry = {"tokens_per_s": round(tok_s, 1),
                 "loss": round(float(loss), 3)}
        if peak:
            entry["mfu"] = round(tok_s * flops_per_tok / peak, 4)
        out[mode] = entry
    out["seq_len"] = T
    out["params_m"] = round(n_params / 1e6, 1)
    return out


# ----------------------------------------------------------------------- main
def main():
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "examples"))
    sys.path.insert(0, here)

    platform = "default"
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # in-process override is the only platform selection a startup hook
        # cannot trump (see .claude/skills/verify/SKILL.md gotchas)
        import jax
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu(forced)"
    elif not _probe_devices():
        # a wedged TPU tunnel blocks device init forever; a CPU run with an
        # explicit marker beats a bench that never reports
        import jax
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu(tpu-unavailable-fallback)"
        print("# TPU device init timed out; falling back to CPU",
              file=sys.stderr)

    selected = [c.strip() for c in os.environ.get(
        "BENCH_CONFIGS", "nyctaxi,dlrm,keras,transformer").split(",")
        if c.strip()]
    table = {"nyctaxi": bench_nyctaxi, "dlrm": bench_dlrm,
             "keras": bench_keras, "transformer": bench_transformer}
    extra = {}
    primary = None
    for name in selected:
        t0 = time.perf_counter()
        try:
            result = table[name]()
        except Exception as e:  # keep the matrix going; record the failure
            result = {"error": f"{type(e).__name__}: {e}"}
        result["config_wall_s"] = round(time.perf_counter() - t0, 1)
        if name == "nyctaxi":
            primary = result
        extra[name] = result
        print(f"# {name}: {result}", file=sys.stderr)

    out = {
        "metric": "nyctaxi_e2e_train_samples_per_sec_per_chip",
        "unit": "samples/s/chip",
        "platform": platform,
        "baseline_note": "self-measured reference workload, torch CPU "
                         f"batch 8192 ({REF_NYCTAXI_B8192:.0f} samples/s; "
                         f"batch-64-as-shipped: {REF_NYCTAXI_B64:.0f})",
        "extra": extra,
    }
    if primary is None:
        # headline config not selected: null, not a fake measured 0.0
        out.update(value=None, vs_baseline=None, skipped_primary=True)
    elif "error" in primary:
        out.update(value=0.0, vs_baseline=0.0, error=primary["error"])
    else:
        value = round(primary["samples_per_s_per_chip"], 1)
        out.update(value=value,
                   vs_baseline=round(value / REF_NYCTAXI_B8192, 3))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
