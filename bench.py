"""Benchmark matrix: ETL→train end-to-end plus flagship-kernel throughput.

Prints ONE JSON line (primary metric = the BASELINE.json headline config:
NYCTaxi ETL→train samples/sec/chip) with the other configs under ``extra``:

- ``nyctaxi``      CSV → distributed feature ETL → pjit MLP (FlaxEstimator)
- ``dlrm``         Criteo-format TSV → dictionary/log preprocess → DLRM
                   (reference examples/pytorch_dlrm.ipynb workload shape)
- ``keras``        the TFEstimator-parity path (Keras 3 on JAX)
- ``transformer``  TransformerLM fwd+bwd tokens/s + MFU at long context,
                   flash (Pallas) vs fused-jnp fallback
- ``gang``         2-process jax.distributed DP gang (raytrain-8-worker /
                   horovod BASELINE configs; CPU ranks, labeled as such)

``vs_baseline`` compares against the self-measured reference workload: the
reference publishes no numbers (BASELINE.md), so round 2 measured its
examples/pytorch_nyctaxi.py pipeline — same data, same preprocessing, same
5-layer BatchNorm MLP, torch CPU (the reference's own CI hardware class) via
benchmarks/reference_nyctaxi_torch.py. Select configs with e.g.
``BENCH_CONFIGS=nyctaxi,transformer``.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from functools import partial
from typing import Optional

# Self-measured reference numbers (benchmarks/reference_nyctaxi_torch.py,
# 400k rows, torch 2.13 CPU, 2026-07-29; see BASELINE.md):
REF_NYCTAXI_B8192 = 69_924.2   # samples/s, batch 8192 (apples-to-apples)
REF_NYCTAXI_B64 = 26_456.9     # samples/s, batch 64 (as the reference ships)

ROWS = int(os.environ.get("BENCH_ROWS", "400000"))
EPOCHS = int(os.environ.get("BENCH_EPOCHS", "4"))
BATCH = int(os.environ.get("BENCH_BATCH", "8192"))
DLRM_ROWS = int(os.environ.get("BENCH_DLRM_ROWS", "120000"))
SEQ_LEN = int(os.environ.get("BENCH_SEQ_LEN", "8192"))


def _num_chips() -> int:
    import jax
    return max(1, len(jax.devices()))


def _probe_devices(timeout_s: Optional[float] = None) -> bool:
    """Can a fresh process enumerate devices? Run in a subprocess so a hung
    init cannot take this process with it. Note: the probe itself briefly
    claims the chip, so never run bench concurrently with another TPU job
    (which would be wrong anyway — one process owns the chip). Tune the
    deadline with BENCH_TPU_PROBE_S.
    """
    import subprocess
    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_TPU_PROBE_S", "300"))
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return proc.returncode == 0 and "ok" in (out or "")
    except subprocess.TimeoutExpired:
        proc.kill()
        # no further wait: a child stuck in an uninterruptible device ioctl
        # is unreapable, and waiting on it would recreate the hang here
        return False


def _steady(history):
    rows = history[1:] or history
    return sum(r["samples_per_s"] for r in rows) / len(rows)


# steady-state averages over epochs[1:]: anything fewer than 3 epochs leaves
# a single-epoch window, whose numbers swing ~4x between runs on a loaded
# host/tunnel
STEADY_EPOCHS = max(3, EPOCHS // 2 + 1)


# --------------------------------------------------------------------- nyctaxi
def bench_nyctaxi() -> dict:
    import optax

    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.models import NYCTaxiModel
    from raydp_tpu.train import FlaxEstimator
    import jax.numpy as jnp

    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(ROWS).to_csv(csv_path, index=False)

    session = raydp_tpu.init("bench", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)
        est = FlaxEstimator(
            model=NYCTaxiModel(dtype=jnp.bfloat16),
            optimizer=optax.adam(1e-3),
            loss="smooth_l1",
            feature_columns=features,
            label_column=LABEL,
            batch_size=BATCH,
            num_epochs=EPOCHS,
            shuffle=True,
        )
        t0 = time.perf_counter()
        result = est.fit_on_frame(data)
        wall = time.perf_counter() - t0
        return {"samples_per_s_per_chip": _steady(result.history) / _num_chips(),
                "wall_s": round(wall, 1), "rows": ROWS, "batch": BATCH}
    finally:
        raydp_tpu.stop()


# ----------------------------------------------------------------------- dlrm
def bench_dlrm() -> dict:
    import numpy as np
    import optax

    import raydp_tpu
    from dlrm_criteo import (
        CAT_COLS, DENSE_COLS, LABEL, NUM_DENSE, generate_criteo, pre_process,
    )
    from raydp_tpu.models import DLRM, criteo_batch_preprocessor
    from raydp_tpu.train import FlaxEstimator
    import jax.numpy as jnp

    tsv = os.path.join(tempfile.mkdtemp(prefix="rdt-bench-"), "criteo.tsv")
    generate_criteo(DLRM_ROWS, tsv)
    session = raydp_tpu.init("bench-dlrm", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        names = [LABEL] + DENSE_COLS + CAT_COLS
        df = session.read.csv(tsv, num_partitions=4,
                              options={"delimiter": "\t",
                                       "column_names": names})
        t_etl = time.perf_counter()
        df, cat_sizes = pre_process(session, df)
        est = FlaxEstimator(
            model=DLRM(categorical_sizes=cat_sizes, num_dense=NUM_DENSE,
                       embedding_dim=32, bottom_mlp=(512, 128, 32),
                       top_mlp=(1024, 1024, 512, 256, 1),
                       dtype=jnp.bfloat16),
            optimizer=optax.adagrad(1e-2),
            loss="bce_with_logits",
            feature_columns=DENSE_COLS + CAT_COLS,
            label_column=LABEL,
            feature_dtype=np.float64,
            batch_size=min(4096, BATCH),
            num_epochs=STEADY_EPOCHS,
            batch_preprocessor=criteo_batch_preprocessor(NUM_DENSE),
        )
        result = est.fit_on_frame(df)
        wall = time.perf_counter() - t_etl
        return {"samples_per_s_per_chip": _steady(result.history) / _num_chips(),
                "wall_s": round(wall, 1), "rows": DLRM_ROWS}
    finally:
        raydp_tpu.stop()


# ---------------------------------------------------------------------- keras
def bench_keras() -> dict:
    os.environ.setdefault("KERAS_BACKEND", "jax")
    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.train import KerasEstimator

    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(min(ROWS, 200_000)).to_csv(csv_path, index=False)
    session = raydp_tpu.init("bench-keras", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)

        def build():
            import keras
            return keras.Sequential([
                keras.layers.Input(shape=(len(features),)),
                keras.layers.Dense(256, activation="relu"),
                keras.layers.BatchNormalization(),
                keras.layers.Dense(128, activation="relu"),
                keras.layers.Dense(1),
            ])

        epochs = STEADY_EPOCHS
        est = KerasEstimator(
            model_builder=build, optimizer="adam", loss="mse",
            feature_columns=features, label_column=LABEL,
            batch_size=min(BATCH, 4096), num_epochs=epochs,
            data_parallel=_num_chips() > 1)
        t0 = time.perf_counter()
        result = est.fit_on_frame(data)
        wall = time.perf_counter() - t0
        return {"samples_per_s_per_chip": _steady(result.history) / _num_chips(),
                "final_loss": result.history[-1].get("loss"),
                "wall_s": round(wall, 1)}
    finally:
        raydp_tpu.stop()


# ----------------------------------------------------------------------- gbdt
def bench_gbdt() -> dict:
    """GBDT training on the NYCTaxi shape (BASELINE workload
    examples/xgboost_ray_nyctaxi.py:60-75: hist trees, 90/10 split,
    fare_amount label, num_boost_round=10, per-round eval). Throughput =
    training rows × boosting rounds / fit wall — each round is one full
    histogram pass over every row, the hist-method unit of work."""
    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.train import GBDTEstimator
    from raydp_tpu.utils import random_split

    rows = min(ROWS, 200_000)
    rounds = int(os.environ.get("BENCH_GBDT_ROUNDS", "10"))
    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(rows).to_csv(csv_path, index=False)
    session = raydp_tpu.init("bench-gbdt", num_executors=2, executor_cores=2,
                             executor_memory="2GB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)
        train_df, test_df = random_split(data, [0.9, 0.1], 0)
        est = GBDTEstimator(
            params={"tree_method": "hist", "max_depth": 6},
            feature_columns=features, label_column=LABEL,
            num_boost_round=rounds)
        t_etl = time.perf_counter()
        train_ds, eval_ds = est._convert_frames(train_df, test_df)
        t0 = time.perf_counter()
        result = est.fit(train_ds, eval_ds)
        wall = time.perf_counter() - t0
        n_train = int(rows * 0.9)
        report = result.history[-1]
        return {"samples_per_s_per_chip":
                round(n_train * rounds / wall / _num_chips(), 1),
                "throughput_def": "train_rows*rounds/fit_wall",
                "rows": rows, "rounds": rounds,
                "train_rmse": report.get("train_rmse"),
                "eval_rmse": report.get("eval_rmse"),
                "fit_wall_s": round(wall, 1),
                "wall_s": round(time.perf_counter() - t_etl, 1)}
    finally:
        raydp_tpu.stop()


# ----------------------------------------------------------------------- gang
def bench_gang() -> dict:
    """Multi-worker data-parallel gang (BASELINE.json configs: "NYCTaxi MLP
    via raytrain_nyctaxi.py (Ray Train data-parallel, 8 workers)" and the
    Horovod-allreduce→psum port), swept at 1/2/4 rank processes over a FIXED
    8-virtual-CPU-device global mesh (8/4/2 devices per rank): same global
    batch and model at every width, so the curve isolates gang-orchestration
    cost — process fan-out, per-rank host feed, cross-process collectives —
    from compute. Ranks are pinned to CPU (two processes cannot share the one
    physical TPU chip), labeled cpu-gang; ``scaling`` is throughput relative
    to the 1-worker gang.
    """
    import optax

    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.data import from_frame_recoverable
    from raydp_tpu.models import NYCTaxiModel
    from raydp_tpu.train import FlaxEstimator

    rows = min(ROWS, 200_000)
    tmp = tempfile.mkdtemp(prefix="rdt-bench-")
    csv_path = os.path.join(tmp, "nyctaxi.csv")
    generate(rows).to_csv(csv_path, index=False)
    # a wide virtual node: the widest gang's 4 rank bundles must fit beside
    # the 2 executors regardless of the host's advertised core count
    session = raydp_tpu.init("bench-gang", num_executors=2, executor_cores=1,
                             executor_memory="2GB",
                             virtual_nodes=[{"CPU": 16.0,
                                             "memory": float(8 << 30)}])
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = nyc_taxi_preprocess(data)
        features = feature_columns(data)
        ds = from_frame_recoverable(data)

        sweep = {}
        for workers in (1, 2, 4):
            est = FlaxEstimator(
                model=NYCTaxiModel(),
                optimizer=optax.adam(1e-3),
                loss="smooth_l1",
                feature_columns=features,
                label_column=LABEL,
                batch_size=min(BATCH, 4096),
                num_epochs=3,
                shuffle=False,
            )
            t0 = time.perf_counter()
            result = est.fit_gang(
                ds, num_workers=workers, run_timeout=1800.0,
                worker_env={
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count="
                                 f"{8 // workers}",
                    # keep ranks off the TPU tunnel
                    "PALLAS_AXON_POOL_IPS": None,
                })
            sweep[workers] = {
                "samples_per_s": round(_steady(result.history), 1),
                "final_loss": result.history[-1].get("train_loss"),
                "wall_s": round(time.perf_counter() - t0, 1),
            }
        base = sweep[1]["samples_per_s"] or 1.0
        out = {"samples_per_s_gang": sweep[2]["samples_per_s"],
               "devices": 8, "platform": "cpu-gang", "rows": rows,
               "sweep": {str(w): v for w, v in sweep.items()},
               "scaling": {str(w): round(v["samples_per_s"] / base, 3)
                           for w, v in sweep.items()}}
        return out
    finally:
        raydp_tpu.stop()


# ---------------------------------------------------------------- transformer
_PEAK_BF16 = {  # per-chip peak bf16 FLOP/s by device kind substring
    "v6": 918e12, "v5p": 459e12, "v5": 197e12, "v4": 275e12, "v3": 123e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_BF16.items():
        if key in kind:
            return val
    return 0.0


def _lm_mode_run(mode: str, T: int) -> dict:
    """One TransformerLM fwd+bwd timing at sequence length ``T``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from raydp_tpu.models import TransformerLM, lm_loss
    from raydp_tpu.models.transformer import lm_loss_fused

    dim = int(os.environ.get("BENCH_LM_DIM", "512"))
    head_dim = int(os.environ.get("BENCH_LM_HEAD_DIM", "64"))
    if dim % head_dim:
        raise SystemExit("BENCH_LM_DIM must be a multiple of "
                         "BENCH_LM_HEAD_DIM")
    layers = int(os.environ.get("BENCH_LM_LAYERS", "4"))
    heads, vocab = dim // head_dim, 32768
    B = int(os.environ.get("BENCH_LM_BATCH", "2"))
    steps = int(os.environ.get("BENCH_LM_STEPS", "8"))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, vocab, size=(B, T)), jnp.int32)

    model = TransformerLM(vocab_size=vocab, dim=dim, num_heads=heads,
                          num_layers=layers, attention=mode,
                          dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    # all `steps` train steps are CHAINED on device inside one executable and
    # the final loss is fetched as a host float: one dispatch, one real
    # round-trip. Anything finer is untrustworthy on a remote-tunnel backend —
    # measured here: ~64 ms RTT per dispatch+fetch, and block_until_ready
    # returning without a true sync (a per-call timing once reported 26M
    # tok/s ≈ 40x peak FLOPs).
    from jax import lax

    # BENCH_LM_FUSED: 0 = materialized [B,T,V] f32 logits, 1 = chunked fused
    # CE with remat (smallest memory), 2 = chunked fused CE without remat
    # (bf16 chunk logits stored; no head recompute). Measured on v5e at
    # dim=512/T=8192 the three are within ~10% — see bench notes.
    fused = os.environ.get("BENCH_LM_FUSED", "0")

    def step_loss(p, tokens):
        if fused in ("1", "2"):
            hidden = model.apply({"params": p}, tokens, return_hidden=True)
            return lm_loss_fused(hidden, p["lm_head"]["kernel"], tokens,
                                 remat=fused == "1")
        return lm_loss(model.apply({"params": p}, tokens), tokens)

    @partial(jax.jit, donate_argnums=(0, 1))
    def run_steps(params, opt, tokens):
        def body(carry, _):
            params, opt = carry
            loss, grads = jax.value_and_grad(
                lambda p: step_loss(p, tokens))(params)
            upd, opt = tx.update(grads, opt, params)
            return (optax.apply_updates(params, upd), opt), loss

        (params, opt), losses = lax.scan(body, (params, opt), None,
                                         length=steps)
        return params, opt, losses[-1]

    params, opt, loss = run_steps(params, opt, tokens)  # compile + warm
    float(loss)
    t0 = time.perf_counter()
    params, opt, loss = run_steps(params, opt, tokens)
    loss = float(loss)
    dt = time.perf_counter() - t0
    tok_s = B * T * steps / dt

    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    # train FLOPs/token ≈ 6·(P − embed) + 6·L·d·T: the embedding table is
    # a gather, not a matmul (the lm_head, same size, IS one and stays in
    # P); attention is causal, hence T/2 effective keys per query
    matmul_params = n_params - vocab * dim
    flops_per_tok = 6 * matmul_params + 6 * layers * dim * T
    peak = _peak_flops(jax.devices()[0])
    entry = {"tokens_per_s": round(tok_s, 1), "seq_len": T,
             "loss": round(float(loss), 3),
             "params_m": round(n_params / 1e6, 1)}
    if peak:
        entry["mfu"] = round(tok_s * flops_per_tok / peak, 4)
    return entry


def bench_transformer() -> dict:
    """TransformerLM fwd+bwd at long context: tokens/s and MFU, Pallas flash
    vs the dense fallback (VERDICT round 1: no recorded kernel perf).

    Per-mode isolation: dense attention materializes the full T×T score
    matrix and OOMs HBM at long context on a single chip (observed: 20.25G
    needed vs 15.75G on v5e at T=8192) — that failure must not discard the
    flash number, and dense retries at T/2 until it fits, recording where it
    first OOM'd. The gap IS the point: flash runs contexts dense cannot.
    """
    out = {}
    for mode in ("flash", "dense"):
        t_mode = SEQ_LEN
        while True:
            try:
                entry = _lm_mode_run(mode, t_mode)
                break
            except Exception as e:  # noqa: BLE001 - per-mode isolation
                msg = str(e)
                oom = ("RESOURCE_EXHAUSTED" in msg or "hbm" in msg
                       or "out of memory" in msg.lower()
                       or "Ran out of memory" in msg)
                if oom and t_mode > 1024:
                    out.setdefault(f"{mode}_oom_at_seq_len", t_mode)
                    t_mode //= 2
                    continue
                entry = {"error": f"{type(e).__name__}: {msg[:300]}",
                         "seq_len": t_mode}
                break
        out[mode] = entry
    return out


# ----------------------------------------------------------------------- main
def main():
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.join(here, "examples"))
    sys.path.insert(0, here)

    platform = "default"
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # in-process override is the only platform selection a startup hook
        # cannot trump (see .claude/skills/verify/SKILL.md gotchas)
        import jax
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu(forced)"
    elif not _probe_devices():
        # a wedged TPU tunnel blocks device init forever; a CPU run with an
        # explicit marker beats a bench that never reports
        import jax
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu(tpu-unavailable-fallback)"
        print("# TPU device init timed out; falling back to CPU",
              file=sys.stderr)

    selected = [c.strip() for c in os.environ.get(
        "BENCH_CONFIGS",
        "nyctaxi,dlrm,keras,transformer,gbdt,gang").split(",")
        if c.strip()]
    table = {"nyctaxi": bench_nyctaxi, "dlrm": bench_dlrm,
             "keras": bench_keras, "transformer": bench_transformer,
             "gbdt": bench_gbdt, "gang": bench_gang}
    extra = {}
    primary = None
    for name in selected:
        t0 = time.perf_counter()
        try:
            result = table[name]()
        except Exception as e:  # keep the matrix going; record the failure
            result = {"error": f"{type(e).__name__}: {str(e)[:500]}"}
        result["config_wall_s"] = round(time.perf_counter() - t0, 1)
        if name == "nyctaxi":
            primary = result
        extra[name] = result
        print(f"# {name}: {result}", file=sys.stderr)

    out = {
        "metric": "nyctaxi_e2e_train_samples_per_sec_per_chip",
        "unit": "samples/s/chip",
        "platform": platform,
        "baseline_note": "self-measured reference workload, torch CPU "
                         f"batch 8192 ({REF_NYCTAXI_B8192:.0f} samples/s; "
                         f"batch-64-as-shipped: {REF_NYCTAXI_B64:.0f})",
        "extra": extra,
    }
    if primary is None:
        # headline config not selected: null, not a fake measured 0.0
        out.update(value=None, vs_baseline=None, skipped_primary=True)
    elif "error" in primary:
        out.update(value=0.0, vs_baseline=0.0, error=primary["error"])
    else:
        value = round(primary["samples_per_s_per_chip"], 1)
        out.update(value=value,
                   vs_baseline=round(value / REF_NYCTAXI_B8192, 3))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
