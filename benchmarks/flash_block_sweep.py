"""Flash-attention block-size sweep on the real chip (VERDICT #5).

Times forward and forward+backward through ``flash_attention`` for a grid of
(block_q, block_k) at long context — the evidence behind the default block
choices. Methodology for a remote-tunnel TPU backend: per-call timing is
useless (~64 ms dispatch+fetch RTT, and ``block_until_ready`` does not truly
sync), so every measurement chains ``--iters`` kernel applications on device
inside ONE executable (``lax.scan`` feeding the output back as q) and fetches
a scalar once; per-iter time = (wall - one RTT) / iters, with the RTT itself
measured on a trivial op.

Run: python benchmarks/flash_block_sweep.py [--seq-len 8192] [--dim 64]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=8192)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64, help="head dim")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--iters", type=int, default=16)
    ap.add_argument("--grad", action="store_true",
                    help="time fwd+bwd instead of fwd")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from raydp_tpu.ops.flash_attention import flash_attention

    B, T, H, D = args.batch, args.seq_len, args.heads, args.dim
    iters = args.iters
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.randn(B, T, H, D).astype(np.float32) * 0.3).astype(jnp.bfloat16)
    q, k, v = mk(), mk(), mk()

    def rtt_ms() -> float:
        x = jnp.ones((8, 8))
        f = jax.jit(lambda a, c: (a * c).sum())
        float(f(x, 1.0))
        t0 = time.perf_counter()
        float(f(x, 2.0))
        return (time.perf_counter() - t0) * 1e3

    rtt = min(rtt_ms() for _ in range(3))
    print(f"dispatch+fetch RTT: {rtt:.1f} ms (subtracted)", file=sys.stderr)

    def timed(bq: int, bk: int) -> float:
        if args.grad:
            def one(x):
                g = jax.grad(lambda qq: flash_attention(
                    qq, k, v, causal=True, block_q=bq, block_k=bk)
                    .astype(jnp.float32).sum())(x)
                return g.astype(x.dtype)
        else:
            def one(x):
                return flash_attention(x, k, v, causal=True,
                                       block_q=bq, block_k=bk)

        @jax.jit
        def chained(x):
            out = lax.scan(lambda c, _: (one(c), ()), x, None,
                           length=iters)[0]
            return out.astype(jnp.float32).sum()

        float(chained(q))                    # compile + warm
        t0 = time.perf_counter()
        float(chained(q))
        wall = (time.perf_counter() - t0) * 1e3
        per_iter = (wall - rtt) / iters
        if per_iter <= 0:
            raise RuntimeError(
                f"measurement below timing noise (wall {wall:.1f} ms <= RTT "
                f"{rtt:.1f} ms) — raise --iters or --seq-len")
        return per_iter

    results = []
    grid = [(128, 128), (128, 256), (256, 256), (256, 512), (512, 512),
            (512, 1024), (1024, 1024)]
    what = "fwd+bwd" if args.grad else "fwd"
    for bq, bk in grid:
        if bq > T or bk > T:
            continue
        try:
            us = timed(bq, bk) * 1e3
        except Exception as e:  # noqa: BLE001 - tunnel compiles can flake
            print(f"blk_q={bq:5d} blk_k={bk:5d}  FAILED "
                  f"({type(e).__name__}: {str(e)[:120]})", file=sys.stderr)
            continue
        results.append((us, bq, bk))
        print(f"blk_q={bq:5d} blk_k={bk:5d}  {us:9.1f} us/{what}",
              file=sys.stderr)
    if not results:
        raise SystemExit("every configuration failed")
    best = min(results)
    # causal flash fwd FLOPs: 2 matmuls x B*H*(T^2/2)*D x 2
    flops = 4.0 * B * H * (T * T / 2) * D * (3.5 if args.grad else 1.0)
    tflops = flops / (best[0] * 1e-6) / 1e12
    print(f"best: blk_q={best[1]} blk_k={best[2]} ({best[0]:.1f} us/{what}, "
          f"~{tflops:.1f} TFLOP/s) at B={B} T={T} H={H} D={D} on "
          f"{jax.devices()[0].device_kind}")


if __name__ == "__main__":
    main()
