"""Flash-attention block-size sweep on the real chip (VERDICT #5).

Times the Pallas forward+backward through ``flash_attention`` for a grid of
(block_q, block_k) at long context, printing μs/call and the best pair — the
evidence behind the DEFAULT_BLOCK_* choices.

Run: python benchmarks/flash_block_sweep.py [--seq-len 8192] [--dim 128]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=8192)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64, help="head dim")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from raydp_tpu.ops.flash_attention import flash_attention

    B, T, H, D = args.batch, args.seq_len, args.heads, args.dim
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, T, H, D), jnp.bfloat16)

    results = []
    grid = [(128, 128), (128, 256), (256, 256), (256, 512), (512, 512),
            (512, 1024), (1024, 1024)]
    for bq, bk in grid:
            if bq > T or bk > T:
                continue

            def loss(q, bq=bq, bk=bk):
                return flash_attention(q, k, v, causal=True,
                                       block_q=bq, block_k=bk).sum()

            step = jax.jit(jax.grad(loss))
            g = step(q)
            jax.block_until_ready(g)
            t0 = time.perf_counter()
            for _ in range(args.iters):
                g = step(q)
            jax.block_until_ready(g)
            us = (time.perf_counter() - t0) / args.iters * 1e6
            results.append((us, bq, bk))
            print(f"blk_q={bq:5d} blk_k={bk:5d}  {us:9.1f} us/fwd+bwd",
                  file=sys.stderr)
    best = min(results)
    print(f"best: blk_q={best[1]} blk_k={best[2]} ({best[0]:.1f} us) "
          f"at B={B} T={T} H={H} D={D} on "
          f"{jax.devices()[0].device_kind}")


if __name__ == "__main__":
    main()
