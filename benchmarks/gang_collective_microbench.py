"""Isolate the gang sweep's cross-process collective cost (r5 diagnosis).

The bench ``gang`` config records steady scaling ~0.5 at 2 ranks on this
host. This microbench measures the pure-collective component, with zero
model compute: the same global 8-device mesh, one ``psum`` per
NYCTaxi-MLP-gradient-sized leaf per step (the collective pattern GSPMD
inserts for data-parallel gradients), scanned 232 steps (29 steps/epoch x
chain 8).

Measured on the 1-core build host (2026-07-31):

    workers=1: 20.8 s  (89.6 ms/step)   in-process, 8 virtual devices
    workers=2: 44.5 s (191.7 ms/step)   4 virtual devices per rank

What the numbers do and do not explain (VERDICT r5 Weak #2): the recorded
in-run values (``psum_microbench_ms_per_step`` in BENCH_LOCAL_R5_CPU.json:
92.1 / 190.3) put the pure cross-process all-reduce delta near ~100
ms/step, while the recorded train-loop 2-rank steady delta is ~190-200
ms/step — the collective mechanism accounts for roughly HALF the observed
loss (``collective_mechanism_ratio`` ≈ 1.9-2.0), not all of it. The
remainder was previously unattributed; the train loop now reports a
per-phase feed split (``decode/stage/h2d`` beside ``dispatch/sync``, see
raydp_tpu/data/feed.py) so the residual shows up as measured host-side
phases instead of a guess, and ``measure(4)`` below adds the 4-rank leg the
r5 record explained only by extrapolation. On a real multi-host TPU mesh
the same all-reduces ride ICI at hardware bandwidth and overlap compute.

Run: python benchmarks/gang_collective_microbench.py
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.pop("TPU_NAME", None)

from raydp_tpu.spmd.job import create_spmd_job

STEPS = 232  # 29 steps/epoch x chain 8, one bench-gang epoch equivalent


def rank_fn(ctx):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("d",))
    # the NYCTaxi MLP's gradient leaves (kernels, biases, BN scales/offsets)
    sizes = [13 * 256, 256, 256, 256, 256 * 128, 128, 128, 128,
             128 * 64, 64, 64, 64 * 32, 32, 32 * 1]
    tree = [jnp.ones((s,), jnp.float32) for s in sizes]

    def allreduce(*leaves):
        return tuple(jax.lax.psum(leaf, "d") for leaf in leaves)

    ar = shard_map(allreduce, mesh=mesh,
                   in_specs=tuple(P() for _ in sizes),
                   out_specs=tuple(P() for _ in sizes))

    @jax.jit
    def run(tree):
        def body(c, _):
            out = ar(*c)
            return [o / mesh.size for o in out], None

        c, _ = jax.lax.scan(body, tree, None, length=STEPS)
        return c

    jax.block_until_ready(run(tree))  # compile + warm
    t0 = time.perf_counter()
    jax.block_until_ready(run(tree))
    dt = time.perf_counter() - t0
    return {"rank": ctx.rank, "steps": STEPS, "wall_s": dt,
            "ms_per_step": dt / STEPS * 1e3}


def measure(workers: int, devices: int = 8, timeout: float = 600.0) -> float:
    """ms/step of the pure-collective scan at ``workers`` rank processes over
    a fixed ``devices``-wide global mesh (chief rank's clock)."""
    job = create_spmd_job(
        f"psum{workers}", workers, jax_distributed=True,
        env={"JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count="
                          f"{devices // workers}",
             "PALLAS_AXON_POOL_IPS": None})
    job.start()
    try:
        res = job.run(rank_fn, timeout=timeout)
    finally:
        job.stop()
    return float(res[0]["ms_per_step"])


def main():
    # 1/2/4 ranks: the 4-rank leg turns the r5 record's extrapolated 4-rank
    # delta into a measurement (VERDICT r5 missing #4)
    for workers in (1, 2, 4):
        ms = measure(workers)
        print(f"workers={workers}: {ms:.2f} ms/step "
              f"({ms * STEPS / 1e3:.2f}s over {STEPS} steps)")


if __name__ == "__main__":
    main()
