"""Data-gravity bench: warm-start readiness + residency-aware locality
(ISSUE 19 acceptance).

Two configs, each a fresh session:

1. ``warm_start`` — executor readiness, cold spawn vs warm fork. A cold
   1-executor session times ``Session._grow_executor`` (fresh interpreter
   + the import chain); a warm session (``RDT_WARM_FORK=1``) times the
   same grow served by the pre-imported prototype. The warm session also
   carries the warm-fork-crash chaos leg: a ``pool.fork:crash`` rule
   kills one fresh fork BEFORE its readiness handshake — the half-started
   worker must be reaped (never admitted) or supervisor-restarted, the
   pool must still reach its target size, and results stay
   byte-identical. Asserted: warm readiness ≥2× faster than cold, every
   admitted executor reports ``warm_forked`` provenance, zero orphan
   processes after stop (prototype + workers audited by pid), zero store
   orphans, and the blackbox bundle carries ``warm_fork`` events
   (including the injected death).

2. ``gravity`` — residency-aware locality under a seeded spill +
   fault-in-delay storm, on a REAL two-host topology (the head plus one
   isolated node agent, one executor on each). The head's store budget is
   deliberately tiny, so the join's head-side bucket blobs spill
   (``store.spill:delay`` injects the slow-disk model); the agent host is
   roomy. The same join then runs under two knob settings of the SAME
   session: residency-aware (``RDT_LOCALITY_SPILLED_WEIGHT=0.5``, the
   default — spilled bytes pull half as hard, so reduce tasks tip to the
   host whose copy is fast) vs tier-blind (``=1.0``, the pre-PR
   behavior: the spilled host scores on raw bytes and the storm host
   wins). Asserted: the locality run's stage wall beats the tier-blind
   baseline, both byte-identical to each other and to a roomy-budget
   baseline, spill + fault-ins really engaged, zero orphans. The chaos
   leg retires the STORM-HOST executor mid-join (retire-during-fault-in):
   byte-identical, zero orphans, and the blackbox carries the
   ``store_fault_in`` / ``store_budget`` evidence.

``--smoke`` shrinks the load, writes to /tmp (never the recorded
artifact), and ASSERTS the contract above; the full run records
``benchmarks/GRAVITY.json`` (override with ``--out``).

Run: RDT_FAULTS_SEED=7 python benchmarks/gravity_bench.py [--smoke] [--out P]
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _ipc_bytes(table):
    import pyarrow as pa
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def _groupagg_bytes(session, df):
    from raydp_tpu.etl import functions as F
    out = df.groupBy("k").agg(F.sum("v").alias("s"), F.count("v").alias("n"))
    return _ipc_bytes(session.engine.collect(out._plan)
                      .sort_by([("k", "ascending")]))


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except (ProcessLookupError, PermissionError):
        return False


# ---- config 1: warm-start readiness ------------------------------------------


def _timed_grows(session, n):
    """Wall-clock of n sequential _grow_executor calls (spawn → admitted)."""
    times = []
    for _ in range(n):
        t0 = time.time()
        h = session._grow_executor()
        assert h is not None, "grow failed"
        times.append(time.time() - t0)
    return times


def run_warm_start_config(smoke):
    import raydp_tpu
    from raydp_tpu import faults, metrics
    from raydp_tpu.runtime import head as head_mod
    from raydp_tpu.runtime.object_store import get_client

    rows = 6_000 if smoke else 20_000
    grows = 2

    # cold baseline: every grow pays interpreter + import chain
    s = raydp_tpu.init("gravity-cold", num_executors=1, executor_cores=1,
                       executor_memory="512MB")
    try:
        df = None
        cold_times = _timed_grows(s, grows)
        rng = np.random.RandomState(0)
        df = s.createDataFrame(pd.DataFrame({
            "k": rng.randint(0, 50, rows),
            "v": rng.randint(0, 1000, rows).astype(np.int64),
        }), num_partitions=8)
        base = _groupagg_bytes(s, df)
    finally:
        raydp_tpu.stop()

    # warm: the prototype pays the imports once, grows fork from it
    os.environ["RDT_WARM_FORK"] = "1"
    os.environ["RDT_WARM_IMPORTS"] = "pyarrow,pandas,numpy,cloudpickle"
    s = raydp_tpu.init("gravity-warm", num_executors=1, executor_cores=1,
                       executor_memory="512MB")
    try:
        metrics.reset()
        client = get_client()

        # chaos leg: the next fork is killed BEFORE its readiness
        # handshake (dies-in-bootstrap). The half-started worker must be
        # reaped (grow returns None) or supervisor-restarted into a ready
        # executor — either way never a phantom member, and the plane
        # serves the retry.
        live_before = len(s.executors)
        faults.inject("pool.fork", "crash", times=1)
        try:
            h = s._grow_executor()
        finally:
            faults.clear()
        if h is None:  # reaped: the pool must be exactly where it was
            assert len(s.executors) == live_before, "phantom executor"
            h = s._grow_executor()
            assert h is not None, "warm plane did not serve the retry"
        crash_events = [e for e in metrics.events()
                        if e["kind"] == "warm_fork"
                        and e.get("injected_death")]

        warm_times = _timed_grows(s, grows)
        rng = np.random.RandomState(0)
        df = s.createDataFrame(pd.DataFrame({
            "k": rng.randint(0, 50, rows),
            "v": rng.randint(0, 1000, rows).astype(np.int64),
        }), num_partitions=8)
        # audit baseline includes the live input frame; the ACTION must
        # add nothing
        before = client.stats()["num_objects"]
        got = _groupagg_bytes(s, df)

        infos = [h.spawn_info() for h in s.executors]
        pids = [i["pid"] for i in infos]
        mgr = head_mod.get_runtime()._warm_fork[0]
        proto_pid = mgr._proc.pid if mgr is not None and mgr._proc else None
        bundle_path = metrics.write_blackbox("gravity-warm")
        with open(bundle_path) as fh:
            bundle = json.load(fh)
        driver_events = [e["kind"]
                        for e in bundle["processes"]["driver"]["events"]]
        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.25)
        orphans = client.stats()["num_objects"] - before
    finally:
        raydp_tpu.stop()
        for k in ("RDT_WARM_FORK", "RDT_WARM_IMPORTS"):
            os.environ.pop(k, None)

    # zero-orphan process audit: workers AND the prototype died with stop
    # (executor exit is graceful — a shutdown RPC with a short grace
    # delay — so poll rather than snapshot)
    audit = pids + ([proto_pid] if proto_pid else [])
    deadline = time.time() + 15
    while time.time() < deadline and any(_pid_alive(p) for p in audit):
        time.sleep(0.25)
    leaked = [p for p in audit if _pid_alive(p)]
    speedup = min(cold_times) / max(min(warm_times), 1e-6)
    record = {
        "cold_grow_s": [round(t, 3) for t in cold_times],
        "warm_grow_s": [round(t, 3) for t in warm_times],
        "readiness_speedup": round(speedup, 2),
        "warm_forked_provenance": [bool(i["warm_forked"]) for i in infos],
        "crash_fired": len(crash_events) >= 1,
        "pool_size_after_chaos": len(pids),
        "byte_identical": got == base,
        "orphan_processes": leaked,
        "orphans": orphans,
        "blackbox": bundle_path,
        "blackbox_has_warm_fork": "warm_fork" in driver_events,
    }
    print(f"[warm-start] cold={record['cold_grow_s']} "
          f"warm={record['warm_grow_s']} speedup={speedup:.1f}x "
          f"crash_fired={record['crash_fired']} "
          f"identical={record['byte_identical']} orphans={orphans}")
    return record


# ---- config 2: residency-aware locality --------------------------------------


def _start_isolated_agent(head_url, cpus=4.0):
    """A node agent with its OWN payload plane on this machine — the
    second store host of the two-host gravity topology."""
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["RDT_STORE_ISOLATED"] = "1"
    env["RDT_ARENA_FREE_GRACE_S"] = "0"
    return subprocess.Popen(
        [sys.executable, "-m", "raydp_tpu.runtime.node_agent",
         "--head", head_url, "--cpus", str(cpus)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True)


def _ensure_one_executor_per_host(session, agent_host):
    """Grow/retire until the pool is exactly one head-host + one
    agent-host executor (allocation is round-robin, so a grow may land on
    either node)."""
    for _ in range(6):
        hosts = session._executor_hosts()
        if any(h == agent_host for h in hosts.values()):
            break
        h = session._grow_executor()
        if h is None:
            continue
        if session._executor_hosts().get(h.name) != agent_host:
            session.retire_executor(h.name)
    hosts = session._executor_hosts()
    agent_execs = [n for n, h in hosts.items() if h == agent_host]
    head_execs = [n for n, h in hosts.items() if h != agent_host]
    assert agent_execs, f"no executor landed on the agent host: {hosts}"
    for name in head_execs[1:]:
        session.retire_executor(name)
    return head_execs[0], agent_execs[0]


def run_gravity_config(smoke):
    import raydp_tpu
    from raydp_tpu import metrics
    from raydp_tpu import config as cfg
    from raydp_tpu.runtime.head import get_runtime
    from raydp_tpu.runtime.object_store import get_client

    rows_a = 30_000 if smoke else 120_000
    rows_b = 10_000 if smoke else 40_000
    budget = 1 << 20 if smoke else 4 << 20
    parts = 12 if smoke else 16

    rng = np.random.RandomState(0)
    pdf_a = pd.DataFrame({
        "k": rng.randint(0, 200, rows_a),
        "v": rng.randint(0, 1000, rows_a).astype(np.int64),
        "payload": ["x" * 48 + f"{i:016d}" for i in range(rows_a)],
    })
    pdf_b = pd.DataFrame({
        "k": np.arange(200) % 200,
        "w": rng.randint(0, 1000, 200).astype(np.int64),
    })

    def join_bytes(s, df_a, df_b):
        from raydp_tpu.etl import functions as F
        out = (df_a.join(df_b, on="k")
               .groupBy("k").agg(F.sum("v").alias("s"),
                                 F.sum("w").alias("t"),
                                 F.count("v").alias("n")))
        return _ipc_bytes(s.engine.collect(out._plan)
                          .sort_by([("k", "ascending")]))

    # roomy single-host baseline: the correctness reference
    os.environ["RDT_ETL_AQE"] = "0"
    os.environ["RDT_SHUFFLE_PIPELINE"] = "1"
    s = raydp_tpu.init("gravity-base", num_executors=2, executor_cores=1,
                       executor_memory="512MB",
                       configs={cfg.SHUFFLE_PARTITIONS_KEY: str(parts)})
    try:
        base = join_bytes(s, s.createDataFrame(pdf_a, num_partitions=8),
                          s.createDataFrame(pdf_b, num_partitions=2))
    finally:
        raydp_tpu.stop()

    # the storm topology: tiny head budget + slow spill IO, roomy agent
    os.environ["RDT_STORE_HIGH_WATERMARK"] = "1e9"  # spill IS the test
    os.environ["RDT_FAULTS"] = "store.spill:delay:ms=25"
    s = raydp_tpu.init(
        "gravity", num_executors=1, executor_cores=1,
        executor_memory="512MB",
        configs={cfg.OBJECT_STORE_MEMORY_KEY: str(budget),
                 cfg.SPILL_BUDGET_KEY: str(budget),
                 cfg.SHUFFLE_PARTITIONS_KEY: str(parts)})
    agent = None
    try:
        rt = get_runtime()
        agent = _start_isolated_agent(rt.server.url)
        deadline = time.time() + 30
        while time.time() < deadline and not rt.store_hosts:
            time.sleep(0.2)
        assert rt.store_hosts, "agent never registered its store host"
        agent_host = next(iter(rt.store_hosts))
        head_exec, agent_exec = _ensure_one_executor_per_host(s, agent_host)

        metrics.reset()
        client = get_client()
        df_a = s.createDataFrame(pdf_a, num_partitions=8)
        df_b = s.createDataFrame(pdf_b, num_partitions=2)
        before = client.stats()["num_objects"]

        def run_variant(spilled_weight, repeats=2):
            """min wall over repeats; fault-in/spill deltas alongside."""
            os.environ["RDT_LOCALITY_SPILLED_WEIGHT"] = str(spilled_weight)
            walls, datas = [], []
            c0 = metrics.snapshot()["counters"]
            for _ in range(repeats):
                t0 = time.time()
                datas.append(join_bytes(s, df_a, df_b))
                walls.append(time.time() - t0)
            c1 = metrics.snapshot()["counters"]

            def delta(name):
                return (sum(c1.get(name, {}).values())
                        - sum(c0.get(name, {}).values()))
            return {"wall_s": round(min(walls), 3),
                    "walls_s": [round(w, 3) for w in walls],
                    "fault_ins": delta("store_fault_in_total"),
                    "locality_hits": delta("sched_locality_hits_total"),
                    "data": datas}

        blind = run_variant(1.0)     # tier-blind: raw bytes win
        aware = run_variant(0.5)     # residency-aware (the default)
        os.environ.pop("RDT_LOCALITY_SPILLED_WEIGHT", None)

        stats = client.stats()
        spilled = stats.get("spilled_objects", 0)

        # AQE-fed budget derivation over the measured join (the
        # store_budget evidence for the blackbox; derived budgets only
        # ever tighten, so the tiny head budget stands)
        derived = s.engine.derive_store_budgets()
        derived_stats = client.stats().get("derived_budgets", {})

        # chaos leg: retire the STORM-HOST executor mid-join, while its
        # spilled buckets are faulting in (the 25ms spill delay keeps the
        # storm alive long enough for the drain to race it)
        box = {}

        def run():
            try:
                box["bytes"] = join_bytes(s, df_a, df_b)
            except Exception as e:  # noqa: BLE001 - surfaced below
                box["error"] = repr(e)

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.4)
        s.retire_executor(head_exec)
        t.join(timeout=600)

        bundle_path = metrics.write_blackbox("gravity")
        with open(bundle_path) as fh:
            bundle = json.load(fh)
        driver_events = [e["kind"]
                         for e in bundle["processes"]["driver"]["events"]]
        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.25)
        orphans = client.stats()["num_objects"] - before
        record = {
            "rows_join_side": rows_a,
            "head_budget_bytes": budget,
            "shuffle_partitions": parts,
            "blind_wall_s": blind["wall_s"],
            "blind_walls_s": blind["walls_s"],
            "locality_wall_s": aware["wall_s"],
            "locality_walls_s": aware["walls_s"],
            "stage_wall_win": round(blind["wall_s"]
                                    / max(aware["wall_s"], 1e-6), 2),
            "blind_fault_ins": blind["fault_ins"],
            "locality_fault_ins": aware["fault_ins"],
            "locality_hits": aware["locality_hits"],
            "spill_engaged": spilled > 0,
            "spilled_objects": spilled,
            "byte_identical": all(d == base
                                  for d in blind["data"] + aware["data"]),
            "budget_derived": bool(derived) and bool(derived_stats),
            "chaos_failed_action": box.get("error"),
            "chaos_byte_identical": box.get("bytes") == base,
            "pool_size_after_chaos": len(s.executors),
            "orphans": orphans,
            "blackbox": bundle_path,
            "blackbox_has_fault_in": "store_fault_in" in driver_events,
            "blackbox_has_store_budget": "store_budget" in driver_events,
            "blackbox_has_drain": "executor_drain" in driver_events,
        }
    finally:
        raydp_tpu.stop()
        if agent is not None:
            try:
                os.killpg(agent.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                agent.kill()
        for k in ("RDT_ETL_AQE", "RDT_SHUFFLE_PIPELINE", "RDT_FAULTS",
                  "RDT_STORE_HIGH_WATERMARK",
                  "RDT_LOCALITY_SPILLED_WEIGHT"):
            os.environ.pop(k, None)
    print(f"[gravity] blind={record['blind_wall_s']}s "
          f"locality={record['locality_wall_s']}s "
          f"win={record['stage_wall_win']}x "
          f"fault_ins={record['blind_fault_ins']}"
          f"->{record['locality_fault_ins']} "
          f"identical={record['byte_identical']} "
          f"orphans={record['orphans']}")
    return record


def _assert_warm(rec):
    assert rec["readiness_speedup"] >= 2.0, rec
    assert all(rec["warm_forked_provenance"]), rec
    assert rec["crash_fired"], rec
    assert rec["byte_identical"], rec
    assert not rec["orphan_processes"], rec
    assert rec["orphans"] == 0, rec
    assert rec["blackbox_has_warm_fork"], rec


def _assert_gravity(rec):
    assert rec["byte_identical"], rec
    assert rec["spill_engaged"], rec
    assert rec["locality_wall_s"] < rec["blind_wall_s"], rec
    assert rec["locality_hits"] > 0, rec
    assert rec["budget_derived"], rec
    assert rec["chaos_failed_action"] is None, rec
    assert rec["chaos_byte_identical"], rec
    assert rec["orphans"] == 0, rec
    assert rec["blackbox_has_fault_in"], rec
    assert rec["blackbox_has_store_budget"], rec
    assert rec["blackbox_has_drain"], rec


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI contract: small load, asserts, writes to /tmp")
    ap.add_argument("--out", default=None, help="record path override")
    args = ap.parse_args()
    here = os.path.dirname(os.path.abspath(__file__))
    out = args.out or ("/tmp/GRAVITY_SMOKE.json" if args.smoke
                       else os.path.join(here, "GRAVITY.json"))
    warm = run_warm_start_config(args.smoke)
    grav = run_gravity_config(args.smoke)
    record = {
        "bench": "gravity_bench",
        # headline + PERF_CLAIMS handle (tests/test_perf_claims)
        "metric": "warm_readiness_speedup",
        "value": warm["readiness_speedup"],
        "smoke": args.smoke,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "configs": {"warm_start": warm, "gravity": grav},
    }
    with open(out, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(f"record written to {out}")
    _assert_warm(warm)
    _assert_gravity(grav)
    print("gravity bench contract: OK")


if __name__ == "__main__":
    main()
