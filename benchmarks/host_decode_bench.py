"""Host-feed decode: native staging kernel vs the numpy astype+stack path,
plus the end-to-end PIPELINE OVERLAP leg.

The streaming DeviceFeed's per-epoch host cost is dominated by this decode
for over-cap datasets (VERDICT r4 #3 / SURVEY §7 step 2). Shapes mirror the
bench workloads: NYCTaxi (25 f64 cols -> f32) and Criteo DLRM dense+cats
(13 f64 -> f32 + 26 i64 -> i32).

``--overlap`` runs the async double-buffered device feed (DevicePrefetcher,
raydp_tpu/data/feed.py) against a jitted per-batch compute and records the
per-phase split (decode/stage/h2d vs compute): the pipelined wall-clock
coming in UNDER the sum of the phase walls is the direct evidence that
host staging and H2D placement are hidden behind device compute. The
record is persisted to ``benchmarks/HOST_DECODE_DETAIL.json``
(override: RDT_HOST_DECODE_DETAIL_PATH) so the overlap claim has an
artifact, not a narrative.

Run: python benchmarks/host_decode_bench.py [rows]
     python benchmarks/host_decode_bench.py --overlap [rows]
"""
import json
import os
import sys
import time

import numpy as np
import pyarrow as pa

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from raydp_tpu.native.stage import native_stage_available, stage_table  # noqa: E402


def numpy_path(table, columns, dtype):
    return np.stack(
        [table.column(c).to_numpy(zero_copy_only=False).astype(dtype,
                                                               copy=False)
         for c in columns], axis=1)


def bench(name, table, columns, dtype, reps=5):
    # warm + correctness
    a = numpy_path(table, columns, dtype)
    b = stage_table(table, columns, np.dtype(dtype))
    assert b is not None, "kernel declined an eligible table"
    np.testing.assert_array_equal(a, b)

    t0 = time.perf_counter()
    for _ in range(reps):
        numpy_path(table, columns, dtype)
    t_np = (time.perf_counter() - t0) / reps

    t0 = time.perf_counter()
    for _ in range(reps):
        stage_table(table, columns, np.dtype(dtype))
    t_nat = (time.perf_counter() - t0) / reps

    rows = table.num_rows
    print(f"{name}: rows={rows} cols={len(columns)} "
          f"numpy={t_np * 1e3:.1f}ms native={t_nat * 1e3:.1f}ms "
          f"speedup={t_np / t_nat:.2f}x "
          f"({rows / t_nat / 1e6:.1f}M rows/s native)")


class _TableDataset:
    """The minimal dataset surface the feed needs (block_sizes / get_block),
    over in-memory Arrow tables — keeps the overlap leg free of the ETL
    runtime so it isolates the feed pipeline itself."""

    def __init__(self, tables):
        self._tables = list(tables)

    def num_blocks(self):
        return len(self._tables)

    def block_sizes(self):
        return [t.num_rows for t in self._tables]

    def get_block(self, i, zero_copy=False):
        return self._tables[i]


def overlap_run(rows=400_000, batch=8192, chain=4, hidden=256, layers=2,
                prefetch_to_device=2, out_path=None):
    """One epoch of the streaming pipeline against a jitted MLP-shaped
    compute: per-phase walls (decode/stage/h2d from the feed's thread-side
    timers, compute on the consumer clock) vs the pipeline wall-clock.

    ``overlap_hidden_s = sum(phases) - wall`` > 0 means the host phases ran
    WHILE the device computed — the double-buffering win the synchronous
    feed cannot have (its wall is exactly the sum of its phases)."""
    import jax
    import jax.numpy as jnp

    from raydp_tpu.data.feed import DeviceFeed

    n_cols = 25
    rng = np.random.RandomState(0)
    n_blocks = 8
    per = rows // n_blocks
    tables = [pa.table({f"f{i}": rng.randn(per) for i in range(n_cols)})
              for _ in range(n_blocks)]
    ds = _TableDataset(tables)
    columns = {"features": ([f"f{i}" for i in range(n_cols)], np.float32),
               "label": ("f0", np.float32)}
    feed = DeviceFeed(ds, batch, columns, shuffle=False,
                      prefetch_to_device=prefetch_to_device)

    w1 = jnp.asarray(rng.randn(n_cols, hidden).astype(np.float32))
    w2 = jnp.asarray(rng.randn(hidden, hidden).astype(np.float32))

    @jax.jit
    def compute(feats):
        h = jnp.tanh(feats @ w1)
        for _ in range(layers):
            h = jnp.tanh(h @ w2)
        return h.sum()

    # warm the compile outside the timed window (the chained path folds the
    # [k, B, C] stack into one [k*B, C] matmul batch)
    warm_rows = batch * (chain if chain > 1 else 1)
    jax.block_until_ready(compute(jnp.zeros((warm_rows, n_cols),
                                            jnp.float32)))

    compute_s = 0.0
    steps = 0
    t_wall = time.perf_counter()
    for item, k in feed.chained(chain):
        t0 = time.perf_counter()
        feats = item["features"]
        if feats.ndim == 3:   # stacked [k, B, C] chain (k may be 1 on the
            # epoch tail): fold the scan dim
            feats = feats.reshape((-1, feats.shape[-1]))
        jax.block_until_ready(compute(feats))
        compute_s += time.perf_counter() - t0
        steps += k
    wall = time.perf_counter() - t_wall
    phases = feed.timings.take()
    sum_phases = (phases["decode"] + phases["stage"] + phases["h2d"]
                  + compute_s)
    record = {
        "rows": rows, "batch": batch, "chain": chain,
        "prefetch_to_device": prefetch_to_device, "steps": steps,
        "platform": jax.devices()[0].platform,
        "wall_s": round(wall, 3),
        "decode_s": round(phases["decode"], 3),
        "stage_s": round(phases["stage"], 3),
        "h2d_s": round(phases["h2d"], 3),
        "compute_s": round(compute_s, 3),
        "sum_phases_s": round(sum_phases, 3),
        "overlap_hidden_s": round(sum_phases - wall, 3),
        "overlapped": bool(wall < sum_phases),
    }
    # rdtlint: allow[knob-registry] bench output-path plumbing, not a runtime knob
    path = out_path or os.environ.get(
        "RDT_HOST_DECODE_DETAIL_PATH",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "HOST_DECODE_DETAIL.json"))
    with open(path, "w") as fh:
        json.dump(record, fh, indent=1)
    print(json.dumps(record))
    return record


def main():
    args = [a for a in sys.argv[1:] if a != "--overlap"]
    rows = int(args[0]) if args else 400_000
    if "--overlap" in sys.argv[1:]:
        overlap_run(rows=rows)
        return
    if not native_stage_available():
        raise SystemExit("native staging kernel unavailable")
    rng = np.random.RandomState(0)

    nyctaxi = pa.table({f"f{i}": rng.randn(rows) for i in range(25)})
    bench("nyctaxi-features f64->f32", nyctaxi,
          [f"f{i}" for i in range(25)], np.float32)

    dense = pa.table({f"d{i}": rng.randn(rows) for i in range(13)})
    bench("dlrm-dense f64->f32", dense, [f"d{i}" for i in range(13)],
          np.float32)

    cats = pa.table({f"c{i}": rng.randint(0, 1 << 20, rows)
                     for i in range(26)})
    bench("dlrm-cats i64->i32", cats, [f"c{i}" for i in range(26)], np.int32)


if __name__ == "__main__":
    main()
