"""Host-feed decode: native staging kernel vs the numpy astype+stack path.

The streaming DeviceFeed's per-epoch host cost is dominated by this decode
for over-cap datasets (VERDICT r4 #3 / SURVEY §7 step 2). Shapes mirror the
bench workloads: NYCTaxi (25 f64 cols -> f32) and Criteo DLRM dense+cats
(13 f64 -> f32 + 26 i64 -> i32).

Run: python benchmarks/host_decode_bench.py [rows]
"""
import os
import sys
import time

import numpy as np
import pyarrow as pa

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from raydp_tpu.native.stage import native_stage_available, stage_table  # noqa: E402


def numpy_path(table, columns, dtype):
    return np.stack(
        [table.column(c).to_numpy(zero_copy_only=False).astype(dtype,
                                                               copy=False)
         for c in columns], axis=1)


def bench(name, table, columns, dtype, reps=5):
    # warm + correctness
    a = numpy_path(table, columns, dtype)
    b = stage_table(table, columns, np.dtype(dtype))
    assert b is not None, "kernel declined an eligible table"
    np.testing.assert_array_equal(a, b)

    t0 = time.perf_counter()
    for _ in range(reps):
        numpy_path(table, columns, dtype)
    t_np = (time.perf_counter() - t0) / reps

    t0 = time.perf_counter()
    for _ in range(reps):
        stage_table(table, columns, np.dtype(dtype))
    t_nat = (time.perf_counter() - t0) / reps

    rows = table.num_rows
    print(f"{name}: rows={rows} cols={len(columns)} "
          f"numpy={t_np * 1e3:.1f}ms native={t_nat * 1e3:.1f}ms "
          f"speedup={t_np / t_nat:.2f}x "
          f"({rows / t_nat / 1e6:.1f}M rows/s native)")


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 400_000
    if not native_stage_available():
        raise SystemExit("native staging kernel unavailable")
    rng = np.random.RandomState(0)

    nyctaxi = pa.table({f"f{i}": rng.randn(rows) for i in range(25)})
    bench("nyctaxi-features f64->f32", nyctaxi,
          [f"f{i}" for i in range(25)], np.float32)

    dense = pa.table({f"d{i}": rng.randn(rows) for i in range(13)})
    bench("dlrm-dense f64->f32", dense, [f"d{i}" for i in range(13)],
          np.float32)

    cats = pa.table({f"c{i}": rng.randint(0, 1 << 20, rows)
                     for i in range(26)})
    bench("dlrm-cats i64->i32", cats, [f"c{i}" for i in range(26)], np.int32)


if __name__ == "__main__":
    main()
