"""Multi-axis sharded training bench: the ISSUE 16 acceptance record
(MESH.json).

Two configs on one process with 8 virtual devices (the same topology the
mesh-matrix tests run), each a fresh session:

1. ``memory`` — the FSDP claim, in bytes where it is true: train an
   embedding-dominated regressor once with every parameter replicated
   (dp-only mesh) and once under ``mesh_spec=dict(fsdp=8)`` with the role
   policy choosing the specs, and record the params+optimizer bytes
   resident per process after placement (``addressable_nbytes`` — the
   number behind the ``train_param_bytes_per_process`` gauge; replicated
   leaves count one copy per device, which IS the memory they occupy).
   Against the config's per-process HBM budget the replicated run must NOT
   fit and the sharded run MUST — the adam moments inherit their
   parameter's spec, so the win covers optimizer state too. Both runs must
   land the same final loss (sharding is a layout, not a math change).
2. ``overlap`` — the sharded feed path keeps its prefetch win: streaming
   epochs under ``fsdp=8`` with ``prefetch_to_device=2`` (H2D for batch
   k+1 overlaps the jitted step of batch k) vs synchronous placement
   (``prefetch_to_device=0``). The prefetching epoch must not be slower,
   and the overlap must be visible: the feed-thread phase walls
   (decode/stage/h2d) plus dispatch exceed the epoch wall only when the
   phases actually ran concurrently.

``--smoke`` shrinks the model/rows, writes to /tmp (never the recorded
artifact), and ASSERTS the contract above; the full run records
``benchmarks/MESH.json`` (override with ``--out``).

Run: RDT_FAULTS_SEED=7 python benchmarks/mesh_bench.py [--smoke] [--out P]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# an 8-device mesh before jax imports: real accelerators keep their count,
# a CPU host splits into 8 virtual devices (the test topology)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def _embed_model(vocab, dim):
    import flax.linen as nn
    import jax.numpy as jnp

    class EmbedRegressor(nn.Module):
        """An embedding-dominated model: the table (and its adam moments)
        carries ~99% of the state bytes, so per-process residency tracks
        the embedding's placement — the shape the role policy shards
        hardest (rows over fsdp×tensor)."""

        @nn.compact
        def __call__(self, x):
            ids = jnp.clip(x.astype(jnp.int32), 0, vocab - 1)
            e = nn.Embed(vocab, dim, name="embed_tokens")(ids)
            h = nn.relu(nn.Dense(dim)(e))
            return nn.Dense(1)(h)

    return EmbedRegressor()


def _ids_frame(session, n, vocab, parts=4):
    import pandas as pd

    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, n)
    y = (ids % 7).astype(np.float64) / 7.0
    return session.createDataFrame(pd.DataFrame({"c": ids, "y": y}),
                                   num_partitions=parts)


def _linear_frame(session, n, parts=4):
    import pandas as pd

    rng = np.random.RandomState(0)
    x = rng.random_sample((n, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    return session.createDataFrame(
        pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y}),
        num_partitions=parts)


def run_memory_config(smoke):
    """Config 1: per-process param+optimizer bytes, replicated vs fsdp."""
    import optax

    import raydp_tpu
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.parallel.roles import addressable_nbytes, describe_roles
    from raydp_tpu.train import FlaxEstimator

    vocab = 8_192 if smoke else 65_536
    dim = 32
    n = 1_024 if smoke else 4_096
    # the synthetic per-process budget the claim is judged against: between
    # one sharded copy and eight replicated ones (adam triples the bytes)
    budget = (8 if smoke else 64) * (1 << 20)

    s = raydp_tpu.init("mesh-bench-mem", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        ds = from_frame(_ids_frame(s, n, vocab))

        def one_run(mesh_spec):
            est = FlaxEstimator(
                model=_embed_model(vocab, dim),
                optimizer=optax.adam(1e-2), loss="mse",
                feature_columns=["c"], label_column="y",
                feature_dtype=np.int32,
                batch_size=256, num_epochs=1, mesh_spec=mesh_spec,
                shuffle=False)
            r = est.fit(ds)
            state = est.get_state()
            return {
                "bytes_per_process": int(addressable_nbytes(state)),
                "final_loss": round(float(r.history[-1]["train_loss"]), 6),
            }, state

        replicated, _ = one_run(None)            # dp-only: 8 device copies
        sharded, state = one_run(dict(fsdp=8))   # role policy shards
        roles = describe_roles(state.params)
        embed_role = roles.get("embed_tokens/embedding", (None, ()))[0]
        record = {
            "vocab": vocab,
            "embedding_dim": dim,
            "hbm_budget_bytes": budget,
            "replicated_bytes_per_process": replicated["bytes_per_process"],
            "sharded_bytes_per_process": sharded["bytes_per_process"],
            "replicated_over_sharded": round(
                replicated["bytes_per_process"]
                / max(1, sharded["bytes_per_process"]), 2),
            "fits_replicated":
                replicated["bytes_per_process"] <= budget,
            "fits_sharded": sharded["bytes_per_process"] <= budget,
            "embedding_role": embed_role,
            "loss_replicated": replicated["final_loss"],
            "loss_sharded": sharded["final_loss"],
        }
    finally:
        raydp_tpu.stop()
    print(f"[memory] replicated={record['replicated_bytes_per_process']}B "
          f"sharded={record['sharded_bytes_per_process']}B "
          f"ratio={record['replicated_over_sharded']}x "
          f"budget={budget}B")
    return record


def run_overlap_config(smoke):
    """Config 2: sharded streaming feed, prefetch overlap vs synchronous
    placement (the fsdp batch path must keep the prefetch win)."""
    import optax

    import raydp_tpu
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator

    n = 4_096 if smoke else 32_768
    epochs = 3
    os.environ["RDT_DEVICE_CACHE"] = "0"  # force the streaming feed path
    s = raydp_tpu.init("mesh-bench-ovl", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        ds = from_frame(_linear_frame(s, n))

        def one_run(mesh_spec, prefetch):
            est = FlaxEstimator(
                model=MLP(features=(128, 64), use_batch_norm=False),
                optimizer=optax.sgd(5e-2), loss="mse",
                feature_columns=["x1", "x2"], label_column="y",
                batch_size=512, num_epochs=epochs,
                mesh_spec=mesh_spec, shuffle=False,
                prefetch_to_device=prefetch)
            r = est.fit(ds)
            h = r.history[-1]  # steady state: compile paid in epoch 0
            return {
                "epoch_time_s": round(h["epoch_time_s"], 4),
                "dispatch_time_s": round(h["dispatch_time_s"], 4),
                "feed_thread_s": round(h["decode_time_s"]
                                       + h["stage_time_s"]
                                       + h["h2d_time_s"], 4),
                "samples_per_s": round(h["samples_per_s"], 1),
                "train_loss": round(float(h["train_loss"]), 6),
            }

        replicated = one_run(None, 2)          # dp: params replicated
        sharded = one_run(dict(fsdp=8), 2)     # fsdp feed, same prefetch
        sync = one_run(dict(fsdp=8), 0)        # fsdp, synchronous placement
        # phase walls summing past the epoch wall is the overlap signature:
        # serial execution can never exceed 1.0
        overlap = (sharded["feed_thread_s"] + sharded["dispatch_time_s"]) \
            / max(sharded["epoch_time_s"], 1e-9)
        record = {
            "rows": n,
            "replicated": replicated,
            "sharded": sharded,
            "sharded_sync": sync,
            "sharded_over_replicated_epoch": round(
                sharded["epoch_time_s"]
                / max(replicated["epoch_time_s"], 1e-9), 3),
            "overlap_ratio": round(overlap, 3),
            "overlap_visible": overlap > 1.0,
        }
    finally:
        raydp_tpu.stop()
        os.environ.pop("RDT_DEVICE_CACHE", None)
    print(f"[overlap] replicated={replicated['epoch_time_s']}s "
          f"sharded={sharded['epoch_time_s']}s "
          f"ratio={record['sharded_over_replicated_epoch']}x "
          f"overlap_ratio={record['overlap_ratio']}")
    return record


def run_activation_config(smoke):
    """Config 3 (``--activation``, ISSUE 17): peak live activation bytes of
    the train step at a FIXED global batch, full-batch vs accumulated vs
    accumulated×remat vs accumulated×remat×seq-sharded.

    The model is a per-position MLP whose ``[B, T, H]`` hidden activations
    dominate the step's temp allocation — the shape gradient accumulation
    (only one ``B/k`` microbatch's activations ever live, because the
    value_and_grad runs INSIDE the scan body), remat (``jax.checkpoint``
    recomputes the residuals), and seq sharding (dim 1 over the mesh's
    ``seq`` axis) each cut along a different dimension. Peak temp bytes are
    read off XLA's own ``memory_analysis`` of the compiled step — the same
    number the estimator's ``train_activation_bytes_per_process`` gauge
    publishes — so the record is deterministic, not a wall-clock guess.
    Every variant then runs real optimizer steps on the same data: the
    final losses must agree to float-summation tolerance (residency is a
    layout/schedule change, not a math change)."""
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from raydp_tpu.parallel.mesh import make_mesh
    from raydp_tpu.parallel.roles import apply_remat

    B = 1_024 if smoke else 2_048       # fixed global batch for ALL variants
    T = 128 if smoke else 256
    H = 64 if smoke else 128
    accum = 8
    opt_steps = 3

    mesh = make_mesh(dict(data=4, seq=2))
    n_local = mesh.devices.size

    class PerPosMLP(nn.Module):
        """[B, T] → [B]: Dense stack applied per position, so the hidden
        activations are [B, T, H] — big enough that the step's temp bytes
        track activation residency, not parameter scratch."""

        @nn.compact
        def __call__(self, x):
            h = nn.relu(nn.Dense(H)(x[..., None]))
            h = nn.relu(nn.Dense(H)(h))
            return nn.Dense(1)(h).squeeze(-1).mean(axis=-1)

    model = PerPosMLP()
    rng = np.random.RandomState(0)
    xs = rng.random_sample((B, T)).astype(np.float32)
    ys = (xs.mean(axis=1) * 2.0 - 1.0).astype(np.float32)

    import jax.random as jrandom
    params0 = model.init(jrandom.PRNGKey(0), jnp.zeros((1, T)))["params"]
    tx = optax.sgd(5e-2)

    data_sh = NamedSharding(mesh, P("data"))
    seq_sh = NamedSharding(mesh, P("data", "seq"))

    def make_step(k, remat_mode, seq):
        in_sh = seq_sh if seq else data_sh

        def loss_of(p, xb, yb):
            preds = model.apply({"params": p}, xb)
            return jnp.mean((preds - yb) ** 2)

        fwd = apply_remat(loss_of, remat_mode)

        def step(p, opt, x, y):
            x = jax.lax.with_sharding_constraint(x, in_sh)
            y = jax.lax.with_sharding_constraint(y, data_sh)
            if k <= 1:
                lv, g = jax.value_and_grad(fwd)(p, x, y)
            else:
                xm = x.reshape((k, B // k, T))
                ym = y.reshape((k, B // k))

                def body(carry, mb):
                    g_acc, l_acc = carry
                    # re-constrain the microbatch: the [B]→[k, B/k] reshape
                    # breaks sharding propagation and XLA would otherwise
                    # gather every microbatch onto all data shards, erasing
                    # most of the accumulation win (measured: 4× worse)
                    mx = jax.lax.with_sharding_constraint(mb[0], in_sh)
                    my = jax.lax.with_sharding_constraint(mb[1], data_sh)
                    lv, g = jax.value_and_grad(fwd)(p, mx, my)
                    g_acc = jax.tree.map(lambda a, b: a + b, g_acc, g)
                    return (g_acc, l_acc + lv), ()

                g0 = jax.tree.map(jnp.zeros_like, p)
                (g, lv), _ = jax.lax.scan(body, (g0, jnp.float32(0)),
                                          (xm, ym))
                g = jax.tree.map(lambda a: a / k, g)
                lv = lv / k
            upd, opt = tx.update(g, opt, p)
            return optax.apply_updates(p, upd), opt, lv

        return jax.jit(step)

    x_dev = jax.device_put(xs, data_sh)
    y_dev = jax.device_put(ys, data_sh)

    def measure(name, k, remat_mode, seq):
        step = make_step(k, remat_mode, seq)
        p = jax.device_put(params0)
        opt = tx.init(p)
        compiled = step.lower(p, opt, x_dev, y_dev).compile()
        temp = int(compiled.memory_analysis().temp_size_in_bytes) * n_local
        lv = None
        for _ in range(opt_steps):
            p, opt, lv = step(p, opt, x_dev, y_dev)
        lv = float(lv)
        t0 = time.perf_counter()
        for _ in range(opt_steps):
            p, opt, lv2 = step(p, opt, x_dev, y_dev)
        jax.block_until_ready(lv2)
        wall = (time.perf_counter() - t0) / opt_steps
        print(f"[activation] {name}: temp={temp}B loss={lv:.6f} "
              f"step={wall * 1e3:.1f}ms")
        return {"bytes_per_process": temp, "final_loss": lv,
                "step_wall_s": round(wall, 5)}

    full = measure("full-batch", 1, "none", False)
    acc = measure("accum", accum, "none", False)
    acc_remat = measure("accum+remat", accum, "full", False)
    acc_remat_seq = measure("accum+remat+seq", accum, "full", True)

    ratio = round(full["bytes_per_process"]
                  / max(1, acc_remat["bytes_per_process"]), 2)
    ratio_seq = round(full["bytes_per_process"]
                      / max(1, acc_remat_seq["bytes_per_process"]), 2)
    tol = 5e-4 * max(1.0, abs(full["final_loss"]))
    return {
        "global_batch": B,
        "seq_len": T,
        "hidden": H,
        "accum_steps": accum,
        "mesh": {"data": 4, "seq": 2},
        "full_batch": full,
        "accum": acc,
        "accum_remat": acc_remat,
        "accum_remat_seq": acc_remat_seq,
        "full_over_accum_remat": ratio,
        "full_over_accum_remat_seq": ratio_seq,
        "losses_match": (
            abs(full["final_loss"] - acc_remat["final_loss"]) <= tol
            and abs(full["final_loss"] - acc_remat_seq["final_loss"]) <= tol
            and abs(full["final_loss"] - acc["final_loss"]) <= tol),
    }


def run_pipeline_config(smoke):
    """Config 4 (``--pipeline``, ISSUE 20): end-to-end pipeline-parallel
    training through the estimator — the SAME ``FlaxEstimator.fit`` call on
    the same data, once on a ``stage=1`` mesh (every layer replicated over
    the data axis) and once on ``stage=2`` (the layer stack split across
    the mesh's stage axis, accum microbatches marching through the GPipe
    scan as pipeline microbatches).

    Three numbers make the claim: per-process params+optimizer bytes after
    placement (``addressable_nbytes`` — stage-sharding the stack must cut
    resident state, the adam moments inherit their parameter's stage
    spec), steady-state step wall (the staged step may pay at most the
    pipeline bubble, ``(stages-1)/n_micro``, plus scheduling noise), and
    the final loss (staging is a placement change, not a math change — the
    losses must agree to float tolerance)."""
    import flax.linen as nn
    import optax

    import raydp_tpu
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.parallel import make_mesh
    from raydp_tpu.parallel.roles import addressable_nbytes
    from raydp_tpu.train import FlaxEstimator, PipelineModel

    dim = 64 if smoke else 128
    n_layers = 4
    n = 2_048 if smoke else 8_192
    accum = 4
    stages = 2
    epochs = 3

    class Block(nn.Module):
        """Residual MLP block: the 4×dim expansion puts the state bytes in
        the stacked layers, where the stage axis can shard them."""

        @nn.compact
        def __call__(self, x):
            h = nn.relu(nn.Dense(4 * dim)(x))
            return x + nn.Dense(dim)(h)

    s = raydp_tpu.init("mesh-bench-pipe", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        import pandas as pd

        rng = np.random.RandomState(0)
        x = rng.normal(size=(n, dim))
        w = rng.normal(size=(dim,))
        pdf = pd.DataFrame({f"f{i}": x[:, i] for i in range(dim)})
        pdf["y"] = x @ w / np.sqrt(dim)
        ds = from_frame(s.createDataFrame(pdf, num_partitions=4))

        def one_run(stage):
            est = FlaxEstimator(
                model=PipelineModel(
                    layers=[Block() for _ in range(n_layers)],
                    head=nn.Dense(1)),
                optimizer=optax.adam(1e-3), loss="mse",
                feature_columns=[f"f{i}" for i in range(dim)],
                label_column="y", batch_size=256, num_epochs=epochs,
                mesh=make_mesh(dict(stage=stage, data=8 // stage)),
                accum_steps=accum, seed=0, shuffle=False)
            r = est.fit(ds)
            h = r.history[-1]  # steady state: compile paid in epoch 0
            return {
                "bytes_per_process": int(addressable_nbytes(est.get_state())),
                "step_wall_s": round(
                    h["epoch_time_s"] / max(1, h["steps"]), 5),
                "final_loss": round(float(h["train_loss"]), 6),
            }

        unstaged = one_run(1)
        staged = one_run(stages)
    finally:
        raydp_tpu.stop()

    bubble = (stages - 1) / accum
    # CPU walls are noisy (8 virtual devices share the host's cores): the
    # bound is the pipeline-bubble model with measurement slack, the same
    # spirit as the overlap config's "not slower" bar
    wall_bound = round(unstaged["step_wall_s"] * (1.0 + bubble) * 1.5, 5)
    tol = 5e-4 * max(1.0, abs(unstaged["final_loss"]))
    record = {
        "layers": n_layers,
        "hidden": dim,
        "rows": n,
        "stages": stages,
        "accum_steps": accum,
        "unstaged": unstaged,
        "staged": staged,
        "unstaged_over_staged_bytes": round(
            unstaged["bytes_per_process"]
            / max(1, staged["bytes_per_process"]), 2),
        "bubble_fraction": bubble,
        "step_wall_bound_s": wall_bound,
        "step_wall_bounded": staged["step_wall_s"] <= wall_bound,
        "losses_match":
            abs(staged["final_loss"] - unstaged["final_loss"]) <= tol,
    }
    print(f"[pipeline] unstaged={unstaged['bytes_per_process']}B "
          f"staged={staged['bytes_per_process']}B "
          f"ratio={record['unstaged_over_staged_bytes']}x "
          f"step {unstaged['step_wall_s']}s -> {staged['step_wall_s']}s "
          f"(bound {wall_bound}s)")
    return record


def _assert_contract(record):
    configs = record["configs"]
    if "memory" in configs:
        mem = configs["memory"]
        assert mem["embedding_role"] == "embedding", mem
        assert not mem["fits_replicated"], mem
        assert mem["fits_sharded"], mem
        assert mem["replicated_over_sharded"] >= 4.0, mem
        assert abs(mem["loss_replicated"] - mem["loss_sharded"]) \
            <= 5e-4 * max(1.0, abs(mem["loss_replicated"])), mem
    if "overlap" in configs:
        ovl = configs["overlap"]
        assert ovl["overlap_visible"], ovl
        # CPU walls are noisy: "not slower" with slack, not a strict ≤
        assert ovl["sharded"]["epoch_time_s"] \
            <= ovl["replicated"]["epoch_time_s"] * 1.5, ovl
        assert ovl["sharded"]["train_loss"] \
            == ovl["sharded_sync"]["train_loss"], ovl
    if "activation" in configs:
        act = configs["activation"]
        # the ISSUE 17 acceptance bar: accumulation×remat at least HALVES
        # peak live activation bytes at the same global batch, seq sharding
        # cuts further, and every variant lands the same loss — strictly
        # decreasing residency, identical math
        assert act["full_batch"]["bytes_per_process"] \
            > act["accum"]["bytes_per_process"], act
        assert act["accum"]["bytes_per_process"] \
            >= act["accum_remat"]["bytes_per_process"], act
        assert act["accum_remat"]["bytes_per_process"] \
            > act["accum_remat_seq"]["bytes_per_process"], act
        assert act["full_over_accum_remat"] >= 2.0, act
        assert act["full_over_accum_remat_seq"] \
            > act["full_over_accum_remat"], act
        assert act["losses_match"], act
    if "pipeline" in configs:
        pipe = configs["pipeline"]
        # the ISSUE 20 acceptance bar: stage-stacked placement cuts resident
        # state (layers + adam moments live on HALF the devices at stage=2),
        # the staged step wall stays inside the bubble bound, and the staged
        # fit lands the unstaged loss — cheaper residency, identical math
        assert pipe["unstaged_over_staged_bytes"] >= 1.5, pipe
        assert pipe["step_wall_bounded"], pipe
        assert pipe["losses_match"], pipe


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI contract: small load, asserts, writes to /tmp")
    ap.add_argument("--activation", action="store_true",
                    help="run ONLY the activation-residency config (accum × "
                         "remat × seq); a full run merges configs.activation "
                         "into the existing MESH.json record so the "
                         "memory/overlap numbers (and their PERF_CLAIMS) "
                         "stay as measured")
    ap.add_argument("--pipeline", action="store_true",
                    help="run ONLY the pipeline-parallel config (stage-"
                         "stacked estimator placement vs unstaged); a full "
                         "run merges configs.pipeline into the existing "
                         "MESH.json record")
    ap.add_argument("--out", default=None, help="record path override")
    args = ap.parse_args()
    here = os.path.dirname(os.path.abspath(__file__))
    smoke_out = ("/tmp/MESH_ACTIVATION_SMOKE.json" if args.activation
                 else "/tmp/MESH_PIPELINE_SMOKE.json" if args.pipeline
                 else "/tmp/MESH_SMOKE.json")
    out = args.out or (smoke_out if args.smoke
                       else os.path.join(here, "MESH.json"))
    if args.activation:
        configs = {"activation": run_activation_config(args.smoke)}
    elif args.pipeline:
        configs = {"pipeline": run_pipeline_config(args.smoke)}
    else:
        configs = {
            "memory": run_memory_config(args.smoke),
            "overlap": run_overlap_config(args.smoke),
        }
    if not args.smoke and os.path.exists(out):
        # merge with the prior record: each config's numbers (and the claims
        # pinned to them) survive a run that didn't re-measure them
        with open(out) as fh:
            prior = json.load(fh)
        merged = dict(prior.get("configs", {}))
        merged.update(configs)
        configs = merged
    record = {
        "bench": "mesh_bench",
        # the headline number + PERF_CLAIMS handle (tests/test_perf_claims)
        "metric": "fsdp_state_bytes_reduction",
        "value": (configs["memory"]["replicated_over_sharded"]
                  if "memory" in configs
                  else configs["activation"]["full_over_accum_remat"]
                  if "activation" in configs
                  else configs["pipeline"]["unstaged_over_staged_bytes"]),
        "smoke": args.smoke,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "configs": configs,
    }
    with open(out, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(f"record written to {out}")
    _assert_contract(record)
    print("mesh bench contract: OK")


if __name__ == "__main__":
    main()
