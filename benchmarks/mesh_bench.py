"""Multi-axis sharded training bench: the ISSUE 16 acceptance record
(MESH.json).

Two configs on one process with 8 virtual devices (the same topology the
mesh-matrix tests run), each a fresh session:

1. ``memory`` — the FSDP claim, in bytes where it is true: train an
   embedding-dominated regressor once with every parameter replicated
   (dp-only mesh) and once under ``mesh_spec=dict(fsdp=8)`` with the role
   policy choosing the specs, and record the params+optimizer bytes
   resident per process after placement (``addressable_nbytes`` — the
   number behind the ``train_param_bytes_per_process`` gauge; replicated
   leaves count one copy per device, which IS the memory they occupy).
   Against the config's per-process HBM budget the replicated run must NOT
   fit and the sharded run MUST — the adam moments inherit their
   parameter's spec, so the win covers optimizer state too. Both runs must
   land the same final loss (sharding is a layout, not a math change).
2. ``overlap`` — the sharded feed path keeps its prefetch win: streaming
   epochs under ``fsdp=8`` with ``prefetch_to_device=2`` (H2D for batch
   k+1 overlaps the jitted step of batch k) vs synchronous placement
   (``prefetch_to_device=0``). The prefetching epoch must not be slower,
   and the overlap must be visible: the feed-thread phase walls
   (decode/stage/h2d) plus dispatch exceed the epoch wall only when the
   phases actually ran concurrently.

``--smoke`` shrinks the model/rows, writes to /tmp (never the recorded
artifact), and ASSERTS the contract above; the full run records
``benchmarks/MESH.json`` (override with ``--out``).

Run: RDT_FAULTS_SEED=7 python benchmarks/mesh_bench.py [--smoke] [--out P]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# an 8-device mesh before jax imports: real accelerators keep their count,
# a CPU host splits into 8 virtual devices (the test topology)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()


def _embed_model(vocab, dim):
    import flax.linen as nn
    import jax.numpy as jnp

    class EmbedRegressor(nn.Module):
        """An embedding-dominated model: the table (and its adam moments)
        carries ~99% of the state bytes, so per-process residency tracks
        the embedding's placement — the shape the role policy shards
        hardest (rows over fsdp×tensor)."""

        @nn.compact
        def __call__(self, x):
            ids = jnp.clip(x.astype(jnp.int32), 0, vocab - 1)
            e = nn.Embed(vocab, dim, name="embed_tokens")(ids)
            h = nn.relu(nn.Dense(dim)(e))
            return nn.Dense(1)(h)

    return EmbedRegressor()


def _ids_frame(session, n, vocab, parts=4):
    import pandas as pd

    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, n)
    y = (ids % 7).astype(np.float64) / 7.0
    return session.createDataFrame(pd.DataFrame({"c": ids, "y": y}),
                                   num_partitions=parts)


def _linear_frame(session, n, parts=4):
    import pandas as pd

    rng = np.random.RandomState(0)
    x = rng.random_sample((n, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    return session.createDataFrame(
        pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y}),
        num_partitions=parts)


def run_memory_config(smoke):
    """Config 1: per-process param+optimizer bytes, replicated vs fsdp."""
    import optax

    import raydp_tpu
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.parallel.roles import addressable_nbytes, describe_roles
    from raydp_tpu.train import FlaxEstimator

    vocab = 8_192 if smoke else 65_536
    dim = 32
    n = 1_024 if smoke else 4_096
    # the synthetic per-process budget the claim is judged against: between
    # one sharded copy and eight replicated ones (adam triples the bytes)
    budget = (8 if smoke else 64) * (1 << 20)

    s = raydp_tpu.init("mesh-bench-mem", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        ds = from_frame(_ids_frame(s, n, vocab))

        def one_run(mesh_spec):
            est = FlaxEstimator(
                model=_embed_model(vocab, dim),
                optimizer=optax.adam(1e-2), loss="mse",
                feature_columns=["c"], label_column="y",
                feature_dtype=np.int32,
                batch_size=256, num_epochs=1, mesh_spec=mesh_spec,
                shuffle=False)
            r = est.fit(ds)
            state = est.get_state()
            return {
                "bytes_per_process": int(addressable_nbytes(state)),
                "final_loss": round(float(r.history[-1]["train_loss"]), 6),
            }, state

        replicated, _ = one_run(None)            # dp-only: 8 device copies
        sharded, state = one_run(dict(fsdp=8))   # role policy shards
        roles = describe_roles(state.params)
        embed_role = roles.get("embed_tokens/embedding", (None, ()))[0]
        record = {
            "vocab": vocab,
            "embedding_dim": dim,
            "hbm_budget_bytes": budget,
            "replicated_bytes_per_process": replicated["bytes_per_process"],
            "sharded_bytes_per_process": sharded["bytes_per_process"],
            "replicated_over_sharded": round(
                replicated["bytes_per_process"]
                / max(1, sharded["bytes_per_process"]), 2),
            "fits_replicated":
                replicated["bytes_per_process"] <= budget,
            "fits_sharded": sharded["bytes_per_process"] <= budget,
            "embedding_role": embed_role,
            "loss_replicated": replicated["final_loss"],
            "loss_sharded": sharded["final_loss"],
        }
    finally:
        raydp_tpu.stop()
    print(f"[memory] replicated={record['replicated_bytes_per_process']}B "
          f"sharded={record['sharded_bytes_per_process']}B "
          f"ratio={record['replicated_over_sharded']}x "
          f"budget={budget}B")
    return record


def run_overlap_config(smoke):
    """Config 2: sharded streaming feed, prefetch overlap vs synchronous
    placement (the fsdp batch path must keep the prefetch win)."""
    import optax

    import raydp_tpu
    from raydp_tpu.data.dataset import from_frame
    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator

    n = 4_096 if smoke else 32_768
    epochs = 3
    os.environ["RDT_DEVICE_CACHE"] = "0"  # force the streaming feed path
    s = raydp_tpu.init("mesh-bench-ovl", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        ds = from_frame(_linear_frame(s, n))

        def one_run(mesh_spec, prefetch):
            est = FlaxEstimator(
                model=MLP(features=(128, 64), use_batch_norm=False),
                optimizer=optax.sgd(5e-2), loss="mse",
                feature_columns=["x1", "x2"], label_column="y",
                batch_size=512, num_epochs=epochs,
                mesh_spec=mesh_spec, shuffle=False,
                prefetch_to_device=prefetch)
            r = est.fit(ds)
            h = r.history[-1]  # steady state: compile paid in epoch 0
            return {
                "epoch_time_s": round(h["epoch_time_s"], 4),
                "dispatch_time_s": round(h["dispatch_time_s"], 4),
                "feed_thread_s": round(h["decode_time_s"]
                                       + h["stage_time_s"]
                                       + h["h2d_time_s"], 4),
                "samples_per_s": round(h["samples_per_s"], 1),
                "train_loss": round(float(h["train_loss"]), 6),
            }

        replicated = one_run(None, 2)          # dp: params replicated
        sharded = one_run(dict(fsdp=8), 2)     # fsdp feed, same prefetch
        sync = one_run(dict(fsdp=8), 0)        # fsdp, synchronous placement
        # phase walls summing past the epoch wall is the overlap signature:
        # serial execution can never exceed 1.0
        overlap = (sharded["feed_thread_s"] + sharded["dispatch_time_s"]) \
            / max(sharded["epoch_time_s"], 1e-9)
        record = {
            "rows": n,
            "replicated": replicated,
            "sharded": sharded,
            "sharded_sync": sync,
            "sharded_over_replicated_epoch": round(
                sharded["epoch_time_s"]
                / max(replicated["epoch_time_s"], 1e-9), 3),
            "overlap_ratio": round(overlap, 3),
            "overlap_visible": overlap > 1.0,
        }
    finally:
        raydp_tpu.stop()
        os.environ.pop("RDT_DEVICE_CACHE", None)
    print(f"[overlap] replicated={replicated['epoch_time_s']}s "
          f"sharded={sharded['epoch_time_s']}s "
          f"ratio={record['sharded_over_replicated_epoch']}x "
          f"overlap_ratio={record['overlap_ratio']}")
    return record


def _assert_contract(record):
    mem = record["configs"]["memory"]
    assert mem["embedding_role"] == "embedding", mem
    assert not mem["fits_replicated"], mem
    assert mem["fits_sharded"], mem
    assert mem["replicated_over_sharded"] >= 4.0, mem
    assert abs(mem["loss_replicated"] - mem["loss_sharded"]) \
        <= 5e-4 * max(1.0, abs(mem["loss_replicated"])), mem
    ovl = record["configs"]["overlap"]
    assert ovl["overlap_visible"], ovl
    # CPU walls are noisy: "not slower" with slack, not a strict ≤
    assert ovl["sharded"]["epoch_time_s"] \
        <= ovl["replicated"]["epoch_time_s"] * 1.5, ovl
    assert ovl["sharded"]["train_loss"] == ovl["sharded_sync"]["train_loss"], \
        ovl


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI contract: small load, asserts, writes to /tmp")
    ap.add_argument("--out", default=None, help="record path override")
    args = ap.parse_args()
    here = os.path.dirname(os.path.abspath(__file__))
    out = args.out or ("/tmp/MESH_SMOKE.json" if args.smoke
                       else os.path.join(here, "MESH.json"))
    configs = {
        "memory": run_memory_config(args.smoke),
        "overlap": run_overlap_config(args.smoke),
    }
    record = {
        "bench": "mesh_bench",
        # the headline number + PERF_CLAIMS handle (tests/test_perf_claims)
        "metric": "fsdp_state_bytes_reduction",
        "value": configs["memory"]["replicated_over_sharded"],
        "smoke": args.smoke,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "configs": configs,
    }
    with open(out, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(f"record written to {out}")
    _assert_contract(record)
    print("mesh bench contract: OK")


if __name__ == "__main__":
    main()
