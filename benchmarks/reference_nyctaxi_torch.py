"""Self-measured reference baseline: the reference's NYCTaxi workload in torch.

The reference (pang-wu/raydp) publishes no numbers (BASELINE.md), and its
stack (Spark+Ray+raydp JVM) is not installable in this environment — so this
reproduces the *workload* of `examples/pytorch_nyctaxi.py` faithfully on CPU
torch and measures end-to-end samples/sec: the same synthetic NYCTaxi data,
the same preprocessing (clean_up + time + distance features, pandas standing
in for the Spark stage), the same 5-layer BatchNorm MLP (256-128-64-16-1,
reference examples/pytorch_nyctaxi.py:69-92), SmoothL1 + Adam(1e-3), batch 64
(reference :98-102), DataLoader feed. Steady-state throughput skips epoch 0.

Run: python benchmarks/reference_nyctaxi_torch.py [--rows 400000] [--epochs 3]
Record the number in BASELINE.md and bench.py's REF_BASELINE.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples"))


def preprocess_pandas(df: pd.DataFrame) -> pd.DataFrame:
    """The reference's data_process.py pipeline, vectorized over pandas."""
    df = df[
        (df.pickup_longitude <= -72) & (df.pickup_longitude >= -76)
        & (df.dropoff_longitude <= -72) & (df.dropoff_longitude >= -76)
        & (df.pickup_latitude <= 42) & (df.pickup_latitude >= 38)
        & (df.dropoff_latitude <= 42) & (df.dropoff_latitude >= 38)
        & (df.passenger_count <= 6) & (df.passenger_count >= 1)
        & (df.fare_amount > 0) & (df.fare_amount < 250)
        & (df.dropoff_longitude != df.pickup_longitude)
        & (df.dropoff_latitude != df.pickup_latitude)
    ].copy()
    ts = pd.to_datetime(df.pop("pickup_datetime"))
    df["day"] = ts.dt.day
    df["hour_of_day"] = ts.dt.hour
    df["day_of_week"] = ts.dt.dayofweek
    df["week_of_year"] = ts.dt.isocalendar().week.astype(np.int64)
    df["month_of_year"] = ts.dt.month
    df["quarter_of_year"] = ts.dt.quarter
    df["year"] = ts.dt.year
    df["night"] = ((df.hour_of_day >= 16) & (df.hour_of_day <= 20)
                   & (df.day_of_week < 5)).astype(np.int64)
    df["late_night"] = ((df.hour_of_day <= 6)
                        | (df.hour_of_day >= 20)).astype(np.int64)
    df["abs_diff_longitude"] = (df.dropoff_longitude
                                - df.pickup_longitude).abs()
    df["abs_diff_latitude"] = (df.dropoff_latitude - df.pickup_latitude).abs()
    df["manhattan"] = df.abs_diff_longitude + df.abs_diff_latitude
    airports = {"jfk": (-73.7781, 40.6413), "ewr": (-74.1745, 40.6895),
                "lgr": (-73.8740, 40.7769), "downtown": (-74.0060, 40.7128)}
    for name, (lon, lat) in airports.items():
        df[f"pickup_distance_{name}"] = np.sqrt(
            (df.pickup_longitude - lon) ** 2 + (df.pickup_latitude - lat) ** 2)
        df[f"dropoff_distance_{name}"] = np.sqrt(
            (df.dropoff_longitude - lon) ** 2
            + (df.dropoff_latitude - lat) ** 2)
    return df


def main():
    import torch
    import torch.nn as nn
    import torch.nn.functional as TF

    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=400_000)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    from generate_nyctaxi import generate

    t_etl = time.perf_counter()
    df = preprocess_pandas(generate(args.rows))
    label = df.pop("fare_amount").to_numpy(np.float32)
    feats = df.to_numpy(np.float32)
    etl_s = time.perf_counter() - t_etl

    class NYCModel(nn.Module):
        # same topology as the reference model (pytorch_nyctaxi.py:69-92)
        def __init__(self, cols):
            super().__init__()
            widths = [256, 128, 64, 16]
            self.layers = nn.ModuleList()
            self.norms = nn.ModuleList()
            prev = cols
            for w in widths:
                self.layers.append(nn.Linear(prev, w))
                self.norms.append(nn.BatchNorm1d(w))
                prev = w
            self.head = nn.Linear(prev, 1)

        def forward(self, x):
            for lin, bn in zip(self.layers, self.norms):
                x = bn(TF.relu(lin(x)))
            return self.head(x)

    torch.set_num_threads(os.cpu_count() or 4)
    model = NYCModel(feats.shape[1])
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    crit = nn.SmoothL1Loss()
    ds = torch.utils.data.TensorDataset(
        torch.from_numpy(feats), torch.from_numpy(label))
    loader = torch.utils.data.DataLoader(ds, batch_size=args.batch_size,
                                         shuffle=True)

    rates = []
    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        seen = 0
        for xb, yb in loader:
            opt.zero_grad()
            loss = crit(model(xb).squeeze(-1), yb)
            loss.backward()
            opt.step()
            seen += xb.shape[0]
        dt = time.perf_counter() - t0
        rates.append(seen / dt)
        print(f"epoch {epoch}: {seen} samples in {dt:.1f}s "
              f"({seen / dt:.0f} samples/s) loss={float(loss):.4f}",
              file=sys.stderr)
    steady = rates[1:] or rates
    print(f"# etl_s={etl_s:.1f} rows={args.rows} batch={args.batch_size}",
          file=sys.stderr)
    print(f"{sum(steady) / len(steady):.1f}")


if __name__ == "__main__":
    main()
