"""Elastic-pool bench: autoscale under a queued burst + chaos-hardened
scale-down (ISSUE 13 acceptance), plus multi-tenant fairness (ISSUE 14).

Three configs, each a fresh session:

1. ``autoscale`` — a 1-executor session with the controller armed
   (min=1, max=3, fast cadence) under a seeded per-task delay
   (``executor.run_task:delay`` — the queued-burst model): a burst of
   concurrent groupagg actions must GROW the pool, every action must
   succeed with identical results, and the idle window afterwards must
   DRAIN the pool back to min. The record carries the controller's event
   timeline, the peak size, and the action-failure count (must be 0).
2. ``chaos_scale`` — the scale-down chaos contract: a 3-executor session
   runs a PIPELINED groupagg (AQE off) with a seeded per-map delay, a
   dropped map blob (forcing a lineage-recovery round), and a
   ``pool.drain:crash`` rule that kills the retiring executor MID-DRAIN
   when the bench retires it mid-action. The action must return bytes
   identical to a fault-free fixed-pool BARRIER run, the store must end at
   its pre-action object count, and a flight-recorder bundle written at
   the end must carry the drain/recovery evidence chain
   (``executor_drain`` → ``executor_down`` → ``recovery_round``).

4. ``outofcore`` (``--outofcore``; records ``benchmarks/SPILL.json``) —
   the ROADMAP item 4c headroom proof: a full-row sort shuffle moving
   several× the store's configured shm budget, so the sealed input and map
   blobs MUST spill to disk mid-action and fault back in transparently.
   Asserted: the spill really engaged (``spilled_objects > 0``, measured
   peak bytes a recorded multiple of the budget), the result is
   byte-identical to the same action under a roomy budget, zero failed
   actions, zero orphans.

3. ``fairness`` (``--fairness``; the ``chaos-overload`` CI leg) — the
   multi-tenant overload contract on one fixed 2-executor pool under a
   seeded per-map delay: a FLOODING tenant (a second ``Engine`` over the
   session's pool, tenant="flood") loops wide groupaggs while the
   INTERACTIVE tenant runs a stream of small groupaggs — every
   interactive action must return bytes identical to its uncontended
   baseline with its p99 bounded (never queued behind the flood), zero
   failed accepted actions on either tenant, and a zero-orphan store
   audit; then two SATURATING tenants at weights 3:1 must show a
   per-tenant dispatch split within tolerance of the weight ratio.
   Recorded in ``benchmarks/FAIR.json``.

``--smoke`` shrinks the load, writes to /tmp (never the recorded
artifact), and ASSERTS the CI contract above; the full run records
``benchmarks/SCALE.json`` — or ``benchmarks/FAIR.json`` with
``--fairness`` (override with ``--out``).

Run: RDT_FAULTS_SEED=7 python benchmarks/scale_bench.py [--fairness]
     [--smoke] [--out P]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _ipc_bytes(table):
    import pyarrow as pa
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def _frame(session, rows, parts):
    rng = np.random.RandomState(0)
    pdf = pd.DataFrame({
        "k": rng.randint(0, 50, rows),
        "v": rng.randint(0, 1000, rows).astype(np.int64),
    })
    return session.createDataFrame(pdf, num_partitions=parts)


def _groupagg_bytes(session, df):
    from raydp_tpu.etl import functions as F
    out = df.groupBy("k").agg(F.sum("v").alias("s"), F.count("v").alias("n"))
    return _ipc_bytes(session.engine.collect(out._plan)
                      .sort_by([("k", "ascending")]))


def run_autoscale_config(smoke):
    """Config 1: queued burst grows the pool, idle drains it back."""
    import raydp_tpu

    rows = 8_000 if smoke else 40_000
    parts = 8 if smoke else 16
    burst = 3 if smoke else 4
    os.environ.update({
        "RDT_POOL_SCALE_INTERVAL_S": "0.2",
        "RDT_POOL_SCALE_UP_S": "0.4",
        "RDT_POOL_IDLE_S": "1.5",
        "RDT_POOL_COOLDOWN_S": "1.0",
        "RDT_FAULTS": "executor.run_task:delay:ms=400",
    })
    t0 = time.time()
    s = raydp_tpu.init("scale-bench", num_executors=1, executor_cores=1,
                       executor_memory="512MB")
    try:
        auto = s.autoscale(min_size=1, max_size=3)
        df = _frame(s, rows, parts)
        results, errors = [], []

        def run():
            try:
                results.append(_groupagg_bytes(s, df))
            except Exception as e:  # noqa: BLE001 - counted below
                errors.append(repr(e))

        threads = [threading.Thread(target=run) for _ in range(burst)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        peak = max([1] + [e["size"] for e in auto.events
                          if e["direction"] == "up"])
        burst_wall = time.time() - t0
        deadline = time.time() + 60
        while time.time() < deadline and len(s.executors) > 1:
            time.sleep(0.3)
        final = len(s.executors)
        identical = len(set(results)) <= 1
        record = {
            "burst_actions": burst,
            "failed_actions": len(errors),
            "errors": errors,
            "results_identical": identical,
            "peak_pool_size": peak,
            "final_pool_size": final,
            "grew": peak > 1,
            "shrank_to_min": final == 1,
            "burst_wall_s": round(burst_wall, 2),
            "scale_events": [{"direction": e["direction"], "size": e["size"],
                              "reason": e["reason"]} for e in auto.events],
        }
    finally:
        raydp_tpu.stop()
        for k in ("RDT_POOL_SCALE_INTERVAL_S", "RDT_POOL_SCALE_UP_S",
                  "RDT_POOL_IDLE_S", "RDT_POOL_COOLDOWN_S", "RDT_FAULTS"):
            os.environ.pop(k, None)
    print(f"[autoscale] peak={record['peak_pool_size']} "
          f"final={record['final_pool_size']} "
          f"failed={record['failed_actions']} "
          f"identical={record['results_identical']}")
    return record


def run_chaos_scale_config(smoke):
    """Config 2: drain-crash racing a pipelined groupagg + recovery."""
    import raydp_tpu
    from raydp_tpu import metrics
    from raydp_tpu.runtime.object_store import get_client

    rows = 8_000 if smoke else 40_000

    # fault-free fixed-pool BARRIER baseline
    os.environ["RDT_ETL_AQE"] = "0"
    os.environ["RDT_SHUFFLE_PIPELINE"] = "0"
    s = raydp_tpu.init("scale-chaos-base", num_executors=3,
                       executor_cores=1, executor_memory="512MB")
    try:
        base = _groupagg_bytes(s, _frame(s, rows, 4))
    finally:
        raydp_tpu.stop()

    # chaos run: pipelined, slowed maps, a dropped map blob (recovery),
    # and a drain-crash fired when the bench retires executor -2
    sentinels = [os.path.join(tempfile.gettempdir(),
                              f"rdt_scale_bench_{os.getpid()}_{n}.sentinel")
                 for n in ("crash", "drop")]
    for p in sentinels:
        if os.path.exists(p):
            os.remove(p)
    os.environ["RDT_SHUFFLE_PIPELINE"] = "1"
    os.environ["RDT_FAULTS"] = (
        "executor.run_task:delay:ms=400:match=|mt-;"
        f"shuffle.write:drop:nth=2:once={sentinels[1]};"
        f"pool.drain:crash:once={sentinels[0]}")
    s = raydp_tpu.init("scale-chaos", num_executors=3, executor_cores=1,
                       executor_memory="512MB")
    try:
        metrics.reset()
        client = get_client()
        df = _frame(s, rows, 4)
        before = client.stats()["num_objects"]
        box = {}

        def run():
            try:
                box["bytes"] = _groupagg_bytes(s, df)
            except Exception as e:  # noqa: BLE001 - surfaced below
                box["error"] = repr(e)

        t = threading.Thread(target=run)
        t.start()
        # mid-map-stage: the 400ms per-map delay guarantees the victim has
        # in-flight work when the drain-crash kills it, so the blackbox
        # carries the full executor_drain → executor_down → recovery chain
        time.sleep(0.25)
        s.retire_executor("rdt-executor-scale-chaos-2")
        t.join(timeout=600)
        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.25)
        orphans = client.stats()["num_objects"] - before
        report = s.engine.shuffle_stage_report()
        # the postmortem evidence chain: harvest every process's ring into
        # a blackbox bundle and read the drain/recovery sequence back
        bundle_path = metrics.write_blackbox("chaos-scale")
        with open(bundle_path) as fh:
            bundle = json.load(fh)
        driver_events = [e["kind"]
                         for e in bundle["processes"]["driver"]["events"]]
        record = {
            "failed_action": box.get("error"),
            "byte_identical": box.get("bytes") == base,
            "orphans": orphans,
            "pool_size_after": len(s.executors),
            "pipelined": any(e.get("pipelined") for e in report),
            "recovered": sum(e.get("recovered", 0) for e in report),
            "regenerated": sum(e.get("regenerated", 0) for e in report),
            "crash_fired": os.path.exists(sentinels[0]),
            "drop_fired": os.path.exists(sentinels[1]),
            "blackbox": bundle_path,
            "blackbox_has_drain": "executor_drain" in driver_events,
            "blackbox_has_executor_down": "executor_down" in driver_events,
            "blackbox_has_recovery_round": "recovery_round" in driver_events,
        }
    finally:
        raydp_tpu.stop()
        for k in ("RDT_ETL_AQE", "RDT_SHUFFLE_PIPELINE", "RDT_FAULTS"):
            os.environ.pop(k, None)
        for p in sentinels:
            if os.path.exists(p):
                os.remove(p)
    print(f"[chaos-scale] identical={record['byte_identical']} "
          f"orphans={record['orphans']} recovered={record['recovered']} "
          f"blackbox={os.path.basename(bundle_path)}")
    return record


def run_fairness_config(smoke):
    """Config 3: flood + interactive tenants on one pool, then a weighted
    3:1 saturation split (the ISSUE 14 fairness contract)."""
    import raydp_tpu
    from raydp_tpu.etl.engine import Engine

    rows_wide = 12_000 if smoke else 40_000
    parts_wide = 24 if smoke else 48
    inter_actions = 6 if smoke else 16
    # per-MAP delay (both tenants alike): stretches every map stage so the
    # flood holds a real backlog without inflating data volume
    os.environ["RDT_FAULTS"] = "executor.run_task:delay:ms=120:match=|mt-"
    s = raydp_tpu.init("fair-bench", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        from raydp_tpu.runtime.object_store import get_client
        client = get_client()
        pool = s.engine.pool
        small = _frame(s, 4_000 if smoke else 8_000, 4)
        rng = np.random.RandomState(1)
        wide = s.createDataFrame(pd.DataFrame({
            "k": rng.randint(0, 50, rows_wide),
            "v": rng.randint(0, 1000, rows_wide).astype(np.int64),
        }), num_partitions=parts_wide)
        before = client.stats()["num_objects"]

        # uncontended interactive baseline (bytes + wall)
        t0 = time.time()
        base_small = _groupagg_bytes(s, small)
        uncontended_s = time.time() - t0

        flood_eng = Engine(pool,
                           shuffle_partitions=s.engine.shuffle_partitions,
                           owner=s.engine.owner, tenant="flood")
        from raydp_tpu.etl import functions as F
        out_w = wide.groupBy("k").agg(F.sum("v").alias("s"),
                                      F.count("v").alias("n"))
        stop = threading.Event()
        flood_stats = {"actions": 0, "errors": []}

        def flood():
            while not stop.is_set():
                try:
                    _ipc_bytes(flood_eng.collect(out_w._plan)
                               .sort_by([("k", "ascending")]))
                    flood_stats["actions"] += 1
                except Exception as e:  # noqa: BLE001 - counted below
                    flood_stats["errors"].append(repr(e))
                    return

        tf = threading.Thread(target=flood)
        tf.start()
        deadline = time.time() + 60
        while time.time() < deadline and (pool.load()["tenants"]
                                          .get("flood", {})
                                          .get("queued", 0)) < 4:
            time.sleep(0.02)

        # the interactive stream under the flood
        walls, mismatches = [], 0
        flood_queued_seen = 0
        for _ in range(inter_actions):
            flood_queued_seen = max(
                flood_queued_seen,
                pool.load()["tenants"].get("flood", {}).get("queued", 0))
            t0 = time.time()
            got = _groupagg_bytes(s, small)
            walls.append(time.time() - t0)
            if got != base_small:
                mismatches += 1
        stop.set()
        tf.join(timeout=600)
        walls.sort()
        p50 = walls[len(walls) // 2]
        p99 = walls[min(len(walls) - 1, int(0.99 * len(walls)))]

        # weighted phase: two SATURATING tenants at 3:1, sampled when the
        # heavy one finishes (both still contending throughout its run)
        eng_a = Engine(pool, shuffle_partitions=s.engine.shuffle_partitions,
                       owner=s.engine.owner, tenant="wA", tenant_weight=1.0)
        eng_b = Engine(pool, shuffle_partitions=s.engine.shuffle_partitions,
                       owner=s.engine.owner, tenant="wB", tenant_weight=3.0)
        boxes = {}

        def run_w(tag, eng):
            try:
                boxes[tag] = _ipc_bytes(eng.collect(out_w._plan)
                                        .sort_by([("k", "ascending")]))
            except Exception as e:  # noqa: BLE001 - surfaced below
                boxes[tag + "_error"] = repr(e)

        ta = threading.Thread(target=run_w, args=("wA", eng_a))
        tb = threading.Thread(target=run_w, args=("wB", eng_b))
        ta.start()
        tb.start()
        # the split only means something WHILE both tenants contend (once
        # the heavy action's queue drains, the light one rightly floods the
        # freed slots): keep the last sample with both queues nonempty.
        # Note the per-stage in-flight caps bound the achievable ratio —
        # the heavy tenant can hold at most one stage's cap worth of slots
        # — so "tracks the weights" is a tolerance band, not an equality.
        sample = None
        deadline = time.time() + 600
        while tb.is_alive() and time.time() < deadline:
            t = pool.load()["tenants"]
            a, b = t.get("wA", {}), t.get("wB", {})
            if a.get("queued", 0) > 0 and b.get("queued", 0) > 0 \
                    and a.get("dispatched", 0) >= 4:
                sample = (a["dispatched"], b["dispatched"])
            time.sleep(0.05)
        tb.join(timeout=600)
        ta.join(timeout=600)
        disp_a, disp_b = sample if sample else (0, 0)
        ratio = (disp_b / disp_a) if disp_a else float("inf")

        deadline = time.time() + 30
        while time.time() < deadline \
                and client.stats()["num_objects"] != before:
            time.sleep(0.25)
        record = {
            "interactive_actions": inter_actions,
            "interactive_failed": mismatches,
            "results_identical": mismatches == 0,
            "uncontended_s": round(uncontended_s, 3),
            "contended_p50_s": round(p50, 3),
            "contended_p99_s": round(p99, 3),
            "p99_bounded": p99 < 10.0 * max(uncontended_s, 0.5) + 2.0,
            "flood_actions": flood_stats["actions"],
            "flood_failed": len(flood_stats["errors"]),
            "flood_errors": flood_stats["errors"],
            "flood_queued_seen": flood_queued_seen,
            "weight_ratio": 3.0,
            "observed_dispatch_ratio": round(ratio, 2),
            "ratio_within_tolerance": 1.5 <= ratio <= 6.0,
            "weighted_identical": boxes.get("wA") == boxes.get("wB"),
            "weighted_errors": [boxes[k] for k in boxes if "error" in k],
            "orphans": client.stats()["num_objects"] - before,
        }
    finally:
        raydp_tpu.stop()
        os.environ.pop("RDT_FAULTS", None)
    print(f"[fairness] p99={record['contended_p99_s']}s "
          f"(uncontended {record['uncontended_s']}s) "
          f"ratio={record['observed_dispatch_ratio']} "
          f"failed={record['interactive_failed']} "
          f"orphans={record['orphans']}")
    return record


def run_outofcore_config(smoke):
    """Config 4: sort-shuffle several× the store budget — spill engages,
    results stay byte-identical, nothing fails, nothing orphans."""
    import pandas as _pd

    import raydp_tpu
    from raydp_tpu import config as cfg
    from raydp_tpu.runtime.object_store import get_client

    rows = 60_000 if smoke else 240_000
    budget = (2 << 20) if smoke else (8 << 20)
    rng = np.random.RandomState(0)
    pdf = _pd.DataFrame({
        "k": rng.randint(0, 1_000_000, rows),
        "v": rng.randint(0, 1000, rows).astype(np.int64),
        # a fat payload column so the sort shuffle moves real bytes —
        # ~128 B/row of string data dominates the row's footprint
        "payload": ["x" * 96 + f"{i:032d}" for i in range(rows)],
    })

    def one_run(shm_budget):
        configs = None
        if shm_budget:
            configs = {cfg.OBJECT_STORE_MEMORY_KEY: str(shm_budget),
                       cfg.SPILL_BUDGET_KEY: str(shm_budget)}
            # this config DELIBERATELY oversubscribes the store — disk
            # spill is the mechanism under test, so the PR 14 memory
            # backpressure (which would pause dispatch at 1.25× budget and
            # deadlock an action whose own inputs hold the memory) steps
            # aside for the run
            os.environ["RDT_STORE_HIGH_WATERMARK"] = "1e9"
        s = raydp_tpu.init("spill-bench", num_executors=2, executor_cores=1,
                           executor_memory="512MB", configs=configs)
        try:
            client = get_client()
            df = s.createDataFrame(pdf, num_partitions=8)
            # the audit baseline includes the live input frame (its blocks
            # belong to df for the whole run); the ACTION must add nothing
            before = client.stats()["num_objects"]
            t0 = time.time()
            out = s.engine.collect(df.sort("k")._plan)
            wall = time.time() - t0
            stats = client.stats()
            peak = {
                "spilled_objects": stats.get("spilled_objects", 0),
                "spilled_bytes": stats.get("spilled_bytes", 0),
                "shm_bytes": stats.get("shm_bytes", 0),
            }
            data = _ipc_bytes(out)
            deadline = time.time() + 30
            while time.time() < deadline \
                    and client.stats()["num_objects"] != before:
                time.sleep(0.25)
            orphans = client.stats()["num_objects"] - before
            return data, wall, peak, orphans
        finally:
            raydp_tpu.stop()
            os.environ.pop("RDT_STORE_HIGH_WATERMARK", None)

    base, base_wall, _, orphans0 = one_run(None)  # roomy default budget
    got, wall, peak, orphans1 = one_run(budget)
    moved = peak["spilled_bytes"] + peak["shm_bytes"]
    record = {
        "rows": rows,
        "budget_bytes": budget,
        "result_bytes": len(base),
        "byte_identical": base == got,
        "spilled_objects": peak["spilled_objects"],
        "spilled_bytes": peak["spilled_bytes"],
        "store_bytes_over_budget": round(moved / budget, 2),
        "spill_engaged": peak["spilled_objects"] > 0,
        "wall_s": round(wall, 2),
        "incore_wall_s": round(base_wall, 2),
        "failed_actions": 0,  # one_run raises (and the bench fails) on any
        "orphans_incore": orphans0,
        "orphans_spill": orphans1,
    }
    print(f"[outofcore] spilled={record['spilled_objects']} objs "
          f"({record['store_bytes_over_budget']}x budget) "
          f"identical={record['byte_identical']} "
          f"wall={record['wall_s']}s (incore {record['incore_wall_s']}s) "
          f"orphans={record['orphans_spill']}")
    return record


def _assert_outofcore(rec):
    assert rec["byte_identical"], rec
    assert rec["spill_engaged"], rec
    assert rec["store_bytes_over_budget"] >= 2.0, rec
    assert rec["failed_actions"] == 0, rec
    assert rec["orphans_incore"] == 0 and rec["orphans_spill"] == 0, rec


def _assert_fairness(fair):
    assert fair["interactive_failed"] == 0, fair
    assert fair["results_identical"], fair
    assert fair["flood_failed"] == 0, fair
    assert fair["flood_queued_seen"] > 0, fair  # the flood really contended
    assert fair["p99_bounded"], fair
    assert fair["ratio_within_tolerance"], fair
    assert fair["weighted_identical"], fair
    assert not fair["weighted_errors"], fair
    assert fair["orphans"] == 0, fair


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI contract: small load, asserts, writes to /tmp")
    ap.add_argument("--fairness", action="store_true",
                    help="run ONLY the multi-tenant fairness config "
                         "(records benchmarks/FAIR.json)")
    ap.add_argument("--outofcore", action="store_true",
                    help="run ONLY the out-of-core headroom config "
                         "(records benchmarks/SPILL.json)")
    ap.add_argument("--out", default=None, help="record path override")
    args = ap.parse_args()
    here = os.path.dirname(os.path.abspath(__file__))
    if args.outofcore:
        out = args.out or ("/tmp/SPILL_SMOKE.json" if args.smoke
                           else os.path.join(here, "SPILL.json"))
        ooc = run_outofcore_config(args.smoke)
        record = {
            "bench": "scale_bench",
            # headline + PERF_CLAIMS handle (tests/test_perf_claims)
            "metric": "outofcore_store_bytes_over_budget",
            "value": ooc["store_bytes_over_budget"],
            "smoke": args.smoke,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "configs": {"outofcore": ooc},
        }
        with open(out, "w") as fh:
            json.dump(record, fh, indent=2, sort_keys=True)
        print(f"record written to {out}")
        _assert_outofcore(record["configs"]["outofcore"])
        print("outofcore bench contract: OK")
        return
    if args.fairness:
        out = args.out or ("/tmp/FAIR_SMOKE.json" if args.smoke
                           else os.path.join(here, "FAIR.json"))
        record = {
            "bench": "scale_bench",
            "smoke": args.smoke,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "configs": {"fairness": run_fairness_config(args.smoke)},
        }
        with open(out, "w") as fh:
            json.dump(record, fh, indent=2, sort_keys=True)
        print(f"record written to {out}")
        _assert_fairness(record["configs"]["fairness"])
        print("fairness bench contract: OK")
        return
    out = args.out or ("/tmp/SCALE_SMOKE.json" if args.smoke else
                       os.path.join(here, "SCALE.json"))
    record = {
        "bench": "scale_bench",
        "smoke": args.smoke,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "configs": {
            "autoscale": run_autoscale_config(args.smoke),
            "chaos_scale": run_chaos_scale_config(args.smoke),
        },
    }
    with open(out, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(f"record written to {out}")

    auto = record["configs"]["autoscale"]
    chaos = record["configs"]["chaos_scale"]
    # the contract holds for the recorded artifact too, not just CI
    assert auto["failed_actions"] == 0, auto
    assert auto["results_identical"], auto
    assert auto["grew"] and auto["shrank_to_min"], auto
    assert chaos["failed_action"] is None, chaos
    assert chaos["byte_identical"], chaos
    assert chaos["orphans"] == 0, chaos
    assert chaos["pipelined"], chaos
    assert chaos["crash_fired"] and chaos["drop_fired"], chaos
    assert chaos["recovered"] >= 1, chaos
    assert chaos["blackbox_has_drain"], chaos
    assert chaos["blackbox_has_executor_down"], chaos
    assert chaos["blackbox_has_recovery_round"], chaos
    print("scale bench contract: OK")


if __name__ == "__main__":
    main()
