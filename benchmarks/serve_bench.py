"""Serving-plane bench: train→serve end-to-end, p50/p99 under open-loop
load, hedged vs unhedged tail latency (ISSUE 11 acceptance).

One full pass per mode (hedging off, then on):

1. a fresh 2-executor session trains a small flax MLP on the ETL plane
   (``fit_on_frame`` — the same train half the examples use) and exports a
   servable; the first mode's export is reused by the second (one train),
2. a ``ServingSession`` loads it onto two executor-resident replicas, with
   replica ``serve-r0`` turned into a seeded **straggler**: an
   ``RDT_FAULTS`` rule delays every 3rd batch entering its worker thread
   (``serve.predict:delay:every=3:match=|serve-r0`` — the serving twin of
   the straggler/AQE legs' seeded-delay methodology),
3. an **open-loop** load: arrivals on a fixed schedule (a timer thread,
   independent of completions — so a stalled replica inflates latency, not
   the offered load), small row batches so micro-batching has something to
   coalesce,
4. per-request p50/p99 from ``serving_report()``, plus batching occupancy,
   hedge accounting, and a zero-dropped-requests audit; the two modes'
   prediction sets are compared for identity (same rows in, same bits out,
   hedged or not).

The record lands in ``benchmarks/SERVE.json`` (override ``RDT_SERVE_PATH``;
``--smoke`` shrinks the load and writes to /tmp so a CI run cannot clobber
the recorded artifact). ``--smoke`` also ASSERTS the CI contract: batching
occurred, zero dropped requests, and results identical across modes.

``--rollout`` runs the ISSUE 18 guarded-rollout record instead
(``benchmarks/ROLLOUT.json``): a clean
canary PROMOTES under open-loop load, a canary with a seeded
``serve.predict:delay`` latency regression AUTO-ROLLS-BACK (both with zero
dropped requests), and an overload burst against a throughput-capped plane
sheds with static capacity but not with the ``ServingAutoscaler`` on.
``--rollout --smoke`` asserts that contract (the CI rollout-smoke leg) and
writes to /tmp.

Run: python benchmarks/serve_bench.py [--smoke] [--rollout]
"""

import json
import os
import shutil
import sys
import threading
import time

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _train_and_export(session, export_dir, rows):
    import optax

    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator

    rng = np.random.RandomState(7)
    x = rng.random_sample((rows, 2))
    y = x @ np.array([2.0, -3.0]) + 1.0
    pdf = pd.DataFrame({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    df = session.createDataFrame(pdf, num_partitions=2)
    est = FlaxEstimator(
        model=MLP(features=(16,), use_batch_norm=False),
        optimizer=optax.adam(1e-2), loss="mse",
        feature_columns=["x1", "x2"], label_column="y",
        batch_size=128, num_epochs=1)
    result = est.fit_on_frame(df)
    est.export_serving(export_dir)
    return result


#: arrivals per burst in the open-loop schedule (mean rate is unchanged)
_BURST = 4


def _open_loop(srv, xs, interval_s):
    """Issue one predict_async per row batch on a fixed arrival schedule;
    returns (ordered predictions, per-request latencies ms, dropped count).
    Arrivals never wait on completions — the open-loop contract — and each
    latency is stamped by the future's completion callback, so the
    measurement window is exactly the measured load (no warmup pollution)."""
    n = len(xs)
    futs = [None] * n
    lats = [None] * n

    def _stamp(i, t_issue):
        def cb(_f):
            lats[i] = (time.perf_counter() - t_issue) * 1000.0
        return cb

    t0 = time.perf_counter()
    for i, rows in enumerate(xs):
        # bursty arrivals: BURST requests land together every
        # BURST×interval (same mean rate as a smooth schedule) — the
        # concurrent-client regime micro-batching exists for; a perfectly
        # paced trickle would never leave two requests to coalesce
        due = t0 + (i // _BURST) * _BURST * interval_s
        delay = due - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        t = time.perf_counter()
        try:
            futs[i] = srv.predict_async(rows)
        except Exception:  # noqa: BLE001 - shed at admission: audited drop
            continue
        futs[i].add_done_callback(_stamp(i, t))
    preds, dropped = [], 0
    for f in futs:
        if f is None:
            dropped += 1
            preds.append(None)
            continue
        try:
            preds.append(np.asarray(f.result(timeout=120.0)))
        except Exception:  # noqa: BLE001 - a drop is the audited failure
            dropped += 1
            preds.append(None)
    return preds, [x for x in lats if x is not None], dropped


def run_serve_config(smoke):
    import raydp_tpu
    from raydp_tpu.serve import ServingSession

    n_req = 120 if smoke else 400
    interval_ms = 10.0
    delay_ms = 150 if smoke else 250
    rows_per_req = 2
    train_rows = 2000 if smoke else 20000
    export_dir = os.path.join("/tmp", f"rdt_serve_bench_{os.getpid()}")
    out = {"requests": n_req, "interval_ms": interval_ms,
           "straggler_delay_ms": delay_ms, "rows_per_request": rows_per_req,
           "train_rows": train_rows}

    rng = np.random.RandomState(3)
    x = rng.random_sample((n_req * rows_per_req, 2))
    xs = [{"x1": x[i * rows_per_req:(i + 1) * rows_per_req, 0],
           "x2": x[i * rows_per_req:(i + 1) * rows_per_req, 1]}
          for i in range(n_req)]

    preds_by_mode = {}
    for mode, hedge in (("off", "0"), ("on", "1")):
        app = f"serve_bench_{mode}"
        # the straggler rule must be in the env BEFORE the session spawns
        # its executors (they inherit it); every 3rd batch entering replica
        # serve-r0's worker stalls — an intermittent straggler, the regime
        # hedging targets (a uniformly slow replica would poison the
        # latency quantile the hedge deadline derives from)
        os.environ["RDT_FAULTS"] = (
            f"serve.predict:delay:ms={delay_ms}:every=3:match=|serve-r0")
        os.environ["RDT_SERVE_HEDGE"] = hedge
        os.environ["RDT_SERVE_HEDGE_QUANTILE"] = "0.5"
        os.environ["RDT_SERVE_HEDGE_MULTIPLIER"] = "3.0"
        os.environ["RDT_SERVE_HEDGE_MIN_MS"] = "20"
        os.environ["RDT_SERVE_BATCH_TIMEOUT_MS"] = "5"
        session = raydp_tpu.init(app, num_executors=2, executor_cores=1,
                                 executor_memory="1GB")
        try:
            if not os.path.exists(
                    os.path.join(export_dir, "servable.json")):
                t0 = time.perf_counter()
                _train_and_export(session, export_dir, train_rows)
                out["train_export_s"] = round(time.perf_counter() - t0, 2)
            srv = ServingSession(export_dir, session=session, name="serve")
            try:
                # warmup: jit compile + latency window, not measured
                for i in range(12):
                    srv.predict(xs[i % len(xs)], timeout=60.0)
                t0 = time.perf_counter()
                preds, lats, dropped = _open_loop(srv, xs,
                                                  interval_ms / 1000.0)
                out[f"wall_{mode}_s"] = round(time.perf_counter() - t0, 3)
                rep = srv.serving_report()
                out[f"p50_{mode}_ms"] = round(float(
                    np.percentile(lats, 50)), 3)
                out[f"p99_{mode}_ms"] = round(float(
                    np.percentile(lats, 99)), 3)
                out[f"batches_{mode}"] = rep["batches"]
                out[f"requests_{mode}"] = rep["requests"]
                out[f"occupancy_{mode}"] = rep["mean_batch_occupancy"]
                out[f"hedged_{mode}"] = rep["hedged"]
                out[f"hedge_won_{mode}"] = rep["hedge_won"]
                out[f"rerouted_{mode}"] = rep["rerouted"]
                out[f"dropped_{mode}"] = dropped + rep["failed"]
                preds_by_mode[mode] = preds
            finally:
                srv.close()
        finally:
            raydp_tpu.stop()
            for k in ("RDT_FAULTS", "RDT_SERVE_HEDGE",
                      "RDT_SERVE_HEDGE_QUANTILE",
                      "RDT_SERVE_HEDGE_MULTIPLIER",
                      "RDT_SERVE_HEDGE_MIN_MS",
                      "RDT_SERVE_BATCH_TIMEOUT_MS"):
                os.environ.pop(k, None)
    out["p99_ratio"] = round(
        out["p99_off_ms"] / max(out["p99_on_ms"], 1e-9), 2)
    out["identical"] = all(
        a is not None and b is not None and np.array_equal(a, b)
        for a, b in zip(preds_by_mode["off"], preds_by_mode["on"]))
    return out


# ==== guarded rollouts + serving autoscale (ISSUE 18, --rollout) =============

def _rollout_export_dirs():
    """One train per bench process: every --rollout config shares the same
    /tmp export (and its byte-identical canary copy)."""
    base = os.path.join("/tmp", f"rdt_rollout_bench_{os.getpid()}")
    return base, base + "-canary"


def run_rollout_config(smoke, inject):
    """One guarded rollout under open-loop load. ``inject=False`` is the
    clean path: the canary is the SAME bundle copied to a second export
    dir, so it must ramp healthy and PROMOTE. ``inject=True`` pins a
    seeded ``serve.predict:delay`` to the canary replica ids alone
    (``match=-v2-`` — the canary group's rid infix): a pure latency
    regression with zero errors, which only the judgment's p99 arm can
    catch — it must ROLL BACK. Either way the audited contract is zero
    dropped requests: a guarded deploy may not cost traffic."""
    import raydp_tpu
    from raydp_tpu.serve import ServingSession

    n_req = 240 if smoke else 800
    interval_ms = 10.0
    # the injected canary stall must dwarf the open-loop baseline p99
    # (coalesced batches on a loaded CI host reach ~100ms+), or the 2x
    # judgment bar turns the rollback leg into a coin flip
    delay_ms = 400 if smoke else 500
    rows_per_req = 2
    train_rows = 2000 if smoke else 20000
    base_dir, canary_dir = _rollout_export_dirs()
    out = {"requests": n_req, "interval_ms": interval_ms,
           "rows_per_request": rows_per_req,
           "canary_delay_ms": delay_ms if inject else 0}

    rng = np.random.RandomState(3)
    x = rng.random_sample((n_req * rows_per_req, 2))
    xs = [{"x1": x[i * rows_per_req:(i + 1) * rows_per_req, 0],
           "x2": x[i * rows_per_req:(i + 1) * rows_per_req, 1]}
          for i in range(n_req)]

    mode = "regress" if inject else "clean"
    if inject:
        # env set BEFORE init: the executors inherit the schedule; it only
        # matches once the canary group (v2 rids) exists
        os.environ["RDT_FAULTS"] = (
            f"serve.predict:delay:ms={delay_ms}:match=-v2-")
    os.environ["RDT_SERVE_HEDGE"] = "0"
    os.environ["RDT_SERVE_BATCH_TIMEOUT_MS"] = "5"
    session = raydp_tpu.init(f"rollout_bench_{mode}", num_executors=2,
                             executor_cores=1, executor_memory="1GB")
    try:
        if not os.path.exists(os.path.join(base_dir, "servable.json")):
            t0 = time.perf_counter()
            _train_and_export(session, base_dir, train_rows)
            out["train_export_s"] = round(time.perf_counter() - t0, 2)
        if not os.path.exists(os.path.join(canary_dir, "servable.json")):
            shutil.copytree(base_dir, canary_dir, dirs_exist_ok=True)
        srv = ServingSession(base_dir, session=session, name="roll")
        try:
            # warmup: jit compile + latency window, not measured
            for i in range(12):
                srv.predict(xs[i % len(xs)], timeout=60.0)
            res = {}

            def _load():
                res["preds"], res["lats"], res["dropped"] = _open_loop(
                    srv, xs, interval_ms / 1000.0)

            t0 = time.perf_counter()
            loader = threading.Thread(target=_load)
            loader.start()
            outcome = srv.rollout(
                canary_dir, tag="bench", initial_weight=0.5,
                steps=[0.5, 1.0], step_s=5.0 if smoke else 15.0,
                min_samples=8, p99_factor=2.0, timeout=120.0)
            loader.join(timeout=240.0)
            assert not loader.is_alive(), "open-loop load hung"
            out["wall_s"] = round(time.perf_counter() - t0, 3)
            rep = srv.serving_report()
            out["outcome"] = outcome["outcome"]
            out["reason"] = outcome.get("reason")
            out["judgments"] = len(outcome["steps"])
            out["p50_ms"] = round(float(np.percentile(res["lats"], 50)), 3)
            out["p99_ms"] = round(float(np.percentile(res["lats"], 99)), 3)
            out["dropped"] = res["dropped"] + rep["failed"]
            out["final_version"] = rep["servable"]["version"]
        finally:
            srv.close()
    finally:
        raydp_tpu.stop()
        for k in ("RDT_FAULTS", "RDT_SERVE_HEDGE",
                  "RDT_SERVE_BATCH_TIMEOUT_MS"):
            os.environ.pop(k, None)
    return out


def run_burst_config(smoke, autoscaled):
    """An overload burst against a throughput-capped serving plane: a
    seeded 40ms delay on EVERY predict batch models a heavy servable, and
    a small max batch pins per-replica throughput below the offered load
    (2 rows/req ÷ 4-row batches ÷ 40ms ≈ 50 req/s per replica vs ~143
    req/s offered). Static capacity (2 replicas) must shed at the bounded
    queue; the SAME burst with the autoscaler on grows replicas ahead of
    the backlog and absorbs it — the shed==0 vs shed>0 split ROLLOUT.json
    records."""
    import raydp_tpu
    from raydp_tpu.serve import ServingSession

    n_req = 400 if smoke else 1200
    interval_ms = 7.0
    delay_ms = 40
    rows_per_req = 2
    train_rows = 2000 if smoke else 20000
    base_dir, _ = _rollout_export_dirs()
    out = {"requests": n_req, "interval_ms": interval_ms,
           "rows_per_request": rows_per_req, "predict_delay_ms": delay_ms,
           "max_queue": 64, "autoscaled": autoscaled}

    rng = np.random.RandomState(5)
    x = rng.random_sample((n_req * rows_per_req, 2))
    xs = [{"x1": x[i * rows_per_req:(i + 1) * rows_per_req, 0],
           "x2": x[i * rows_per_req:(i + 1) * rows_per_req, 1]}
          for i in range(n_req)]

    os.environ["RDT_FAULTS"] = f"serve.predict:delay:ms={delay_ms}"
    os.environ["RDT_SERVE_HEDGE"] = "0"
    os.environ["RDT_SERVE_BATCH_TIMEOUT_MS"] = "5"
    os.environ["RDT_SERVE_MAX_BATCH"] = "4"
    os.environ["RDT_SERVE_MAX_QUEUE"] = "64"
    if autoscaled:
        os.environ["RDT_SERVE_MIN_REPLICAS"] = "1"
        os.environ["RDT_SERVE_MAX_REPLICAS"] = "4"
        os.environ["RDT_SERVE_SCALE_INTERVAL_S"] = "0.1"
        os.environ["RDT_SERVE_SCALE_UP_S"] = "0.2"
        os.environ["RDT_SERVE_SCALE_COOLDOWN_S"] = "0.2"
    mode = "auto" if autoscaled else "static"
    session = raydp_tpu.init(f"burst_bench_{mode}", num_executors=2,
                             executor_cores=1, executor_memory="1GB")
    scaler = None
    try:
        if not os.path.exists(os.path.join(base_dir, "servable.json")):
            t0 = time.perf_counter()
            _train_and_export(session, base_dir, train_rows)
            out["train_export_s"] = round(time.perf_counter() - t0, 2)
        srv = ServingSession(base_dir, session=session, name="burst")
        try:
            for i in range(12):
                srv.predict(xs[i % len(xs)], timeout=60.0)
            if autoscaled:
                scaler = srv.autoscale()
            t0 = time.perf_counter()
            preds, lats, dropped = _open_loop(srv, xs,
                                              interval_ms / 1000.0)
            out["wall_s"] = round(time.perf_counter() - t0, 3)
            rep = srv.serving_report()
            out["shed"] = rep["shed"]
            out["dropped"] = dropped
            out["completed"] = sum(p is not None for p in preds)
            out["p50_ms"] = round(float(np.percentile(lats, 50)), 3)
            out["p99_ms"] = round(float(np.percentile(lats, 99)), 3)
            out["final_replicas"] = len(rep["replicas"])
            if scaler is not None:
                out["scale_events"] = len(scaler.events)
        finally:
            if scaler is not None:
                scaler.stop()
            srv.close()
    finally:
        raydp_tpu.stop()
        for k in ("RDT_FAULTS", "RDT_SERVE_HEDGE",
                  "RDT_SERVE_BATCH_TIMEOUT_MS", "RDT_SERVE_MAX_BATCH",
                  "RDT_SERVE_MAX_QUEUE", "RDT_SERVE_MIN_REPLICAS",
                  "RDT_SERVE_MAX_REPLICAS", "RDT_SERVE_SCALE_INTERVAL_S",
                  "RDT_SERVE_SCALE_UP_S", "RDT_SERVE_SCALE_COOLDOWN_S"):
            os.environ.pop(k, None)
    return out


def main_rollout(smoke):
    """The --rollout record (benchmarks/ROLLOUT.json): a clean canary
    promotes, an injected latency regression auto-rolls-back, and an
    overload burst sheds statically but not autoscaled — all with zero
    dropped requests on the guarded paths. --smoke asserts exactly that
    contract (the CI rollout-smoke leg)."""
    out_path = ("/tmp/ROLLOUT_SMOKE.json" if smoke else
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "ROLLOUT.json"))
    promote = run_rollout_config(smoke, inject=False)
    rollback = run_rollout_config(smoke, inject=True)
    static = run_burst_config(smoke, autoscaled=False)
    auto = run_burst_config(smoke, autoscaled=True)
    record = {
        "metric": "guarded_rollout_and_serving_autoscale",
        "unit": "rollout outcomes under open-loop load; shed requests "
                "static vs autoscaled under an overload burst",
        "smoke": smoke,
        "configs": {"promote": promote, "rollback": rollback,
                    "burst_static": static, "burst_autoscaled": auto},
        "value": static["shed"] - auto["shed"],
    }
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(json.dumps({k: v for k, v in record.items() if k != "configs"}))
    print(f"rollout: clean={promote['outcome']} "
          f"({promote['judgments']} judgments, "
          f"dropped {promote['dropped']}), "
          f"regressed={rollback['outcome']} "
          f"(reason={rollback['reason']!r}, dropped {rollback['dropped']}); "
          f"burst: static shed {static['shed']} "
          f"({static['final_replicas']} replicas) vs autoscaled shed "
          f"{auto['shed']} ({auto['final_replicas']} replicas, "
          f"p99 {static['p99_ms']}ms -> {auto['p99_ms']}ms)")
    if smoke:
        # the CI rollout-smoke contract
        assert promote["outcome"] == "promoted", promote
        assert promote["dropped"] == 0, promote
        assert promote["final_version"] == 2, promote
        assert rollback["outcome"] == "rolled_back", rollback
        assert "p99" in (rollback.get("reason") or ""), rollback
        assert rollback["dropped"] == 0, rollback
        assert rollback["final_version"] == 1, rollback
        assert static["shed"] > 0, static
        assert auto["shed"] == 0, auto
        assert auto["final_replicas"] > static["final_replicas"], \
            (static, auto)
    return record


def main():
    smoke = "--smoke" in sys.argv
    if "--rollout" in sys.argv:
        return main_rollout(smoke)
    default_path = ("/tmp/SERVE_SMOKE.json" if smoke else
                    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "SERVE.json"))
    # rdtlint: allow[knob-registry] bench output-path plumbing, not a runtime knob
    out_path = os.environ.get("RDT_SERVE_PATH", default_path)
    record = {
        "metric": "serving_tail_latency_hedging",
        "unit": "p99_off/p99_on under a seeded intermittent straggler "
                "replica, open-loop load",
        "smoke": smoke,
        "configs": {"serve": run_serve_config(smoke)},
    }
    cfg = record["configs"]["serve"]
    record["value"] = cfg["p99_ratio"]
    record["all_identical"] = cfg["identical"]
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(json.dumps({k: v for k, v in record.items() if k != "configs"}))
    print(f"serve: p99 {cfg['p99_off_ms']}ms -> {cfg['p99_on_ms']}ms "
          f"({cfg['p99_ratio']}x), p50 {cfg['p50_off_ms']}ms -> "
          f"{cfg['p50_on_ms']}ms, batches {cfg['batches_on']} for "
          f"{cfg['requests_on']} requests (occupancy "
          f"{cfg['occupancy_on']}), hedged {cfg['hedged_on']} "
          f"(won {cfg['hedge_won_on']}), dropped "
          f"{cfg['dropped_off']}+{cfg['dropped_on']}, "
          f"identical={cfg['identical']}")
    if smoke:
        # the CI serve-smoke contract: micro-batching actually coalesced,
        # nothing was dropped in either mode, and hedging engaged
        assert cfg["batches_on"] < cfg["requests_on"], \
            "no batching occurred"
        assert cfg["dropped_off"] == 0 and cfg["dropped_on"] == 0, \
            "dropped requests"
        assert cfg["identical"], "hedged results diverged"
        assert cfg["hedged_on"] >= 1, "hedging never engaged"
    return record


if __name__ == "__main__":
    main()
