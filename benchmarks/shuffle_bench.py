"""ETL shuffle-byte minimization: optimizer on vs off, bytes moved + wall.

The logical-plan optimizer (raydp_tpu/etl/optimizer.py) plus map-side partial
aggregation turn the wide-operator path from "move everything, then compute"
into "compute partials, move only what's needed". This bench runs groupby and
join configs at two key cardinalities over a deliberately wide frame (key +
8 payload columns, only 2 referenced), with `RDT_ETL_OPTIMIZER` off and on,
and records per-config:

- ``bytes_naive`` / ``bytes_opt`` — shuffled bytes from the engine's
  per-stage shuffle ledger (``Engine.shuffle_stage_report()``; the counters
  are serialized object-store payload sizes, not buffer-view estimates),
- ``rows_naive`` / ``rows_opt`` — rows crossing the shuffle,
- ``reduction_x`` — bytes_naive / bytes_opt,
- ``wall_naive_s`` / ``wall_opt_s``,
- ``identical`` — the two paths' results compared row-for-row after a
  canonical sort (integer payloads, so aggregates are exact).

A second leg measures the CONTROL plane: the ``repartition_many`` config
shuffles a many-partition frame (64 maps x 64 buckets of small rows; the
small-object regime where per-object fixed costs dominate) with
``RDT_SHUFFLE_CONSOLIDATE`` off and on, recording per mode:

- ``store_rpcs_*`` — store table/payload control operations from the head
  server's op counters (a ``seal_batch``/``lookup_batch`` counts ONE op),
- ``wall_*_s`` and ``bytes_*``,
- ``rpc_reduction_x`` — store_rpcs_naive / store_rpcs_consolidated,
- ``identical`` — results row-for-row equal after a canonical sort.

A third leg measures the STRAGGLER path (``--straggler``): one executor of
two is turned into a seeded straggler (``RDT_FAULTS`` delays every task
entering it at ``executor.run_task``), and the same shuffle action runs
with ``RDT_SPECULATION=0`` then ``=1``, recording per mode:

- ``wall_off_s`` / ``wall_on_s`` — action wall with backups off/on,
- ``speculated_on`` / ``speculation_won_on`` — from the engine's stage
  report (0 on the off leg by construction),
- ``speedup_x`` — wall_off / wall_on,
- ``identical`` — results row-for-row equal after a canonical sort,
- ``orphans_on`` — store objects left over after the speculation-on action
  settles (won/lost races must free every loser blob: the audit polls the
  store count back to its pre-action value and records the residue).

The straggler record lands in ``benchmarks/STRAGGLER.json`` (override:
``RDT_STRAGGLER_PATH``; ``--smoke`` → /tmp/STRAGGLER_SMOKE.json); the
recorded full-size run measured 9.3× faster stage wall with speculation on.

A fourth leg measures ADAPTIVE EXECUTION (``--aqe`` → ``benchmarks/
AQE.json``, override ``RDT_AQE_PATH``), each rule off vs on:

- ``broadcast_join`` — the join config's shuffled/broadcast bytes when the
  small dim side replicates instead of hash-shuffling both sides,
- ``skew_groupby`` — stage wall on a seeded hot-key groupby (one key ~50%
  of rows) under a seeded ``shuffle.fetch`` per-MB delay (the slow-data-
  plane analogue of the straggler leg's seeded delay), split vs not,
- ``coalesce_many`` — reduce-task dispatch count on the 64×64 config when
  kilobyte buckets fuse into multi-range reads.

A fifth leg measures the PIPELINED shuffle (``--pipeline`` →
``benchmarks/PIPELINE.json``, override ``RDT_PIPELINE_PATH``): the same
16-map shuffle under a seeded per-map ``executor.run_task:delay`` spread
(every 2nd map task entering an executor sleeps — a real map tail on this
1-core host; the ``mt-`` map-task id prefix pins the rule to the map side)
plus a seeded per-MiB ``shuffle.fetch`` delay (the honest-data-plane
methodology of the AQE skew leg), with ``RDT_SHUFFLE_PIPELINE`` off then
on, recording per mode:

- ``wall_barrier_s`` / ``wall_pipelined_s`` — stage wall (reduce side
  dispatched after the barrier vs concurrently with the maps),
- ``overlap_s`` — time reducers spent fetching/decoding BEFORE the last
  map sealed (0 structurally in barrier mode),
- ``first_reduce_fetch_s`` — first reduce-side fetch relative to map-stage
  start,
- ``speedup_x`` — wall_barrier / wall_pipelined,
- ``identical`` — results row-for-row equal after a canonical sort,
- ``orphans_pipelined`` — store objects left after the pipelined action
  settles (the abort/no-orphan audit with reducers mid-stream).

The byte/RPC record lands in ``benchmarks/SHUFFLE_BYTES.json`` (override:
``RDT_SHUFFLE_BYTES_PATH``). ``--smoke`` shrinks the data to seconds of
wall and writes to /tmp by default so a CI smoke run cannot clobber the
recorded artifact. The optimizer/consolidate/straggler/aqe legs pin
``RDT_ETL_AQE=0`` and/or ``RDT_SHUFFLE_PIPELINE=0`` as needed so each leg
measures exactly one mechanism.

Run: python benchmarks/shuffle_bench.py [--smoke] [--straggler] [--aqe]
     [--pipeline]
"""

import json
import os
import sys
import time

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_frame(session, rows: int, cardinality: int, num_partitions: int):
    rng = np.random.RandomState(7)
    pdf = pd.DataFrame({"k": rng.randint(0, cardinality, rows)})
    # wide payload: 8 int64 columns, of which the queries touch only 2 —
    # projection pruning should drop the other 6 before any shuffle
    for i in range(8):
        pdf[f"c{i}"] = rng.randint(0, 1_000_000, rows)
    return session.createDataFrame(pdf, num_partitions=num_partitions)


def run_config(session, action, sort_keys):
    """Run ``action`` with the optimizer off then on; return the record.
    AQE is pinned OFF here: this leg measures the PR-2 plan optimizer, and
    an adaptive broadcast/coalesce would confound the comparison (the
    ``--aqe`` leg measures those on their own terms)."""
    from raydp_tpu.etl import optimizer

    out = {}
    tables = {}
    os.environ["RDT_ETL_AQE"] = "0"
    # pipeline off too: with AQE off the shuffles would stream, and the
    # background map stage would confound the naive-vs-opt walls
    os.environ["RDT_SHUFFLE_PIPELINE"] = "0"
    for mode, env in (("naive", "0"), ("opt", "1")):
        os.environ["RDT_ETL_OPTIMIZER"] = env
        assert optimizer.enabled() == (env == "1")
        session.engine.reset_shuffle_stage_report()
        t0 = time.perf_counter()
        table = action()
        wall = time.perf_counter() - t0
        report = session.engine.shuffle_stage_report()
        out[f"bytes_{mode}"] = sum(r["bytes_shuffled"] for r in report)
        out[f"rows_{mode}"] = sum(r["rows_shuffled"] for r in report)
        out[f"wall_{mode}_s"] = round(wall, 4)
        tables[mode] = table.sort_by([(k, "ascending") for k in sort_keys])
    os.environ.pop("RDT_ETL_AQE", None)
    os.environ.pop("RDT_SHUFFLE_PIPELINE", None)
    out["reduction_x"] = round(out["bytes_naive"] / max(out["bytes_opt"], 1), 2)
    out["identical"] = tables["naive"].equals(tables["opt"])
    out["stages_opt"] = [r["stage"] for r in
                         session.engine.shuffle_stage_report()]
    return out


#: store control-plane ops that make up the "store RPCs" number (op names
#: from ObjectStoreServer.op_counts(); batch calls count one op each)
STORE_OPS = ("seal", "seal_batch", "lookup", "lookup_batch", "free",
             "locations", "contains", "fetch_ranges", "fetch_payload",
             "store_payload")


def run_consolidate_config(session, rows, maps, buckets):
    """The many-partition shuffle (M maps x B buckets, small rows) with the
    consolidated fast path off then on; returns the record."""
    from raydp_tpu.runtime import get_runtime

    rng = np.random.RandomState(11)
    pdf = pd.DataFrame({"k": rng.randint(0, 1_000_000, rows),
                        "v": rng.randint(0, 1_000_000, rows)})
    df = session.createDataFrame(pdf, num_partitions=maps)
    server = get_runtime().store_server
    out = {"maps": maps, "buckets": buckets, "rows": rows}
    tables = {}
    # AQE off: the leg compares per-bucket vs consolidated CONTROL traffic
    # at a fixed 64-reduce fan-in; coalescing would collapse the reduce side.
    # Pipeline off: it engages only WITH consolidation, which would skew the
    # naive-vs-consolidated wall comparison (the --pipeline leg measures it)
    os.environ["RDT_ETL_AQE"] = "0"
    os.environ["RDT_SHUFFLE_PIPELINE"] = "0"
    for mode, env in (("naive", "0"), ("consolidated", "1")):
        os.environ["RDT_SHUFFLE_CONSOLIDATE"] = env
        session.engine.reset_shuffle_stage_report()
        server.reset_op_counts()
        t0 = time.perf_counter()
        table = df.repartition(buckets).to_arrow()
        out[f"wall_{mode}_s"] = round(time.perf_counter() - t0, 4)
        ops = server.op_counts()
        out[f"store_rpcs_{mode}"] = sum(ops.get(k, 0) for k in STORE_OPS)
        report = session.engine.shuffle_stage_report()
        out[f"bytes_{mode}"] = sum(r["bytes_shuffled"] for r in report)
        out[f"stage_meta_rpcs_{mode}"] = sum(r["meta_rpcs"] for r in report)
        tables[mode] = table.sort_by([("k", "ascending"),
                                      ("v", "ascending")])
    os.environ.pop("RDT_SHUFFLE_CONSOLIDATE", None)
    os.environ.pop("RDT_ETL_AQE", None)
    os.environ.pop("RDT_SHUFFLE_PIPELINE", None)
    out["rpc_reduction_x"] = round(
        out["store_rpcs_naive"] / max(out["store_rpcs_consolidated"], 1), 2)
    out["identical"] = tables["naive"].equals(tables["consolidated"])
    return out


def run_straggler_config(smoke):
    """One executor of two is a seeded straggler (every task entering it is
    delayed at ``executor.run_task``); the same shuffle action runs with
    speculation off then on. The fault spec must be in the env BEFORE the
    session spawns its executors (actors inherit it), and the victim's
    actor name is deterministic: ``rdt-executor-<app>-0``."""
    import raydp_tpu
    from raydp_tpu.runtime.object_store import get_client

    delay_ms = 500 if smoke else 1500
    maps = 16
    rows = maps * (200 if smoke else 2000)
    buckets = 8
    out = {"maps": maps, "buckets": buckets, "rows": rows,
           "delay_ms": delay_ms}
    rng = np.random.RandomState(5)
    pdf = pd.DataFrame({"k": rng.randint(0, 1_000_000, rows),
                        "v": rng.randint(0, 1_000_000, rows)})
    tables = {}
    for mode, env in (("off", "0"), ("on", "1")):
        app = f"straggler_{mode}"
        victim = f"rdt-executor-{app}-0"
        os.environ["RDT_FAULTS"] = (
            f"executor.run_task:delay:ms={delay_ms}:match={victim}|")
        os.environ["RDT_SPECULATION"] = env
        # fixed reduce fan-in: isolate speculation from AQE coalescing;
        # pipeline off so the wall measures speculation alone
        os.environ["RDT_ETL_AQE"] = "0"
        os.environ["RDT_SHUFFLE_PIPELINE"] = "0"
        # half the stage rides the straggler, so the default 0.75 completion
        # gate could never open; the min floor keeps smoke thresholds honest
        os.environ["RDT_SPECULATION_QUANTILE"] = "0.5"
        os.environ["RDT_SPECULATION_MIN_S"] = "0.2"
        session = raydp_tpu.init(app, num_executors=2, executor_cores=2,
                                 executor_memory="1GB")
        try:
            df = session.createDataFrame(pdf, num_partitions=maps)
            client = get_client()
            before = client.stats()["num_objects"]
            session.engine.reset_shuffle_stage_report()
            t0 = time.perf_counter()
            table = df.repartition(buckets).to_arrow()
            out[f"wall_{mode}_s"] = round(time.perf_counter() - t0, 4)
            report = session.engine.shuffle_stage_report()
            out[f"speculated_{mode}"] = sum(e.get("speculated", 0)
                                            for e in report)
            out[f"speculation_won_{mode}"] = sum(e.get("speculation_won", 0)
                                                 for e in report)
            # losing backups land late (the delayed copies) and free through
            # the late-result path: poll the store audit back to baseline
            deadline = time.time() + 30
            while time.time() < deadline \
                    and client.stats()["num_objects"] != before:
                time.sleep(0.2)
            out[f"orphans_{mode}"] = client.stats()["num_objects"] - before
            tables[mode] = table.sort_by([("k", "ascending"),
                                          ("v", "ascending")])
        finally:
            raydp_tpu.stop()
            for k in ("RDT_FAULTS", "RDT_SPECULATION",
                      "RDT_SPECULATION_QUANTILE", "RDT_SPECULATION_MIN_S",
                      "RDT_ETL_AQE", "RDT_SHUFFLE_PIPELINE"):
                os.environ.pop(k, None)
    out["speedup_x"] = round(out["wall_off_s"] / max(out["wall_on_s"], 1e-9),
                             2)
    out["identical"] = tables["off"].equals(tables["on"])
    return out


def run_aqe_broadcast_config(session, rows, parts):
    """Rule (a): the SHUFFLE_BYTES join config (wide frame ⋈ small dim)
    with AQE off vs on. On: the dim side replicates (one ranged fetch per
    executor) and NEITHER side hash-shuffles — the recorded number is how
    many fewer bytes cross the store as shuffle/broadcast payload."""
    from raydp_tpu.etl import functions as F

    df = make_frame(session, rows, 16, parts)
    dim = session.createDataFrame(
        pd.DataFrame({"k": np.arange(16), "label": np.arange(16) * 3}),
        num_partitions=2)
    out = {"rows": rows}
    tables = {}
    # pipeline off: the AQE-off mode would otherwise stream its shuffles
    os.environ["RDT_SHUFFLE_PIPELINE"] = "0"
    for mode, env in (("off", "0"), ("on", "1")):
        os.environ["RDT_ETL_AQE"] = env
        session.engine.reset_shuffle_stage_report()
        t0 = time.perf_counter()
        table = (df.join(dim, on="k").select("k", "c0", "label").to_arrow())
        out[f"wall_{mode}_s"] = round(time.perf_counter() - t0, 4)
        report = session.engine.shuffle_stage_report()
        out[f"bytes_{mode}"] = sum(r["bytes_shuffled"] for r in report)
        out[f"stages_{mode}"] = [r["stage"] for r in report]
        out[f"aqe_broadcast_{mode}"] = sum(r.get("aqe_broadcast", 0)
                                           for r in report)
        tables[mode] = table.sort_by([("k", "ascending"),
                                      ("c0", "ascending")])
    os.environ.pop("RDT_ETL_AQE", None)
    os.environ.pop("RDT_SHUFFLE_PIPELINE", None)
    out["reduction_x"] = round(out["bytes_off"] / max(out["bytes_on"], 1), 2)
    out["identical"] = tables["off"].equals(tables["on"])
    return out


def run_aqe_skew_config(smoke):
    """Rule (b): a seeded skewed-key groupby — ONE hot key holds ~50% of the
    rows (the rest are unique, arranged unique-first per partition so the
    cardinality guard emits row-wise partials and the skew SURVIVES to the
    reduce side). The data plane is made honest about byte cost with a
    seeded ``shuffle.fetch`` delay (``ms_per_mb=`` — the skew-mitigation
    analogue of STRAGGLER.json's seeded one-executor delay): on this
    single-core host the win is overlap, exactly what splitting the hot
    bucket's byte-ranges across k reduce tasks buys. Speculation is pinned
    off in BOTH modes (orthogonal; chaos tests cover the composition)."""
    import raydp_tpu
    from raydp_tpu.etl import functions as F

    rows = 40_000 if smoke else 400_000
    parts = 8
    ms_per_mb = 2000 if smoke else 600
    out = {"rows": rows, "maps": parts, "ms_per_mb": ms_per_mb,
           "hot_fraction": 0.5}
    rng = np.random.RandomState(13)
    nuniq = rows // 2
    # hot key 0 (~50% of rows); unique keys elsewhere; per-chunk layout =
    # [unique..., hot...] so each map task's sampled prefix looks distinct
    per = rows // parts
    chunks = []
    next_uniq = 1
    for _ in range(parts):
        nu = per // 2
        ks = np.concatenate([np.arange(next_uniq, next_uniq + nu) * 7 + 3,
                             np.zeros(per - nu, dtype=np.int64)])
        next_uniq += nu
        chunks.append(pd.DataFrame(
            {"k": ks, "v": rng.randint(0, 1000, per).astype(np.int64)}))
    pdf = pd.concat(chunks).reset_index(drop=True)
    tables = {}
    for mode, env in (("off", "0"), ("on", "1")):
        os.environ["RDT_FAULTS"] = (
            f"shuffle.fetch:delay:ms=0:ms_per_mb={ms_per_mb}")
        os.environ["RDT_SPECULATION"] = "0"
        os.environ["RDT_SHUFFLE_PIPELINE"] = "0"
        os.environ["RDT_ETL_AQE"] = env
        os.environ["RDT_AQE_COALESCE_MIN"] = "65536"
        # 4 executors × (max_concurrency 2) = 8 overlappable fetch slots:
        # the split portions' delays must be able to overlap (they are
        # waits, not CPU — same reason the straggler leg's sleeps overlap)
        session = raydp_tpu.init(f"aqe_skew_{mode}", num_executors=4,
                                 executor_cores=1, executor_memory="512MB")
        try:
            df = session.createDataFrame(pdf, num_partitions=parts)
            session.engine.reset_shuffle_stage_report()
            t0 = time.perf_counter()
            table = (df.groupBy("k")
                     .agg(F.sum("v").alias("sv"), F.count("v").alias("n"))
                     .to_arrow())
            out[f"wall_{mode}_s"] = round(time.perf_counter() - t0, 4)
            report = session.engine.shuffle_stage_report()
            out[f"aqe_split_{mode}"] = sum(r.get("aqe_split", 0)
                                           for r in report)
            tables[mode] = table.sort_by([("k", "ascending")])
        finally:
            raydp_tpu.stop()
            for k in ("RDT_FAULTS", "RDT_SPECULATION", "RDT_ETL_AQE",
                      "RDT_AQE_COALESCE_MIN", "RDT_SHUFFLE_PIPELINE"):
                os.environ.pop(k, None)
    out["speedup_x"] = round(out["wall_off_s"] / max(out["wall_on_s"], 1e-9),
                             2)
    out["identical"] = tables["off"].equals(tables["on"])
    return out


def run_aqe_coalesce_config(session, rows, maps, buckets):
    """Rule (c): the 64×64 many-partition repartition — with AQE on,
    adjacent kilobyte-sized reduce buckets fuse into multi-range reads, so
    the reduce side stops paying a dispatch per tiny bucket. The recorded
    number is the reduce-task (dispatch) reduction."""
    rng = np.random.RandomState(17)
    pdf = pd.DataFrame({"k": rng.randint(0, 1_000_000, rows),
                        "v": rng.randint(0, 1_000_000, rows)})
    df = session.createDataFrame(pdf, num_partitions=maps)
    out = {"maps": maps, "buckets": buckets, "rows": rows}
    tables = {}
    os.environ["RDT_SHUFFLE_PIPELINE"] = "0"
    for mode, env in (("off", "0"), ("on", "1")):
        os.environ["RDT_ETL_AQE"] = env
        session.engine.reset_shuffle_stage_report()
        t0 = time.perf_counter()
        table = df.repartition(buckets).to_arrow()
        out[f"wall_{mode}_s"] = round(time.perf_counter() - t0, 4)
        report = session.engine.shuffle_stage_report()
        fused = sum(r.get("aqe_coalesced", 0) for r in report)
        out[f"reduce_tasks_{mode}"] = buckets - fused
        tables[mode] = table.sort_by([("k", "ascending"),
                                      ("v", "ascending")])
    os.environ.pop("RDT_ETL_AQE", None)
    os.environ.pop("RDT_SHUFFLE_PIPELINE", None)
    out["dispatch_reduction_x"] = round(
        out["reduce_tasks_off"] / max(out["reduce_tasks_on"], 1), 2)
    out["identical"] = tables["off"].equals(tables["on"])
    return out


def run_pipeline_config(smoke):
    """The pipelined-shuffle leg: the same 16-map repartition with the
    reduce side dispatched at the barrier vs as seal notifications arrive.
    The map tail is made real with a seeded per-map delay (every 2nd map
    task entering an executor sleeps; ``match=|mt-`` pins the rule to
    shuffle MAP tasks — reduce tasks never match), and the reduce side's
    byte cost with the AQE-skew-leg methodology (a seeded per-MiB
    ``shuffle.fetch`` delay — on a 1-core host the fetch wall IS the
    honest model of a loaded data plane). The fault spec is identical in
    both modes, so the only variable is `RDT_SHUFFLE_PIPELINE`. AQE and
    speculation are pinned off (orthogonal; chaos tests cover the
    compositions)."""
    import raydp_tpu
    from raydp_tpu.runtime.object_store import get_client

    maps, buckets = 16, 8
    rows = maps * (1500 if smoke else 12_000)
    map_delay_ms = 250 if smoke else 700
    ms_per_mb = 8000 if smoke else 5000
    out = {"maps": maps, "buckets": buckets, "rows": rows,
           "map_delay_ms": map_delay_ms, "ms_per_mb": ms_per_mb}
    rng = np.random.RandomState(23)
    pdf = pd.DataFrame({"k": rng.randint(0, 1_000_000, rows),
                        "v": rng.randint(0, 1_000_000, rows)})
    tables = {}
    for mode, env in (("barrier", "0"), ("pipelined", "1")):
        os.environ["RDT_FAULTS"] = (
            f"executor.run_task:delay:ms={map_delay_ms}:every=2:match=|mt-;"
            f"shuffle.fetch:delay:ms=0:ms_per_mb={ms_per_mb}")
        os.environ["RDT_SHUFFLE_PIPELINE"] = env
        os.environ["RDT_ETL_AQE"] = "0"
        os.environ["RDT_SPECULATION"] = "0"
        session = raydp_tpu.init(f"pipeline_{mode}", num_executors=2,
                                 executor_cores=2, executor_memory="1GB")
        try:
            df = session.createDataFrame(pdf, num_partitions=maps)
            client = get_client()
            before = client.stats()["num_objects"]
            session.engine.reset_shuffle_stage_report()
            t0 = time.perf_counter()
            table = df.repartition(buckets).to_arrow()
            out[f"wall_{mode}_s"] = round(time.perf_counter() - t0, 4)
            report = session.engine.shuffle_stage_report()
            out[f"pipelined_{mode}"] = any(e.get("pipelined")
                                           for e in report)
            out[f"overlap_{mode}_s"] = round(
                sum(e.get("overlap_s", 0.0) for e in report), 4)
            firsts = [e["first_reduce_fetch_s"] for e in report
                      if e.get("first_reduce_fetch_s") is not None]
            out[f"first_reduce_fetch_{mode}_s"] = \
                round(min(firsts), 4) if firsts else None
            # the abort/no-orphan audit with reducers mid-stream: the
            # store count must settle back to its pre-action value
            deadline = time.time() + 30
            while time.time() < deadline \
                    and client.stats()["num_objects"] != before:
                time.sleep(0.2)
            out[f"orphans_{mode}"] = \
                client.stats()["num_objects"] - before
            tables[mode] = table.sort_by([("k", "ascending"),
                                          ("v", "ascending")])
        finally:
            raydp_tpu.stop()
            for k in ("RDT_FAULTS", "RDT_SHUFFLE_PIPELINE", "RDT_ETL_AQE",
                      "RDT_SPECULATION"):
                os.environ.pop(k, None)
    out["overlap_s"] = out["overlap_pipelined_s"]
    out["first_reduce_fetch_s"] = out["first_reduce_fetch_pipelined_s"]
    out["speedup_x"] = round(
        out["wall_barrier_s"] / max(out["wall_pipelined_s"], 1e-9), 2)
    out["identical"] = tables["barrier"].equals(tables["pipelined"])
    return out


def main_pipeline(smoke):
    default_path = ("/tmp/PIPELINE_SMOKE.json" if smoke else
                    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "PIPELINE.json"))
    # rdtlint: allow[knob-registry] bench output-path plumbing, not a runtime knob
    out_path = os.environ.get("RDT_PIPELINE_PATH", default_path)
    record = {
        "metric": "etl_shuffle_pipeline",
        "unit": "wall_barrier/wall_pipelined under a seeded per-map delay "
                "spread + per-MiB fetch delay",
        "smoke": smoke,
        "configs": {"pipeline": run_pipeline_config(smoke)},
    }
    cfg = record["configs"]["pipeline"]
    record["value"] = cfg["speedup_x"]
    record["all_identical"] = cfg["identical"]
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(json.dumps({k: v for k, v in record.items() if k != "configs"}))
    print(f"pipeline: wall {cfg['wall_barrier_s']}s -> "
          f"{cfg['wall_pipelined_s']}s ({cfg['speedup_x']}x), overlap "
          f"{cfg['overlap_s']}s, first reduce fetch at "
          f"{cfg['first_reduce_fetch_s']}s, orphans "
          f"{cfg['orphans_pipelined']}, identical={cfg['identical']}")
    return record


def main_aqe(smoke):
    """The ``--aqe`` leg: all three adaptive rules measured off vs on, one
    record per rule, written to benchmarks/AQE.json (``--smoke`` → /tmp)."""
    import raydp_tpu

    default_path = ("/tmp/AQE_SMOKE.json" if smoke else
                    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "AQE.json"))
    # rdtlint: allow[knob-registry] bench output-path plumbing, not a runtime knob
    out_path = os.environ.get("RDT_AQE_PATH", default_path)
    rows = 4_000 if smoke else 400_000
    parts = 4 if smoke else 8
    record = {
        "metric": "etl_aqe",
        "unit": "off/on per rule: shuffled bytes (broadcast), stage wall "
                "(skew split), reduce dispatches (coalesce)",
        "smoke": smoke,
        "configs": {},
    }
    session = raydp_tpu.init("aqe_bench", num_executors=2, executor_cores=2,
                             executor_memory="1GB")
    try:
        record["configs"]["broadcast_join"] = run_aqe_broadcast_config(
            session, rows, parts)
        mp, bk = (16, 16) if smoke else (64, 64)
        record["configs"]["coalesce_many"] = run_aqe_coalesce_config(
            session, rows=mp * (100 if smoke else 600), maps=mp, buckets=bk)
    finally:
        raydp_tpu.stop()
    record["configs"]["skew_groupby"] = run_aqe_skew_config(smoke)

    record["value"] = record["configs"]["broadcast_join"]["reduction_x"]
    record["all_identical"] = all(c["identical"]
                                  for c in record["configs"].values())
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(json.dumps({k: v for k, v in record.items() if k != "configs"}))
    bc = record["configs"]["broadcast_join"]
    print(f"broadcast_join: bytes {bc['bytes_off']} -> {bc['bytes_on']} "
          f"({bc['reduction_x']}x), stages {bc['stages_on']}, "
          f"identical={bc['identical']}")
    sk = record["configs"]["skew_groupby"]
    print(f"skew_groupby: wall {sk['wall_off_s']}s -> {sk['wall_on_s']}s "
          f"({sk['speedup_x']}x), splits {sk['aqe_split_on']}, "
          f"identical={sk['identical']}")
    co = record["configs"]["coalesce_many"]
    print(f"coalesce_many: reduce tasks {co['reduce_tasks_off']} -> "
          f"{co['reduce_tasks_on']} ({co['dispatch_reduction_x']}x), wall "
          f"{co['wall_off_s']}s -> {co['wall_on_s']}s, "
          f"identical={co['identical']}")
    return record


def main_straggler(smoke):
    default_path = ("/tmp/STRAGGLER_SMOKE.json" if smoke else
                    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "STRAGGLER.json"))
    # rdtlint: allow[knob-registry] bench output-path plumbing, not a runtime knob
    out_path = os.environ.get("RDT_STRAGGLER_PATH", default_path)
    record = {
        "metric": "etl_straggler_speculation",
        "unit": "wall_off/wall_on under a seeded one-executor delay",
        "smoke": smoke,
        "configs": {"straggler": run_straggler_config(smoke)},
    }
    cfg = record["configs"]["straggler"]
    record["value"] = cfg["speedup_x"]
    record["all_identical"] = cfg["identical"]
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(json.dumps({k: v for k, v in record.items() if k != "configs"}))
    print(f"straggler: wall {cfg['wall_off_s']}s -> {cfg['wall_on_s']}s "
          f"({cfg['speedup_x']}x), speculated {cfg['speculated_on']} "
          f"(won {cfg['speculation_won_on']}), orphans "
          f"{cfg['orphans_on']}, identical={cfg['identical']}")
    return record


def main():
    smoke = "--smoke" in sys.argv
    if "--straggler" in sys.argv:
        return main_straggler(smoke)
    if "--aqe" in sys.argv:
        return main_aqe(smoke)
    if "--pipeline" in sys.argv:
        return main_pipeline(smoke)
    rows = 4_000 if smoke else 400_000
    parts = 4 if smoke else 8
    default_path = ("/tmp/SHUFFLE_BYTES_SMOKE.json" if smoke else
                    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "SHUFFLE_BYTES.json"))
    # rdtlint: allow[knob-registry] bench output-path plumbing, not a runtime knob
    out_path = os.environ.get("RDT_SHUFFLE_BYTES_PATH", default_path)

    import raydp_tpu
    from raydp_tpu.etl import functions as F

    session = raydp_tpu.init("shuffle_bench", num_executors=2,
                             executor_cores=2, executor_memory="1GB")
    # discarded warmup: executor spin-up and first-touch costs must not land
    # in the first measured config's wall_naive_s (naive runs first)
    warm = make_frame(session, min(rows, 4000), 16, 2)
    warm.groupBy("k").agg(F.count("c0").alias("n")).to_arrow()
    session.engine.reset_shuffle_stage_report()
    record = {
        "metric": "etl_shuffle_bytes",
        "unit": "bytes_naive/bytes_opt per config",
        "rows": rows,
        "smoke": smoke,
        "configs": {},
    }
    try:
        for name, card in (("low_card", 16), ("high_card", rows // 4)):
            df = make_frame(session, rows, card, parts)

            def groupby_action(frame=df):
                return (frame.groupBy("k")
                        .agg(F.sum("c0").alias("s0"),
                             F.mean("c1").alias("m1"),
                             F.count("c0").alias("n"))
                        .to_arrow())

            record["configs"][f"groupby_{name}"] = dict(
                cardinality=card,
                **run_config(session, groupby_action, ["k"]))

            dim = session.createDataFrame(
                pd.DataFrame({"k": np.arange(card),
                              "label": np.arange(card) * 3}),
                num_partitions=2)

            def join_action(frame=df, d=dim):
                return (frame.join(d, on="k")
                        .select("k", "c0", "label")
                        .to_arrow())

            record["configs"][f"join_{name}"] = dict(
                cardinality=card,
                **run_config(session, join_action, ["k", "c0"]))

        # control-plane leg: many small partitions, where per-object fixed
        # costs dominate and consolidation + batched metadata matter most
        mp, bk = (16, 16) if smoke else (64, 64)
        record["configs"]["repartition_many"] = run_consolidate_config(
            session, rows=mp * (100 if smoke else 600), maps=mp, buckets=bk)
    finally:
        raydp_tpu.stop()

    gb = record["configs"]["groupby_low_card"]
    record["value"] = gb["reduction_x"]
    record["all_identical"] = all(c["identical"]
                                  for c in record["configs"].values())
    os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(json.dumps({k: v for k, v in record.items() if k != "configs"}))
    for name, cfg in record["configs"].items():
        if "rpc_reduction_x" in cfg:
            print(f"{name}: store RPCs {cfg['store_rpcs_naive']} -> "
                  f"{cfg['store_rpcs_consolidated']} "
                  f"({cfg['rpc_reduction_x']}x), wall {cfg['wall_naive_s']}s "
                  f"-> {cfg['wall_consolidated_s']}s, "
                  f"identical={cfg['identical']}")
            continue
        print(f"{name}: bytes {cfg['bytes_naive']} -> {cfg['bytes_opt']} "
              f"({cfg['reduction_x']}x), rows {cfg['rows_naive']} -> "
              f"{cfg['rows_opt']}, wall {cfg['wall_naive_s']}s -> "
              f"{cfg['wall_opt_s']}s, identical={cfg['identical']}")
    return record


if __name__ == "__main__":
    main()
