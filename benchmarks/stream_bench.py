"""Continuous-pipeline bench: the ISSUE 15 acceptance record (STREAM.json).

Three configs, each a fresh session, together covering the full
ingest → window → partial_fit → hot-swap loop (doc/streaming.md):

1. ``sustained`` — a synthetic-rate source drives N micro-batch epochs
   through a filter + sliding windowed aggregation; the record carries the
   per-epoch wall quantiles (p50/p99/max — the "bounded per-epoch latency"
   claim), rows/s, windows closed, and the zero-orphan store audit after
   close.
2. ``fault_replay`` — the exactly-once contract: the same windowed
   pipeline runs once unfaulted (the baseline window bytes) and once with
   a seeded mid-stream ``stream.epoch:drop`` losing a freshly sealed
   epoch's partials; the faulted run must REPLAY the epoch from the source
   journal and produce window results byte-identical to the unfaulted run,
   with ``replays >= 1`` proving the fault actually fired and a
   zero-orphan audit after close.
3. ``hot_swap`` — online training under live traffic: a bootstrap
   servable takes an open-loop predict burst while ``partial_fit``
   consumes a stream and hot-swaps freshly exported servables into the
   SAME serving session mid-burst. Zero dropped requests (every future
   resolves with a prediction), ``hot_swaps >= 2``, and the final
   ``serving_report`` names the active servable version/tag.

``--smoke`` shrinks the load, writes to /tmp (never the recorded
artifact), and ASSERTS the contract above; the full run records
``benchmarks/STREAM.json`` (override with ``--out``).

Run: RDT_FAULTS_SEED=7 python benchmarks/stream_bench.py [--smoke] [--out P]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _make_batch(rows):
    def make(epoch):
        import pyarrow as pa
        rng = np.random.RandomState(epoch)
        return pa.table({
            "k": rng.randint(0, 8, rows),
            "v": rng.randint(0, 1000, rows).astype(np.int64),
        })
    return make


def _train_batch(rows):
    def make(epoch):
        import pyarrow as pa
        rng = np.random.RandomState(epoch)
        x = rng.random_sample((rows, 2))
        y = x @ np.array([2.0, -3.0]) + 1.0
        return pa.table({"x1": x[:, 0], "x2": x[:, 1], "y": y})
    return make


def _windowed_pipeline(session, make, epochs):
    from raydp_tpu import stream
    from raydp_tpu.etl.expressions import col

    return stream.read_stream(
        stream.SyntheticSource(make, max_epochs=epochs), session=session
    ).transform(lambda df: df.filter(col("v") >= 0)).window(
        size=3, slide=1, keys=["k"], aggs={"v": ["sum", "mean", "count"]})


def _drive(pipe):
    """Run the pipeline dry; return (window bytes in close order, report)."""
    import pyarrow as pa

    wins = []
    for er in pipe.epochs():
        for w in er.windows:
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, w.table.schema) as wr:
                wr.write_table(w.table)
            wins.append((w.start, w.end, sink.getvalue().to_pybytes()))
    return wins, pipe.report()


def run_sustained_config(smoke):
    """Config 1: sustained epochs, bounded per-epoch latency, no orphans."""
    import raydp_tpu
    from raydp_tpu.runtime.object_store import get_client

    rows = 2_000 if smoke else 20_000
    epochs = 8 if smoke else 40
    s = raydp_tpu.init("stream-bench", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        before = get_client().stats()["num_objects"]
        pipe = _windowed_pipeline(s, _make_batch(rows), epochs)
        t0 = time.time()
        wins, rep = _drive(pipe)
        wall = time.time() - t0
        pipe.close()
        deadline = time.time() + 30
        while time.time() < deadline \
                and get_client().stats()["num_objects"] != before:
            time.sleep(0.2)
        record = {
            "epochs": rep["epochs"],
            "rows_in": rep["rows_in"],
            "rows_per_s": round(rep["rows_in"] / wall, 1) if wall else 0.0,
            "windows_closed": rep["windows_closed"],
            "replays": rep["replays"],
            "epoch_p50_s": rep["epoch_p50_s"],
            "epoch_p99_s": rep["epoch_p99_s"],
            "epoch_max_s": rep["epoch_max_s"],
            "latency_bounded": rep["epoch_p99_s"] < 10.0,
            "orphans": get_client().stats()["num_objects"] - before,
        }
    finally:
        raydp_tpu.stop()
    print(f"[sustained] epochs={record['epochs']} "
          f"p50={record['epoch_p50_s']}s p99={record['epoch_p99_s']}s "
          f"windows={record['windows_closed']} orphans={record['orphans']}")
    return record


def run_fault_replay_config(smoke):
    """Config 2: a dropped epoch blob replays exactly-once — window results
    byte-identical to the unfaulted run, zero orphans."""
    import raydp_tpu
    from raydp_tpu import faults
    from raydp_tpu.runtime.object_store import get_client

    rows = 2_000 if smoke else 10_000
    epochs = 6 if smoke else 16

    def one_run(fault):
        s = raydp_tpu.init("stream-chaos", num_executors=2,
                           executor_cores=1, executor_memory="512MB")
        try:
            before = get_client().stats()["num_objects"]
            if fault:
                # lose the SECOND epoch's freshly sealed partials — the
                # sliding window that includes it must replay from the
                # source journal
                faults.inject("stream.epoch", "drop", nth=2)
            pipe = _windowed_pipeline(s, _make_batch(rows), epochs)
            wins, rep = _drive(pipe)
            pipe.close()
            deadline = time.time() + 30
            while time.time() < deadline \
                    and get_client().stats()["num_objects"] != before:
                time.sleep(0.2)
            orphans = get_client().stats()["num_objects"] - before
            return wins, rep, orphans
        finally:
            faults.clear()
            raydp_tpu.stop()

    base, _, orphans0 = one_run(fault=False)
    got, rep, orphans1 = one_run(fault=True)
    record = {
        "epochs": epochs,
        "windows": len(base),
        "byte_identical": base == got,
        "replays": rep["replays"],
        "fault_fired": rep["replays"] >= 1,
        "orphans_baseline": orphans0,
        "orphans_faulted": orphans1,
    }
    print(f"[fault-replay] identical={record['byte_identical']} "
          f"replays={record['replays']} orphans={record['orphans_faulted']}")
    return record


def run_hot_swap_config(smoke):
    """Config 3: partial_fit hot-swaps servables into a live session under
    an open-loop predict burst — zero dropped requests."""
    import optax

    import raydp_tpu
    from raydp_tpu import stream
    from raydp_tpu.models import MLP
    from raydp_tpu.runtime.object_store import get_client
    from raydp_tpu.serve import ServingSession
    from raydp_tpu.train import FlaxEstimator

    rows = 512 if smoke else 4_096
    epochs = 4 if smoke else 12
    os.environ["RDT_SERVE_BATCH_TIMEOUT_MS"] = "10"
    s = raydp_tpu.init("stream-serve", num_executors=2, executor_cores=1,
                       executor_memory="512MB")
    try:
        est = FlaxEstimator(
            model=MLP(features=(8,), use_batch_norm=False),
            optimizer=optax.adam(1e-2), loss="mse",
            feature_columns=["x1", "x2"], label_column="y",
            batch_size=128, num_epochs=1)
        boot = _train_batch(rows)(10_000).to_pandas()
        est.fit_on_frame(s.createDataFrame(boot, num_partitions=2))
        root = tempfile.mkdtemp(prefix="rdt-stream-bench-")
        v0 = os.path.join(root, "v0")
        est.export_serving(v0)
        srv = ServingSession(v0, session=s, name="stream-bench")
        before = get_client().stats()["num_objects"]

        stop = threading.Event()
        burst = {"sent": 0, "ok": 0, "errors": []}
        rng = np.random.RandomState(5)

        def fire():
            futs = []
            while not stop.is_set():
                x = rng.random_sample((4, 2))
                try:
                    futs.append(srv.predict_async(
                        {"x1": x[:, 0], "x2": x[:, 1]}))
                    burst["sent"] += 1
                except Exception as e:  # noqa: BLE001 - counted below
                    burst["errors"].append(repr(e))
                time.sleep(0.002)
            for f in futs:
                try:
                    preds = f.result(timeout=120.0)
                    assert preds.shape == (4,)
                    burst["ok"] += 1
                except Exception as e:  # noqa: BLE001 - counted below
                    burst["errors"].append(repr(e))

        t = threading.Thread(target=fire)
        t.start()
        pipe = stream.read_stream(
            stream.SyntheticSource(_train_batch(rows), max_epochs=epochs),
            session=s)
        res = est.partial_fit(pipe, export_every=2, export_dir=root,
                              serving=srv)
        time.sleep(0.3)  # a few more requests against the final servable
        stop.set()
        t.join(timeout=600)
        rep = srv.serving_report()
        pipe.close()
        srv.close()
        deadline = time.time() + 30
        while time.time() < deadline \
                and get_client().stats()["num_objects"] != before:
            time.sleep(0.2)
        record = {
            "train_epochs": res.epochs,
            "exports": len(res.exports),
            "hot_swaps": rep["hot_swaps"],
            "active_servable": rep["servable"],
            "requests_sent": burst["sent"],
            "requests_ok": burst["ok"],
            "dropped": burst["sent"] - burst["ok"],
            "errors": burst["errors"][:5],
            "serve_failed": rep["failed"],
            "final_train_loss": round(
                res.history[-1]["train_loss"], 6) if res.history else None,
            "orphans": get_client().stats()["num_objects"] - before,
        }
    finally:
        raydp_tpu.stop()
        os.environ.pop("RDT_SERVE_BATCH_TIMEOUT_MS", None)
    print(f"[hot-swap] swaps={record['hot_swaps']} "
          f"sent={record['requests_sent']} dropped={record['dropped']} "
          f"active=v{record['active_servable']['version']} "
          f"orphans={record['orphans']}")
    return record


def _assert_contract(record):
    sus = record["configs"]["sustained"]
    assert sus["epochs"] > 0 and sus["windows_closed"] > 0, sus
    assert sus["latency_bounded"], sus
    assert sus["orphans"] == 0, sus
    rep = record["configs"]["fault_replay"]
    assert rep["byte_identical"], rep
    assert rep["fault_fired"], rep
    assert rep["orphans_baseline"] == 0 and rep["orphans_faulted"] == 0, rep
    hs = record["configs"]["hot_swap"]
    assert hs["hot_swaps"] >= 2, hs
    assert hs["requests_sent"] > 0, hs
    assert hs["dropped"] == 0 and not hs["errors"], hs
    assert hs["serve_failed"] == 0, hs
    assert hs["active_servable"]["version"] == hs["hot_swaps"] + 1, hs
    assert hs["orphans"] == 0, hs


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI contract: small load, asserts, writes to /tmp")
    ap.add_argument("--out", default=None, help="record path override")
    args = ap.parse_args()
    here = os.path.dirname(os.path.abspath(__file__))
    out = args.out or ("/tmp/STREAM_SMOKE.json" if args.smoke
                       else os.path.join(here, "STREAM.json"))
    configs = {
        "sustained": run_sustained_config(args.smoke),
        "fault_replay": run_fault_replay_config(args.smoke),
        "hot_swap": run_hot_swap_config(args.smoke),
    }
    record = {
        "bench": "stream_bench",
        # the headline number + PERF_CLAIMS handle (tests/test_perf_claims)
        "metric": "stream_sustained_rows_per_s",
        "value": configs["sustained"]["rows_per_s"],
        "smoke": args.smoke,
        "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "configs": configs,
    }
    with open(out, "w") as fh:
        json.dump(record, fh, indent=2, sort_keys=True)
    print(f"record written to {out}")
    _assert_contract(record)
    print("stream bench contract: OK")


if __name__ == "__main__":
    main()
