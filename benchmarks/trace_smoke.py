"""Trace smoke: the observability plane under seeded faults, end to end.

Two phases over real 2-executor sessions (doc/observability.md):

1. **Causal flows under recovery** — a seeded one-shot ``shuffle.write:drop``
   forces a lineage-recovery round inside a groupagg action; the merged
   chrome trace must contain (i) cross-process flow events linking a driver
   span to an executor task span, and (ii) a ``recover:lineage`` span —
   and the re-run's executor task spans — inside the failed read's action
   trace.
2. **Flight recorder** — an every-call drop defeats recovery
   (``RDT_LINEAGE_ROUNDS=1``), the action surfaces ``StageError``, and the
   postmortem ``blackbox-*.json`` bundle must carry the injected-fault,
   object-loss, and recovery-round events.

Run by the CI ``trace-smoke`` leg: ``python benchmarks/trace_smoke.py``.
Asserts loudly; exit 0 is the pass signal. Everything writes under /tmp.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd


def _dataset(session, rows=4000):
    return session.createDataFrame(pd.DataFrame(
        {"k": np.arange(rows) % 7, "v": np.arange(float(rows))}))


def phase_causal_flows(workdir: str) -> None:
    os.environ["RDT_FAULTS"] = (
        "shuffle.write:drop:nth=1:once="
        + os.path.join(workdir, "drop.sentinel"))
    import raydp_tpu
    from raydp_tpu import profiler

    session = raydp_tpu.init("trace-smoke", num_executors=2,
                             executor_cores=1, executor_memory="512MB")
    try:
        out = _dataset(session).groupBy("k").sum("v").collect()
        assert len(out) == 7, f"groupagg returned {len(out)} groups"
        rep = [e for e in session.engine.shuffle_stage_report()
               if e["regenerated"]]
        assert rep, "the seeded drop did not trigger lineage recovery"
        path = profiler.collect_chrome_trace(
            os.path.join(workdir, "trace.json"))
        assert path.skipped_actors == 0, \
            f"{path.skipped_actors} actor lanes missing from the trace"
    finally:
        raydp_tpu.stop()

    data = json.load(open(path))
    evs = data["traceEvents"]
    spans = [e for e in evs if e.get("ph") == "X"]
    flows = [e for e in evs if e.get("cat") == "flow"]
    # (i) >=1 cross-process flow event: a finish landing on an executor
    # task span whose start sits in the driver lane
    task_finishes = [
        e for e in flows if e["ph"] == "f" and e["pid"] != 0
        and any(s.get("pid") != 0 and str(s["name"]).startswith("task:")
                and int(s["sid"], 16) == e["id"] for s in spans)]
    assert task_finishes, "no flow event links a driver span to an " \
        f"executor task span ({len(flows)} flow events total)"
    # (ii) the recovery re-run links back into the failed action's trace
    recov = [s for s in spans if s["name"] == "recover:lineage"]
    assert recov, "no recover:lineage span in the merged trace"
    tr = recov[0]["tr"]
    assert any(s["name"] == "etl:action" and s["tr"] == tr for s in spans), \
        "recover:lineage lost its action's trace id"
    rerun = [s for s in spans if str(s["name"]).startswith("task:")
             and s["pid"] != 0 and s["tr"] == tr
             and s["ts"] >= recov[0]["ts"]]
    assert rerun, "no re-run executor task span inside the action's trace"
    print(f"phase 1 OK: {len(flows)} flow events "
          f"({len(task_finishes)} driver→task), recovery re-run linked, "
          f"offsets {path.clock_offsets_us}")


def phase_flight_recorder(workdir: str) -> None:
    os.environ["RDT_FAULTS"] = "shuffle.write:drop:every=1"
    os.environ["RDT_LINEAGE_ROUNDS"] = "1"
    import raydp_tpu
    from raydp_tpu.etl.engine import StageError
    from raydp_tpu.runtime import head as head_mod

    session = raydp_tpu.init("bbox-smoke", num_executors=2,
                             executor_cores=1, executor_memory="512MB")
    try:
        session_dir = head_mod.get_runtime().session_dir
        failed = False
        try:
            _dataset(session, rows=1000).groupBy("k").sum("v").collect()
        except StageError:
            failed = True
        assert failed, "the every-call drop did not fail the action"
        bb_dir = os.path.join(session_dir, "blackbox")
        bundles = sorted(f for f in os.listdir(bb_dir)
                         if f.startswith("blackbox-")
                         and f.endswith(".json"))
        assert bundles, "failed action wrote no blackbox bundle"
        bundle = json.load(open(os.path.join(bb_dir, bundles[0])))
        kinds = {ev["kind"] for st in bundle["processes"].values()
                 for ev in st.get("events", [])}
        for want in ("fault_injected", "object_lost", "recovery_round",
                     "action_failed"):
            assert want in kinds, f"bundle missing {want!r} (has {kinds})"
        assert bundle["skipped_processes"] == 0
        print(f"phase 2 OK: {bundles[0]} carries {sorted(kinds)}")
    finally:
        raydp_tpu.stop()
        os.environ.pop("RDT_FAULTS", None)
        os.environ.pop("RDT_LINEAGE_ROUNDS", None)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    workdir = tempfile.mkdtemp(prefix="rdt-trace-smoke-")
    phase_causal_flows(workdir)
    phase_flight_recorder(workdir)
    print("trace smoke passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
