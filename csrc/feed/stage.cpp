// Native host-feed staging: Arrow column buffers -> one [rows, n_cols]
// interleaved train-batch matrix, cast fused with the transpose.
//
// Role (SURVEY.md section 7 step 2, "Arrow IPC <-> pinned host buffer staging
// for fast device_put"): the streaming DeviceFeed's host cost is decoding N
// fixed-width Arrow columns into the contiguous [rows, features] array that
// jax.device_put ships to HBM. The numpy path pays one full pass per column
// for the dtype cast (astype) plus a second full strided pass for the
// interleave (np.stack); this kernel does cast+interleave in ONE pass per
// column straight from the Arrow validity-free data buffer into the
// destination, optionally fanning columns out over a small thread pool
// (useful on multi-core feed hosts; the 1-core CI host runs n_threads=1).
//
// No Arrow library dependency: Python hands raw data-buffer pointers
// (pyarrow exposes them zero-copy) plus dtype codes. Null-bearing or
// non-primitive columns never reach this code (the Python caller falls back
// to the numpy path).
//
// Reference parity note: the reference's equivalent hot path is the
// JVM-side block fetcher feeding torch tensors
// (ObjectStoreReader.java + torch dataset collate); this is its TPU-native
// replacement on the host side of the feed.

#include <atomic>
#include <cstdint>
#include <thread>
#include <type_traits>
#include <vector>

namespace {

// dtype codes shared with raydp_tpu/native/stage.py (keep in sync)
enum DType : int {
  F32 = 0, F64 = 1, I8 = 2, I16 = 3, I32 = 4, I64 = 5,
  U8 = 6, U16 = 7, U32 = 8, U64 = 9,
};

template <typename S, typename D>
void cast_into(const void* src_v, void* dst_v, int64_t rows,
               int64_t dst_stride, int64_t dst_col) {
  const S* src = static_cast<const S*>(src_v);
  D* dst = static_cast<D*>(dst_v) + dst_col;
  for (int64_t r = 0; r < rows; ++r) {
    dst[r * dst_stride] = static_cast<D>(src[r]);
  }
}

template <typename D>
int dispatch_src(const void* src, int src_type, void* dst, int64_t rows,
                 int64_t dst_stride, int64_t dst_col) {
  // float -> integral is undefined behavior in C++ for NaN/out-of-range
  // values (and numpy's fallback has different, platform-defined behavior,
  // so the byte-parity contract cannot hold either way): decline the pair,
  // the Python caller falls back to numpy.
  if (std::is_integral<D>::value && (src_type == F32 || src_type == F64)) {
    return -1;
  }
  switch (src_type) {
    case F32: cast_into<float, D>(src, dst, rows, dst_stride, dst_col); return 0;
    case F64: cast_into<double, D>(src, dst, rows, dst_stride, dst_col); return 0;
    case I8:  cast_into<int8_t, D>(src, dst, rows, dst_stride, dst_col); return 0;
    case I16: cast_into<int16_t, D>(src, dst, rows, dst_stride, dst_col); return 0;
    case I32: cast_into<int32_t, D>(src, dst, rows, dst_stride, dst_col); return 0;
    case I64: cast_into<int64_t, D>(src, dst, rows, dst_stride, dst_col); return 0;
    case U8:  cast_into<uint8_t, D>(src, dst, rows, dst_stride, dst_col); return 0;
    case U16: cast_into<uint16_t, D>(src, dst, rows, dst_stride, dst_col); return 0;
    case U32: cast_into<uint32_t, D>(src, dst, rows, dst_stride, dst_col); return 0;
    case U64: cast_into<uint64_t, D>(src, dst, rows, dst_stride, dst_col); return 0;
    default: return -1;
  }
}

int stage_one(const void* src, int src_type, int64_t rows, void* dst,
              int dst_type, int64_t dst_stride, int64_t dst_col) {
  switch (dst_type) {
    case F32: return dispatch_src<float>(src, src_type, dst, rows, dst_stride, dst_col);
    case F64: return dispatch_src<double>(src, src_type, dst, rows, dst_stride, dst_col);
    case I32: return dispatch_src<int32_t>(src, src_type, dst, rows, dst_stride, dst_col);
    case I64: return dispatch_src<int64_t>(src, src_type, dst, rows, dst_stride, dst_col);
    default: return -1;
  }
}

}  // namespace

extern "C" {

// One column (or one chunk of one column): cast `rows` values of `src_type`
// from `src` into dst[dst_row0 + r][dst_col] of a [*, dst_stride] dst_type
// matrix. Returns 0, or -1 for an unsupported dtype pair.
int rdt_stage_cast(const void* src, int src_type, int64_t rows, void* dst,
                   int dst_type, int64_t dst_stride, int64_t dst_col,
                   int64_t dst_row0) {
  if (rows < 0 || dst_stride <= 0 || dst_col < 0 || dst_col >= dst_stride) {
    return -1;
  }
  char* base = static_cast<char*>(dst);
  int64_t elem = (dst_type == F64 || dst_type == I64) ? 8 : 4;
  return stage_one(src, src_type, rows, base + dst_row0 * dst_stride * elem,
                   dst_type, dst_stride, dst_col);
}

// All columns of a single-chunk table in one call, columns fanned out over
// `n_threads` workers (<=1 = inline). All columns share `rows`.
int rdt_stage_columns(const void** srcs, const int* src_types, int64_t n_cols,
                      int64_t rows, void* dst, int dst_type, int n_threads) {
  if (n_cols <= 0) return -1;
  // validate dtypes up-front so threaded work cannot partially fail
  bool dst_integral = (dst_type == I32 || dst_type == I64);
  for (int64_t c = 0; c < n_cols; ++c) {
    if (src_types[c] < F32 || src_types[c] > U64) return -1;
    // float -> int: UB on NaN/out-of-range, declined (see dispatch_src)
    if (dst_integral && (src_types[c] == F32 || src_types[c] == F64)) {
      return -1;
    }
  }
  if (dst_type != F32 && dst_type != F64 && dst_type != I32 &&
      dst_type != I64) {
    return -1;
  }
  if (n_threads <= 1 || n_cols == 1) {
    for (int64_t c = 0; c < n_cols; ++c) {
      if (stage_one(srcs[c], src_types[c], rows, dst, dst_type, n_cols, c)) {
        return -1;
      }
    }
    return 0;
  }
  int workers = n_threads < n_cols ? n_threads : static_cast<int>(n_cols);
  // per-worker status accumulates into one atomic flag: the pre-checks above
  // should make a dispatch miss unreachable, but a future edit loosening
  // them (or a Python/C++ dtype-table drift) must fail loudly with -1, never
  // silently leave np.empty garbage in unwritten columns (ADVICE r5 #3)
  std::atomic<int> status{0};
  std::vector<std::thread> pool;
  pool.reserve(workers);
  for (int w = 0; w < workers; ++w) {
    pool.emplace_back([=, &status]() {
      for (int64_t c = w; c < n_cols; c += workers) {
        if (stage_one(srcs[c], src_types[c], rows, dst, dst_type, n_cols,
                      c)) {
          status.store(-1, std::memory_order_relaxed);
        }
      }
    });
  }
  for (auto& t : pool) t.join();
  return status.load(std::memory_order_relaxed);
}

}  // extern "C"
