// Shared-memory arena allocator: the C++ core of the object store.
//
// The reference's data plane is Ray's plasma store — a native (C++) shared-memory
// object store that Spark executors (JVM) and Python training workers map
// zero-copy (SURVEY.md §2.3 item 11; reference RayDPUtils.java:45-53 readBinary
// rehydrates an object from raw id + owner address). This file is the TPU build's
// native equivalent: one large POSIX shared-memory segment per session holding
// all object payloads, carved by a first-fit free-list allocator with block
// splitting and address-ordered coalescing. Python processes attach the segment
// once and read every object through zero-copy memoryview slices; writers
// allocate through rdt_alloc from any process (the free list is guarded by a
// process-shared robust mutex).
//
// Design constraints:
// - 64-byte block alignment: Arrow buffers want cache-line alignment, and it
//   keeps payloads aligned for the host-side staging copy into HBM transfers.
// - Robust mutex: if a writer process is SIGKILLed mid-allocation (actor crash,
//   fault-injection tests), the next locker gets EOWNERDEAD, marks the mutex
//   consistent, and continues; at worst a block leaks until session shutdown,
//   which unlinks the whole segment.
// - The metadata table (object id -> offset/size/kind/owner) deliberately lives
//   in the head process, not here: ownership/lineage policy changes often,
//   payload layout does not.

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52445453544f5245ULL;  // "RDTSTORE"
constexpr uint32_t kBlockMagic = 0x424c4b21;        // "BLK!"
constexpr uint64_t kAlign = 64;

struct Header {
  uint64_t magic;
  uint64_t arena_size;
  uint64_t free_head;      // offset of first free block header; 0 = none
  uint64_t bytes_in_use;   // live payload bytes
  uint64_t num_allocs;     // live allocation count
  uint64_t peak_bytes;
  pthread_mutex_t lock;
  char pad_[kAlign];
};

struct BlockHdr {
  uint64_t size;  // payload capacity in bytes, multiple of kAlign
  uint64_t next;  // free-list link (offset of next free block) when free
  uint32_t free;
  uint32_t magic;
  char pad_[kAlign - 2 * sizeof(uint64_t) - 2 * sizeof(uint32_t)];
};
static_assert(sizeof(BlockHdr) == kAlign, "block header must be one cache line");

inline uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

inline Header* hdr(void* base) { return reinterpret_cast<Header*>(base); }

inline BlockHdr* blk(void* base, uint64_t off) {
  return reinterpret_cast<BlockHdr*>(static_cast<char*>(base) + off);
}

inline uint64_t first_block_offset() { return align_up(sizeof(Header), kAlign); }

int lock_arena(Header* h) {
  int rc = pthread_mutex_lock(&h->lock);
  if (rc == EOWNERDEAD) {
    // A lock holder died mid-critical-section. Recover-and-continue policy:
    // the free list may have lost a block (leak), but links are written before
    // publication so traversal stays safe; the leak is bounded by session
    // lifetime (shutdown unlinks the segment).
    pthread_mutex_consistent(&h->lock);
    rc = 0;
  }
  return rc;
}

}  // namespace

extern "C" {

// Creates and maps a new arena segment. Returns the mapped base or null.
void* rdt_arena_create(const char* name, uint64_t size) {
  size = align_up(size, 4096);
  if (size < first_block_offset() + sizeof(BlockHdr) + kAlign) return nullptr;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }

  Header* h = hdr(base);
  memset(h, 0, sizeof(Header));
  h->arena_size = size;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->lock, &attr);
  pthread_mutexattr_destroy(&attr);

  uint64_t first = first_block_offset();
  BlockHdr* b = blk(base, first);
  b->size = size - first - sizeof(BlockHdr);
  b->next = 0;
  b->free = 1;
  b->magic = kBlockMagic;
  h->free_head = first;
  h->magic = kMagic;  // published last: attachers check it
  return base;
}

// Attaches an existing arena. Returns the mapped base or null.
void* rdt_arena_attach(const char* name, uint64_t* size_out) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  if (hdr(base)->magic != kMagic) {
    munmap(base, st.st_size);
    return nullptr;
  }
  if (size_out) *size_out = static_cast<uint64_t>(st.st_size);
  return base;
}

// Allocates `size` payload bytes. Returns the payload offset, or -1 if the
// arena cannot satisfy the request (caller falls back to a dedicated segment).
int64_t rdt_alloc(void* base, uint64_t size) {
  Header* h = hdr(base);
  uint64_t need = align_up(size ? size : 1, kAlign);
  if (lock_arena(h) != 0) return -1;

  uint64_t prev = 0;
  uint64_t off = h->free_head;
  while (off != 0) {
    BlockHdr* b = blk(base, off);
    if (b->size >= need) {
      uint64_t remainder = b->size - need;
      if (remainder >= sizeof(BlockHdr) + kAlign) {
        // Split: tail of this block stays on the free list.
        uint64_t tail_off = off + sizeof(BlockHdr) + need;
        BlockHdr* tail = blk(base, tail_off);
        tail->size = remainder - sizeof(BlockHdr);
        tail->next = b->next;
        tail->free = 1;
        tail->magic = kBlockMagic;
        b->size = need;
        if (prev)
          blk(base, prev)->next = tail_off;
        else
          h->free_head = tail_off;
      } else {
        if (prev)
          blk(base, prev)->next = b->next;
        else
          h->free_head = b->next;
      }
      b->free = 0;
      b->next = 0;
      h->bytes_in_use += b->size;
      h->num_allocs += 1;
      if (h->bytes_in_use > h->peak_bytes) h->peak_bytes = h->bytes_in_use;
      pthread_mutex_unlock(&h->lock);
      return static_cast<int64_t>(off + sizeof(BlockHdr));
    }
    prev = off;
    off = b->next;
  }
  pthread_mutex_unlock(&h->lock);
  return -1;
}

// Frees the allocation whose payload starts at `payload_off`.
// Returns 0 on success, -1 on an invalid or double free.
int rdt_free(void* base, uint64_t payload_off) {
  Header* h = hdr(base);
  if (payload_off < first_block_offset() + sizeof(BlockHdr) ||
      payload_off >= h->arena_size)
    return -1;
  uint64_t off = payload_off - sizeof(BlockHdr);
  BlockHdr* b = blk(base, off);
  if (b->magic != kBlockMagic) return -1;
  if (lock_arena(h) != 0) return -1;
  if (b->free) {
    pthread_mutex_unlock(&h->lock);
    return -1;
  }
  h->bytes_in_use -= b->size;
  h->num_allocs -= 1;
  b->free = 1;

  // Address-ordered insert, then coalesce with both neighbours if adjacent.
  uint64_t prev = 0;
  uint64_t cur = h->free_head;
  while (cur != 0 && cur < off) {
    prev = cur;
    cur = blk(base, cur)->next;
  }
  b->next = cur;
  if (prev)
    blk(base, prev)->next = off;
  else
    h->free_head = off;

  if (cur != 0 && off + sizeof(BlockHdr) + b->size == cur) {
    BlockHdr* nb = blk(base, cur);
    b->size += sizeof(BlockHdr) + nb->size;
    b->next = nb->next;
    nb->magic = 0;
  }
  if (prev != 0) {
    BlockHdr* pb = blk(base, prev);
    if (prev + sizeof(BlockHdr) + pb->size == off) {
      pb->size += sizeof(BlockHdr) + b->size;
      pb->next = b->next;
      b->magic = 0;
    }
  }
  pthread_mutex_unlock(&h->lock);
  return 0;
}

// out[0..3] = arena_size, bytes_in_use, live allocation count, peak bytes.
void rdt_stats(void* base, uint64_t* out) {
  Header* h = hdr(base);
  if (lock_arena(h) != 0) {
    out[0] = out[1] = out[2] = out[3] = 0;
    return;
  }
  out[0] = h->arena_size;
  out[1] = h->bytes_in_use;
  out[2] = h->num_allocs;
  out[3] = h->peak_bytes;
  pthread_mutex_unlock(&h->lock);
}

int rdt_detach(void* base) {
  return munmap(base, hdr(base)->arena_size);
}

int rdt_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
