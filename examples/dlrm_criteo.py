"""Criteo DLRM end-to-end — the port of the reference's heaviest workload
(examples/pytorch_dlrm.ipynb): Criteo-format TSV → distributed preprocessing
(frequency-limited categorical dictionaries via groupBy counts, log-transform
on numerics — the notebook's ``pre_process``) → DLRM with sharded embedding
tables trained under pjit.

Synthetic Criteo-shaped data is generated when no ``--tsv`` is given: 1 int
label, 13 int dense features with missing values, 26 categorical string
columns with a skewed (zipf) distribution — the reference's schema
(pytorch_dlrm.ipynb: LABEL_COL=0, INT_COLS=1..13, CAT_COLS=14..39).

Run: python examples/dlrm_criteo.py [--rows 200000] [--epochs 2]
     [--scale small|full]   # full = reference model dims (512-128-32 bottom,
                            # 1024-1024-512-256-1 top, 26×embedding_dim=32)
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_DENSE = 13
NUM_CAT = 26
LABEL = "_c0"
DENSE_COLS = [f"_c{i}" for i in range(1, NUM_DENSE + 1)]
CAT_COLS = [f"_c{i}" for i in range(NUM_DENSE + 1, NUM_DENSE + 1 + NUM_CAT)]


def generate_criteo(rows: int, path: str, seed: int = 0,
                    cat_cardinality: int = 1000) -> None:
    """Criteo-format TSV: label \\t 13 ints (w/ blanks) \\t 26 cat tokens."""
    rng = np.random.RandomState(seed)
    label = (rng.random_sample(rows) < 0.25).astype(np.int64)
    dense = rng.poisson(8, size=(rows, NUM_DENSE)).astype(object)
    dense[rng.random_sample(dense.shape) < 0.1] = ""  # missing values
    cats = np.empty((rows, NUM_CAT), dtype=object)
    for j in range(NUM_CAT):
        ids = rng.zipf(1.3, size=rows) % cat_cardinality
        cats[:, j] = np.char.add(f"t{j}_", ids.astype(str))
    with open(path, "w") as f:
        for i in range(rows):
            f.write("\t".join([str(label[i])]
                              + [str(v) for v in dense[i]]
                              + list(cats[i])) + "\n")


def pre_process(session, df, frequency_limit: int = 3):
    """The notebook's ``pre_process``: per-column frequency-limited dictionary
    (rank by count, ids dense from 1; rare/null → 0) built with distributed
    groupBy counts, then log(x+1) on the numeric columns."""
    from raydp_tpu.etl import functions as F
    from raydp_tpu.etl.expressions import col, udf

    sizes = []
    for c in CAT_COLS:
        counts = (df.groupBy(c).agg(F.count(c).alias("n"))
                  .to_pandas())
        counts = counts[counts["n"] >= frequency_limit]
        counts = counts.sort_values("n", ascending=False)
        mapping = {v: i + 1 for i, v in enumerate(counts[c])}
        sizes.append(len(mapping) + 1)  # 0 = rare/unseen
        to_id = udf("int64")(lambda v, m=mapping: m.get(v, 0))
        df = df.withColumn(c, to_id(col(c)))
    for c in DENSE_COLS:
        v = col(c).cast("double").fill_null(0.0)
        df = df.withColumn(c, F.log1p(v))
    return df, sizes


def main():
    import optax

    import raydp_tpu
    from raydp_tpu.models import DLRM, criteo_batch_preprocessor, \
        dlrm_param_rules
    from raydp_tpu.parallel import MeshSpec, make_mesh
    from raydp_tpu.train import FlaxEstimator

    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=4096)
    ap.add_argument("--num-executors", type=int, default=2)
    ap.add_argument("--scale", choices=["small", "full"], default="full")
    ap.add_argument("--tsv", default=None, help="real Criteo TSV path")
    args = ap.parse_args()

    tsv = args.tsv
    if tsv is None:
        tsv = os.path.join(tempfile.mkdtemp(), "criteo.tsv")
        print(f"generating {args.rows} Criteo-format rows ...")
        generate_criteo(args.rows, tsv)

    session = raydp_tpu.init("dlrm", num_executors=args.num_executors,
                             executor_cores=1, executor_memory="2GB")
    try:
        names = [LABEL] + DENSE_COLS + CAT_COLS
        df = session.read.csv(
            tsv, num_partitions=args.num_executors * 2,
            options={"delimiter": "\t", "column_names": names})
        t0 = time.perf_counter()
        df, cat_sizes = pre_process(session, df)
        print(f"pre_process: {time.perf_counter() - t0:.1f}s; "
              f"category sizes: min={min(cat_sizes)} max={max(cat_sizes)}")

        if args.scale == "full":
            # reference dims (pytorch_dlrm.ipynb / BASELINE.md)
            model_kw = dict(embedding_dim=32, bottom_mlp=(512, 128, 32),
                            top_mlp=(1024, 1024, 512, 256, 1))
        else:
            model_kw = dict(embedding_dim=8, bottom_mlp=(64, 8),
                            top_mlp=(64, 32, 1))

        import jax
        n_dev = len(jax.devices())
        expert = 2 if n_dev % 2 == 0 else 1
        mesh = make_mesh(MeshSpec(expert=expert))
        import jax.numpy as jnp
        est = FlaxEstimator(
            model=DLRM(categorical_sizes=cat_sizes, num_dense=NUM_DENSE,
                       dtype=jnp.bfloat16, **model_kw),
            optimizer=optax.adagrad(1e-2),
            loss="bce_with_logits",
            feature_columns=DENSE_COLS + CAT_COLS,
            label_column=LABEL,
            feature_dtype=np.float64,
            label_dtype=np.float32,
            batch_size=args.batch_size,
            num_epochs=args.epochs,
            mesh=mesh,
            param_rules=dlrm_param_rules("expert") if expert > 1 else None,
            batch_preprocessor=criteo_batch_preprocessor(NUM_DENSE),
        )
        result = est.fit_on_frame(df)
        for row in result.history:
            print(row)
    finally:
        raydp_tpu.stop()


if __name__ == "__main__":
    main()
