"""NYCTaxi fare regression with XLA-native gradient-boosted trees.

The port of the reference's XGBoost example (examples/xgboost_ray_nyctaxi.py:
Spark ETL → XGBoostTrainer over Rabit): the same ETL feeds
:class:`raydp_tpu.train.GBDTEstimator`, whose histogram trees are dense XLA
array programs (segment-sum histograms + gain scans). Demonstrates per-round
eval reporting and early stopping.

Run: python examples/gbdt_nyctaxi.py [--rows 100000] [--rounds 100]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--rounds", type=int, default=100)
    ap.add_argument("--max-depth", type=int, default=6)
    ap.add_argument("--early-stopping-rounds", type=int, default=10)
    ap.add_argument("--num-executors", type=int, default=2)
    ap.add_argument("--csv", default=None)
    args = ap.parse_args()

    import raydp_tpu
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.train import GBDTEstimator

    csv_path = args.csv
    if csv_path is None:
        from generate_nyctaxi import generate
        csv_path = os.path.join(tempfile.mkdtemp(), "nyctaxi.csv")
        generate(args.rows).to_csv(csv_path, index=False)

    session = raydp_tpu.init("gbdt-nyctaxi", num_executors=args.num_executors,
                             executor_cores=1, executor_memory="1GB")
    try:
        data = session.read.csv(csv_path, num_partitions=args.num_executors * 2)
        data = nyc_taxi_preprocess(data)
        train_df, test_df = data.randomSplit([0.9, 0.1], seed=0)
        features = feature_columns(data)

        est = GBDTEstimator(
            # xgboost-style params (reference xgboost_ray_nyctaxi.py:60-75)
            params={"objective": "reg:squarederror",
                    "max_depth": args.max_depth, "eta": 0.3},
            feature_columns=features,
            label_column=LABEL,
            num_boost_round=args.rounds,
            early_stopping_rounds=args.early_stopping_rounds,
        )
        result = est.fit_on_frame(train_df, test_df)
        print(result.history[-1])
        rounds = est.evals_result.get("eval_rmse", [])
        if rounds:
            print(f"eval rmse by round: first={rounds[0]:.4f} "
                  f"best={min(rounds):.4f} rounds_run={len(rounds)}")
        model = est.get_model()
        print(f"forest: {model.num_trees} trees, "
              f"best_iteration={model.best_iteration}")
    finally:
        raydp_tpu.stop()


if __name__ == "__main__":
    main()
