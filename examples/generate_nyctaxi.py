"""Generate a synthetic NYC-taxi-like CSV (schema parity with the reference's
fake_nyctaxi.csv / random_nyctaxi.py generator — values are synthetic)."""

from __future__ import annotations

import argparse

import numpy as np
import pandas as pd


def generate(num_rows: int, seed: int = 0) -> pd.DataFrame:
    rng = np.random.RandomState(seed)
    pickup_lon = rng.uniform(-74.2, -73.7, num_rows)
    pickup_lat = rng.uniform(40.5, 41.0, num_rows)
    drop_lon = pickup_lon + rng.normal(0, 0.03, num_rows)
    drop_lat = pickup_lat + rng.normal(0, 0.03, num_rows)
    dist = np.abs(drop_lon - pickup_lon) + np.abs(drop_lat - pickup_lat)
    base = pd.Timestamp("2019-01-01").value
    span = pd.Timestamp("2019-12-31").value - base
    ts = pd.to_datetime(base + (rng.random_sample(num_rows) * span).astype("int64"))
    passengers = rng.randint(1, 7, num_rows)
    fare = 2.5 + dist * 110 + passengers * 0.4 + rng.normal(0, 1.5, num_rows)
    return pd.DataFrame({
        "fare_amount": np.clip(fare, 2.5, 249.0),
        "pickup_datetime": ts.strftime("%Y-%m-%d %H:%M:%S"),
        "pickup_longitude": pickup_lon,
        "pickup_latitude": pickup_lat,
        "dropoff_longitude": drop_lon,
        "dropoff_latitude": drop_lat,
        "passenger_count": passengers,
    })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--out", default="nyctaxi.csv")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    generate(args.rows, args.seed).to_csv(args.out, index=False)
    print(f"wrote {args.rows} rows to {args.out}")


if __name__ == "__main__":
    main()
