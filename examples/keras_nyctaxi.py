"""NYCTaxi fare regression through the KerasEstimator (Keras 3, JAX backend).

The port of the reference's TFEstimator example (examples/tensorflow_nyctaxi.py:
Spark ETL → TFEstimator with MultiWorkerMirroredStrategy). Here the same ETL
feeds a Keras model compiled by XLA; ``data_parallel=True`` shards each batch
over all local devices (the MWMS replacement).

Run: python examples/keras_nyctaxi.py [--rows 100000] [--epochs 4]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("KERAS_BACKEND", "jax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--num-executors", type=int, default=2)
    ap.add_argument("--csv", default=None)
    args = ap.parse_args()

    import raydp_tpu
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.train import KerasEstimator

    csv_path = args.csv
    if csv_path is None:
        from generate_nyctaxi import generate
        csv_path = os.path.join(tempfile.mkdtemp(), "nyctaxi.csv")
        generate(args.rows).to_csv(csv_path, index=False)

    session = raydp_tpu.init("keras-nyctaxi", num_executors=args.num_executors,
                             executor_cores=1, executor_memory="1GB")
    try:
        data = session.read.csv(csv_path, num_partitions=args.num_executors * 2)
        data = nyc_taxi_preprocess(data)
        train_df, test_df = data.randomSplit([0.9, 0.1], seed=0)
        features = feature_columns(data)

        def build_model():
            import keras
            # the reference example's layer stack (tensorflow_nyctaxi.py)
            return keras.Sequential([
                keras.layers.Input(shape=(len(features),)),
                keras.layers.Dense(256, activation="relu"),
                keras.layers.BatchNormalization(),
                keras.layers.Dense(128, activation="relu"),
                keras.layers.BatchNormalization(),
                keras.layers.Dense(64, activation="relu"),
                keras.layers.Dense(1),
            ])

        import jax
        est = KerasEstimator(
            model_builder=build_model,
            optimizer="adam",
            loss="mse",
            metrics=["mae"],
            feature_columns=features,
            label_column=LABEL,
            batch_size=args.batch_size,
            num_epochs=args.epochs,
            data_parallel=len(jax.devices()) > 1,
        )
        result = est.fit_on_frame(train_df, test_df)
        for row in result.history:
            print(row)
        print("model saved under:", result.checkpoint_dir)
    finally:
        raydp_tpu.stop()


if __name__ == "__main__":
    main()
