"""Long-context LM training demo: sequence parallelism over the mesh's seq axis.

Runs a small decoder-only transformer over sequences sharded across devices:
ring attention rotates K/V blocks over ICI while each device attends for its
local queries, so per-device memory stays O(T / seq_devices) and contexts can
exceed single-chip HBM. On CPU, run with:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/longcontext_lm.py --seq-len 512 --steps 20

(The reference has no long-context support at all — SURVEY.md §2.4 — this is
TPU-native added capability.)
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--seq-parallel", type=int, default=0,
                   help="devices on the seq axis (0 = all devices)")
    p.add_argument("--tensor-parallel", type=int, default=1,
                   help="devices on the tensor axis (Megatron param split)")
    args = p.parse_args()

    import jax
    # interpreter startup may pre-register a hardware platform; re-assert the
    # requested one before the first device touch (same dance as tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raydp_tpu.models import TransformerLM, lm_loss, \
        transformer_param_rules
    from raydp_tpu.parallel import MeshSpec, make_mesh, shard_params

    n_dev = len(jax.devices())
    tp = args.tensor_parallel
    if tp < 1 or n_dev % tp:
        raise SystemExit(f"--tensor-parallel must be >= 1 and divide the "
                         f"device count ({n_dev})")
    seq_par = args.seq_parallel or n_dev // tp
    mesh = make_mesh(MeshSpec(data=n_dev // (seq_par * tp), seq=seq_par,
                              tensor=tp))
    print(f"devices={n_dev} mesh={dict(mesh.shape)}")

    model = TransformerLM(vocab_size=args.vocab, dim=args.dim,
                          num_heads=args.heads, num_layers=args.layers,
                          attention="ring" if seq_par > 1 else "auto",
                          mesh=mesh)

    rng = np.random.RandomState(0)
    start = rng.randint(0, args.vocab, size=(args.batch, 1))
    tokens = jnp.asarray((start + np.arange(args.seq_len)[None]) % args.vocab,
                         dtype=jnp.int32)
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", "seq")))

    variables = model.init(jax.random.PRNGKey(0), tokens)
    tx = optax.adamw(3e-4)
    params = variables["params"]
    opt_state = tx.init(params)
    if tp > 1:
        # Megatron split: q/k/v + gate/up column-parallel, o/down row-parallel
        rules = transformer_param_rules("tensor")
        params = shard_params(params, mesh, rules)
        opt_state = shard_params(opt_state, mesh, rules)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: lm_loss(model.apply({"params": p}, batch), batch)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    with mesh:
        t0 = time.perf_counter()
        for i in range(args.steps):
            params, opt_state, loss = step(params, opt_state, tokens)
            if i % 5 == 0 or i == args.steps - 1:
                print(f"step {i}: loss {float(loss):.4f}")
        dt = time.perf_counter() - t0
    toks = args.batch * args.seq_len * args.steps
    print(f"{toks / dt:.0f} tokens/s over {n_dev} devices "
          f"(seq_parallel={seq_par}, T={args.seq_len})")


if __name__ == "__main__":
    main()
