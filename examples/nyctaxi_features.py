"""NYC taxi feature pipeline — functional parity with the reference's
examples/data_process.py (clean_up + time features + distance features), built on
raydp_tpu's expression API. Where the reference reaches for Python UDFs
(``night``, ``late_night``, ``manhattan``), we use vectorized expressions — the
columnar path — and keep one UDF only where shown as an escape-hatch example.
"""

from __future__ import annotations

from raydp_tpu.etl import functions as F
from raydp_tpu.etl.expressions import col, lit, when

LABEL = "fare_amount"


def clean_up(data):
    return (data
            .filter(col("pickup_longitude") <= -72)
            .filter(col("pickup_longitude") >= -76)
            .filter(col("dropoff_longitude") <= -72)
            .filter(col("dropoff_longitude") >= -76)
            .filter(col("pickup_latitude") <= 42)
            .filter(col("pickup_latitude") >= 38)
            .filter(col("dropoff_latitude") <= 42)
            .filter(col("dropoff_latitude") >= 38)
            .filter(col("passenger_count") <= 6)
            .filter(col("passenger_count") >= 1)
            .filter(col("fare_amount") > 0)
            .filter(col("fare_amount") < 250)
            .filter(col("dropoff_longitude") != col("pickup_longitude"))
            .filter(col("dropoff_latitude") != col("pickup_latitude")))


def add_time_features(data):
    ts = col("pickup_datetime").cast("timestamp")
    data = (data
            .withColumn("day", F.dayofmonth(ts))
            .withColumn("hour_of_day", F.hour(ts))
            .withColumn("day_of_week", F.dayofweek(ts) - 2)
            .withColumn("week_of_year", F.weekofyear(ts))
            .withColumn("month_of_year", F.month(ts))
            .withColumn("quarter_of_year", F.quarter(ts))
            .withColumn("year", F.year(ts)))
    night = when((col("hour_of_day") >= 16) & (col("hour_of_day") <= 20)
                 & (col("day_of_week") < 5), 1).otherwise(0)
    late_night = when((col("hour_of_day") <= 6)
                      | (col("hour_of_day") >= 20), 1).otherwise(0)
    return (data.withColumn("night", night)
                .withColumn("late_night", late_night))


def _manhattan(lon1, lat1, lon2, lat2):
    return F.abs(lat2 - lat1) + F.abs(lon2 - lon1)


def add_distance_features(data):
    ny = (-74.0063889, 40.7141667)
    jfk = (-73.7822222222, 40.6441666667)
    ewr = (-74.175, 40.69)
    lgr = (-73.87, 40.77)
    data = (data
            .withColumn("abs_diff_longitude",
                        F.abs(col("dropoff_longitude") - col("pickup_longitude")))
            .withColumn("abs_diff_latitude",
                        F.abs(col("dropoff_latitude") - col("pickup_latitude"))))
    data = data.withColumn("manhattan",
                           col("abs_diff_latitude") + col("abs_diff_longitude"))
    for name, (lon, lat) in (("jfk", jfk), ("ewr", ewr), ("lgr", lgr),
                             ("downtown", ny)):
        data = data.withColumn(
            f"pickup_distance_{name}",
            _manhattan(col("pickup_longitude"), col("pickup_latitude"),
                       lit(lon), lit(lat)))
        data = data.withColumn(
            f"dropoff_distance_{name}",
            _manhattan(col("dropoff_longitude"), col("dropoff_latitude"),
                       lit(lon), lit(lat)))
    return data


def drop_columns(data):
    return data.drop("pickup_datetime")


def nyc_taxi_preprocess(data):
    data = clean_up(data)
    data = add_time_features(data)
    data = add_distance_features(data)
    return drop_columns(data)


def feature_columns(df):
    return [c for c in df.columns if c != LABEL]
