"""End-to-end NYCTaxi fare regression — the port of the reference's headline
example (examples/pytorch_nyctaxi.py): CSV → distributed feature ETL on CPU
actors → recoverable Arrow handoff → pjit-compiled MLP training on TPU.

Run: python examples/nyctaxi_mlp.py [--rows 100000] [--epochs 5]

``--num-workers N`` (N>1) trains as a gang of N processes under one
``jax.distributed`` mesh — the reference's multi-worker Ray Train path
(ScalingConfig(num_workers), torch/estimator.py:312-356). On a TPU pod this is
one process per host; on CPU it demonstrates the same code path with virtual
devices.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import optax

import raydp_tpu
from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
from raydp_tpu.models import NYCTaxiModel
from raydp_tpu.train import FlaxEstimator


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--num-executors", type=int, default=2)
    ap.add_argument("--num-workers", type=int, default=1,
                    help=">1 trains as a jax.distributed process gang")
    ap.add_argument("--csv", default=None)
    ap.add_argument("--trace", action="store_true",
                    help="collect a merged causal chrome trace + metrics "
                         "dump before teardown (doc/observability.md)")
    args = ap.parse_args()

    csv_path = args.csv
    if csv_path is None:
        from generate_nyctaxi import generate
        csv_path = os.path.join(tempfile.mkdtemp(), "nyctaxi.csv")
        generate(args.rows).to_csv(csv_path, index=False)

    session = raydp_tpu.init(
        "nyctaxi", num_executors=args.num_executors, executor_cores=1,
        executor_memory="1GB")
    try:
        data = session.read.csv(csv_path, num_partitions=args.num_executors * 2)
        data = nyc_taxi_preprocess(data)
        train_df, test_df = data.randomSplit([0.9, 0.1], seed=0)
        features = feature_columns(data)
        print(f"{len(features)} features: {features}")

        estimator = FlaxEstimator(
            model=NYCTaxiModel(),
            optimizer=optax.adam(1e-3),
            loss="smooth_l1",
            feature_columns=features,
            label_column=LABEL,
            batch_size=args.batch_size,
            num_epochs=args.epochs,
            metrics=["mae", "mse"],
        )
        result = estimator.fit_on_frame(train_df, test_df,
                                        num_workers=args.num_workers)
        for row in result.history:
            print(row)
        if args.trace:
            # collect BEFORE teardown: dead actors' span lanes are lost
            from raydp_tpu import metrics, profiler
            path = profiler.collect_chrome_trace()
            print(f"chrome trace: {path} ({path.flow_events} flow events, "
                  f"{path.actors} actor lanes, "
                  f"{path.skipped_actors} skipped)")
            print(f"metrics dump: {metrics.dump()}")
    finally:
        raydp_tpu.stop()


if __name__ == "__main__":
    main()
