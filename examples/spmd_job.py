"""Gang-SPMD job example — the MPI-pillar analogue (reference doc/mpi.md,
mpi/mpi_job.py): gang-start N rank processes under one global ``jax.distributed``
mesh, broadcast functions, gather world-size results, and read ETL output from
the object store inside the ranks.

    python examples/spmd_job.py [--world-size 2]

Runs on CPU devices by default so it works anywhere; on a TPU pod the same
code runs one rank per host and the collectives ride ICI.
"""

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.dirname(_HERE))


def global_mean_step(ctx):
    """Each rank contributes its devices; XLA inserts the cross-rank reduce."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devices = jax.devices()              # GLOBAL devices across the gang
    mesh = Mesh(devices, ("i",))
    x = jnp.arange(len(devices), dtype=jnp.float32) + 1.0
    mean = jax.jit(lambda v: v.mean(),
                   in_shardings=NamedSharding(mesh, PartitionSpec("i")),
                   out_shardings=NamedSharding(mesh, PartitionSpec()))(x)
    return {"rank": ctx.rank, "n_global_devices": len(devices),
            "global_mean": float(mean)}


def count_rows(payload):
    """A closure over a portable dataset handle: every rank re-opens the
    dataset from the object store (parity: each MPI rank joins Ray and reads
    the data plane, mpi_worker.py:159-160)."""

    def _fn(ctx):
        from raydp_tpu.data.dataset import DistributedDataset

        ds = DistributedDataset.from_portable(payload)
        # each rank counts a round-robin share of the blocks
        mine = [i for i in range(ds.num_blocks())
                if i % ctx.world_size == ctx.rank]
        return sum(ds.get_block(i).num_rows for i in mine)

    return _fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--world-size", type=int, default=2)
    args = ap.parse_args()

    import raydp_tpu
    from raydp_tpu.data import from_frame
    from raydp_tpu.spmd import create_spmd_job
    from generate_nyctaxi import generate

    session = raydp_tpu.init("spmd-example", num_executors=2,
                             executor_cores=1, executor_memory="512MB")
    try:
        import tempfile
        csv = os.path.join(tempfile.mkdtemp(prefix="rdt-spmd-"), "taxi.csv")
        generate(20_000).to_csv(csv, index=False)
        df = session.read.csv(csv, num_partitions=4)
        ds = from_frame(df)
        payload = ds.portable()

        job = create_spmd_job("example", args.world_size,
                              jax_distributed=True)
        job.start()
        try:
            results = job.run(global_mean_step, timeout=300)
            for r in results:
                print(f"rank {r['rank']}: {r['n_global_devices']} global "
                      f"devices, mean={r['global_mean']}")

            counts = job.run(count_rows(payload), timeout=300)
            print(f"rows counted across the gang: {sum(counts)} "
                  f"(per-rank {counts})")
            assert sum(counts) == df.count()
        finally:
            job.stop()
    finally:
        raydp_tpu.stop()


if __name__ == "__main__":
    main()
