"""End-to-end data-processing tutorial pipeline (healthcare stroke shape).

Parity: the reference's tutorials walk a healthcare stroke CSV through Spark
preprocessing into estimator training on one cluster
(``/root/reference/tutorials/pytorch_example.ipynb`` +
``tutorials/dataset/healthcare-dataset-stroke-data.csv``). This is the same
pipeline on the TPU-native stack, and the companion document
``doc/tutorial_data_processing.md`` narrates it step by step: every code block
there is lifted from this file, which CI runs.

Run: ``python examples/stroke_pipeline.py [--rows 6000] [--epochs 6]``
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

import numpy as np
import pandas as pd


def generate_stroke(rows: int, seed: int = 11) -> pd.DataFrame:
    """A stroke-dataset-shaped table (same columns as the reference CSV),
    generated because this environment has no egress. `bmi` has missing
    values and `smoking_status` an Unknown level, like the original."""
    rng = np.random.RandomState(seed)
    age = np.clip(rng.normal(45, 22, rows), 1, 95).round(0)
    hypertension = (rng.random_sample(rows) < 0.10 + 0.2 * (age > 60)) \
        .astype(np.int64)
    heart_disease = (rng.random_sample(rows) < 0.04 + 0.12 * (age > 65)) \
        .astype(np.int64)
    glucose = np.clip(rng.gamma(6.0, 18.0, rows), 55, 280).round(2)
    bmi = np.clip(rng.normal(28.5, 7.5, rows), 12, 60).round(1)
    logit = (-5.2 + 0.055 * (age - 45) + 0.9 * hypertension
             + 0.8 * heart_disease + 0.008 * (glucose - 110)
             + rng.normal(0, 0.6, rows))
    stroke = (rng.random_sample(rows) < 1 / (1 + np.exp(-logit))) \
        .astype(np.int64)
    bmi_missing = rng.random_sample(rows) < 0.04
    return pd.DataFrame({
        "id": np.arange(1, rows + 1),
        "gender": rng.choice(["Male", "Female"], rows, p=[0.41, 0.59]),
        "age": age,
        "hypertension": hypertension,
        "heart_disease": heart_disease,
        "ever_married": rng.choice(["Yes", "No"], rows, p=[0.66, 0.34]),
        "work_type": rng.choice(
            ["Private", "Self-employed", "Govt_job", "children"],
            rows, p=[0.62, 0.16, 0.13, 0.09]),
        "Residence_type": rng.choice(["Urban", "Rural"], rows),
        "avg_glucose_level": glucose,
        "bmi": np.where(bmi_missing, np.nan, bmi),
        "smoking_status": rng.choice(
            ["never smoked", "formerly smoked", "smokes", "Unknown"],
            rows, p=[0.37, 0.17, 0.16, 0.30]),
        "stroke": stroke,
    })


FEATURES = ["age", "hypertension", "heart_disease", "avg_glucose_level",
            "bmi", "is_male", "is_married", "is_urban",
            "work_private", "work_self", "smokes", "smoked_formerly"]
LABEL = "stroke"


def preprocess(df):
    """The tutorial's transformation chapter: impute, filter, encode."""
    from raydp_tpu.etl.expressions import col

    df = df.fillna(28.5, subset=["bmi"])          # median-BMI imputation
    df = df.filter(col("age") >= 2)               # drop infant rows
    df = (df
          .withColumn("is_male", col("gender") == "Male")
          .withColumn("is_married", col("ever_married") == "Yes")
          .withColumn("is_urban", col("Residence_type") == "Urban")
          .withColumn("work_private", col("work_type") == "Private")
          .withColumn("work_self", col("work_type") == "Self-employed")
          .withColumn("smokes", col("smoking_status") == "smokes")
          .withColumn("smoked_formerly",
                      col("smoking_status") == "formerly smoked"))
    return df.select(LABEL, *FEATURES)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=6000)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=256)
    args = ap.parse_args()

    import optax

    import raydp_tpu
    from raydp_tpu.data import from_frame
    from raydp_tpu.models import MLP
    from raydp_tpu.train import FlaxEstimator
    from raydp_tpu.utils import random_split

    csv_path = os.path.join(tempfile.mkdtemp(prefix="rdt-stroke-"),
                            "stroke.csv")
    generate_stroke(args.rows).to_csv(csv_path, index=False)

    session = raydp_tpu.init("stroke", num_executors=2, executor_cores=1,
                             executor_memory="512MB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)

        # -- inspect (tutorial chapter 2) ---------------------------------
        from raydp_tpu.etl import functions as F

        n = data.count()
        by_smoking = (data.groupBy("smoking_status")
                      .agg(F.mean("stroke").alias("stroke_rate"))
                      .to_pandas())
        print(f"{n} rows; stroke rate by smoking status:")
        print(by_smoking.to_string(index=False))

        # -- transform (chapter 3) ----------------------------------------
        data = preprocess(data)
        train_df, test_df = random_split(data, [0.8, 0.2], seed=0)

        # -- hand off to training (chapter 4) ------------------------------
        train_ds, test_ds = from_frame(train_df), from_frame(test_df)
        est = FlaxEstimator(
            model=MLP(features=(64, 32, 1), use_batch_norm=False),
            optimizer=optax.adam(1e-3),
            loss="bce_with_logits",
            feature_columns=FEATURES,
            label_column=LABEL,
            batch_size=args.batch_size,
            num_epochs=args.epochs,
            seed=0,
        )
        result = est.fit(train_ds, test_ds)
        last = result.history[-1]
        print(f"final: train_loss={last['train_loss']:.4f} "
              f"eval_loss={last['eval_loss']:.4f}")
        # the loss must actually improve over training
        if not last["train_loss"] < result.history[0]["train_loss"]:
            print("FAILED: loss did not decrease", file=sys.stderr)
            return 1
        return 0
    finally:
        raydp_tpu.stop()


if __name__ == "__main__":
    raise SystemExit(main())
