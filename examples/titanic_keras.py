"""Titanic-style binary classification on the Keras estimator path.

Parity: the reference's classification on-ramp
(``/root/reference/examples/tensorflow_titanic.ipynb``): load a Titanic-shaped
passenger table, clean and encode it with the distributed ETL engine, then
train a Keras classifier through :class:`raydp_tpu.train.KerasEstimator`
(binary cross-entropy + accuracy), exactly the estimator flow the notebook
runs through its TFEstimator.

The passenger manifest is generated synthetically (this environment has no
egress) with the classic dataset's schema and survival structure — sex, class
and age drive the outcome — so the model has real signal to learn: expect
validation accuracy well above the 0.62 majority-class floor.

Run: ``python examples/titanic_keras.py [--rows 2000] [--epochs 8]``
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

import numpy as np
import pandas as pd

os.environ.setdefault("KERAS_BACKEND", "jax")


def generate_titanic(rows: int, seed: int = 7) -> pd.DataFrame:
    """A Titanic-shaped manifest whose survival follows the classic data's
    dominant effects (sex >> class > age), with noise and missing ages."""
    rng = np.random.RandomState(seed)
    pclass = rng.choice([1, 2, 3], size=rows, p=[0.24, 0.21, 0.55])
    sex = rng.choice(["male", "female"], size=rows, p=[0.65, 0.35])
    age = np.clip(rng.normal(29.7, 14.5, size=rows), 0.4, 80.0).round(1)
    sibsp = rng.poisson(0.5, size=rows)
    parch = rng.poisson(0.4, size=rows)
    fare = np.where(pclass == 1, rng.gamma(3.0, 28.0, rows),
                    np.where(pclass == 2, rng.gamma(3.0, 7.0, rows),
                             rng.gamma(2.0, 7.0, rows))).round(2)
    embarked = rng.choice(["S", "C", "Q"], size=rows, p=[0.72, 0.19, 0.09])

    logit = (-0.9
             + 2.6 * (sex == "female")
             + 0.95 * (pclass == 1) + 0.45 * (pclass == 2)
             - 0.018 * (age - 29.7)
             - 0.18 * np.maximum(sibsp + parch - 1, 0)
             + rng.normal(0.0, 0.8, size=rows))
    survived = (rng.random_sample(rows) < 1 / (1 + np.exp(-logit))).astype(
        np.int64)

    age_missing = rng.random_sample(rows) < 0.2  # like the real manifest
    return pd.DataFrame({
        "PassengerId": np.arange(1, rows + 1),
        "Survived": survived,
        "Pclass": pclass,
        "Sex": sex,
        "Age": np.where(age_missing, np.nan, age),
        "SibSp": sibsp,
        "Parch": parch,
        "Fare": fare,
        "Embarked": embarked,
    })


FEATURES = ["pclass_1", "pclass_2", "is_female", "age", "sibsp", "parch",
            "fare", "embarked_c", "embarked_q"]
LABEL = "Survived"


def preprocess(df):
    """Distributed cleanup + encoding (the notebook's pandas-on-Spark cell,
    expressed on the ETL engine): impute Age, binary/one-hot encode the
    categoricals, drop identifiers."""
    from raydp_tpu.etl.expressions import col

    df = df.fillna(29.7, subset=["Age"])  # median-age imputation
    df = (df
          .withColumn("is_female", col("Sex") == "female")
          .withColumn("pclass_1", col("Pclass") == 1)
          .withColumn("pclass_2", col("Pclass") == 2)
          .withColumn("embarked_c", col("Embarked") == "C")
          .withColumn("embarked_q", col("Embarked") == "Q")
          # standardize the numeric columns: unscaled age/fare dominate the
          # gradient and stall the small MLP
          .withColumn("age", (col("Age") - 29.7) / 14.5)
          .withColumn("fare", (col("Fare") - 30.0) / 40.0)
          .withColumn("sibsp", col("SibSp") / 2.0)
          .withColumn("parch", col("Parch") / 2.0))
    return df.select(LABEL, *FEATURES)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=2000)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    import raydp_tpu
    from raydp_tpu.train import KerasEstimator
    from raydp_tpu.utils import random_split

    csv_path = os.path.join(tempfile.mkdtemp(prefix="rdt-titanic-"),
                            "titanic.csv")
    generate_titanic(args.rows).to_csv(csv_path, index=False)

    session = raydp_tpu.init("titanic", num_executors=2, executor_cores=1,
                             executor_memory="512MB")
    try:
        data = session.read.csv(csv_path, num_partitions=4)
        data = preprocess(data)
        train_df, test_df = random_split(data, [0.8, 0.2], seed=0)

        def build_model():
            import keras
            return keras.Sequential([
                keras.layers.Input(shape=(len(FEATURES),)),
                keras.layers.Dense(32, activation="relu"),
                keras.layers.Dense(16, activation="relu"),
                keras.layers.Dense(1, activation="sigmoid"),
            ])

        est = KerasEstimator(
            model_builder=build_model,
            optimizer="adam",
            loss="binary_crossentropy",
            metrics=["accuracy"],
            feature_columns=FEATURES,
            label_column=LABEL,
            batch_size=args.batch_size,
            num_epochs=args.epochs,
            seed=0,
        )
        result = est.fit_on_frame(train_df, test_df)
        last = result.history[-1]
        print(f"final: loss={last['loss']:.4f} "
              f"acc={last.get('binary_accuracy', last.get('accuracy')):.4f} "
              f"val_acc={last.get('val_binary_accuracy', last.get('val_accuracy')):.4f}")

        val_acc = last.get("val_binary_accuracy", last.get("val_accuracy"))
        if val_acc is None or val_acc < 0.70:
            print("FAILED: validation accuracy below 0.70", file=sys.stderr)
            return 1
        # sanity: the model actually discriminates — sex is the loudest signal
        model = est.get_model()
        # rows in FEATURES order, numeric columns pre-standardized as above
        female_1st = np.array([[1, 0, 1, 0.0, 0, 0, 1.25, 1, 0]], np.float32)
        male_3rd = np.array([[0, 0, 0, 0.0, 0, 0, -0.55, 0, 0]], np.float32)
        p_f = float(model.predict(female_1st, verbose=0)[0, 0])
        p_m = float(model.predict(male_3rd, verbose=0)[0, 0])
        print(f"P(survive | 1st-class female) = {p_f:.3f}, "
              f"P(survive | 3rd-class male) = {p_m:.3f}")
        if not p_f > p_m:
            print("FAILED: survival ordering wrong", file=sys.stderr)
            return 1
        return 0
    finally:
        raydp_tpu.stop()


if __name__ == "__main__":
    raise SystemExit(main())
