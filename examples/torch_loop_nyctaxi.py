"""NYCTaxi with a user-owned torch training loop over the data-plane bridge.

The reference ships bring-your-own-loop examples where the framework only
provides the data plane and the user writes the torch loop (horovod_nyctaxi.py,
raytrain_nyctaxi.py). This is that story here: distributed feature ETL on CPU
actors → ``to_torch_dataset`` → a stock ``DataLoader`` + hand-written
torch loop. Training runs on torch-CPU — the point is the migration path for
an existing torch codebase; TPU training should use ``FlaxEstimator``
(see nyctaxi_mlp.py).

Run: python examples/torch_loop_nyctaxi.py [--rows 50000] [--epochs 3]
      [--loader-workers 2]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=50_000)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--loader-workers", type=int, default=0,
                    help="DataLoader num_workers (the bridge stripes batches "
                         "across workers)")
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    import torch
    from torch import nn

    import raydp_tpu
    from generate_nyctaxi import generate
    from nyctaxi_features import LABEL, feature_columns, nyc_taxi_preprocess
    from raydp_tpu.data import from_frame, to_torch_dataset

    csv_path = os.path.join(tempfile.mkdtemp(prefix="rdt-ex-"), "nyctaxi.csv")
    generate(args.rows).to_csv(csv_path, index=False)

    session = raydp_tpu.init("torch-loop", num_executors=2, executor_cores=2,
                             executor_memory="1GB")
    try:
        df = nyc_taxi_preprocess(session.read.csv(csv_path, num_partitions=4))
        features = feature_columns(df)
        train_df, eval_df = df.randomSplit([0.9, 0.1], seed=0)
        train_ds, eval_ds = from_frame(train_df), from_frame(eval_df)

        train = to_torch_dataset(
            train_ds, feature_columns=features, label_column=LABEL,
            batch_size=args.batch_size, shuffle=True)
        evaluate = to_torch_dataset(
            eval_ds, feature_columns=features, label_column=LABEL,
            batch_size=args.batch_size)
        loader = torch.utils.data.DataLoader(
            train, batch_size=None, num_workers=args.loader_workers)

        model = nn.Sequential(
            nn.Linear(len(features), 256), nn.ReLU(), nn.BatchNorm1d(256),
            nn.Linear(256, 64), nn.ReLU(), nn.BatchNorm1d(64),
            nn.Linear(64, 1))
        opt = torch.optim.Adam(model.parameters(), lr=args.lr)
        loss_fn = nn.SmoothL1Loss()

        for epoch in range(args.epochs):
            model.train()
            t0, total, steps = time.perf_counter(), 0.0, 0
            for feats, labels in loader:
                opt.zero_grad()
                loss = loss_fn(model(feats).squeeze(-1), labels)
                loss.backward()
                opt.step()
                total += float(loss)
                steps += 1
            model.eval()
            with torch.no_grad():
                esum, ecnt = 0.0, 0
                for feats, labels in evaluate:
                    esum += float(loss_fn(model(feats).squeeze(-1), labels)) \
                        * len(labels)
                    ecnt += len(labels)
            print({"epoch": epoch, "train_loss": round(total / steps, 5),
                   "eval_loss": round(esum / max(ecnt, 1), 5),
                   "epoch_time_s": round(time.perf_counter() - t0, 2)})
    finally:
        raydp_tpu.stop()


if __name__ == "__main__":
    main()
