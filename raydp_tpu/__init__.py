"""raydp_tpu — a TPU-native data + AI pipeline framework.

Capability parity target: pang-wu/raydp ("Spark on Ray"). Where the reference runs
Spark executors as Ray actors and trains through Ray Train / torch.distributed
(reference: python/raydp/__init__.py:18-22, context.py:182-254), this framework runs
an Arrow-native distributed ETL engine and JAX/XLA TPU training on one built-in actor
runtime, exchanging data as Arrow record batches through a shared-memory object store
and feeding device-sharded ``jax.Array``s over a ``jax.sharding.Mesh``.

Public surface (mirrors the reference's ``raydp.init_spark`` / ``raydp.stop_spark``):

    import raydp_tpu
    session = raydp_tpu.init(app_name="nyc", num_executors=2,
                             executor_cores=1, executor_memory="1GB")
    df = session.read.csv("data.csv")
    ds = raydp_tpu.data.from_frame_recoverable(df)
"""

__version__ = "0.1.0"

from raydp_tpu.context import init, stop, active_session

__all__ = ["init", "stop", "active_session", "__version__"]
