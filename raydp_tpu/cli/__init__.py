"""Command-line entry points (parity: the reference's bin/raydp-submit)."""
