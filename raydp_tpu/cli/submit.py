"""``rdt-submit`` — non-inline job submission.

Parity: the reference's ``bin/raydp-submit`` + SparkSubmit fork (the fork's one
load-bearing change is accepting ``--master ray``, SparkSubmit.scala:231-240;
the wrapper assembles classpaths and forwards ``--conf``). Here there is no
JVM to assemble: the CLI packages the cluster configuration into the
environment and execs the user script in a child interpreter —
``raydp_tpu.init`` inside the script resolves any argument the script left at
its default from the submitted values (explicit arguments in code still win,
Spark's precedence). The child's exit code is propagated, and SIGINT/SIGTERM
forward to the child's process group.

    rdt-submit --num-executors 4 --executor-cores 2 \\
               --conf raydp.tpu.shuffle.partitions=16 train.py --epochs 3
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
from typing import List, Optional

ENV_SUBMIT = "RDT_SUBMIT_ARGS"


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="rdt-submit",
        description="Run a raydp_tpu script with cluster configuration "
                    "supplied at submit time (parity: bin/raydp-submit)")
    ap.add_argument("--name", default=None, help="application name override")
    ap.add_argument("--num-executors", type=int, default=None)
    ap.add_argument("--executor-cores", type=int, default=None)
    ap.add_argument("--executor-memory", default=None, help="e.g. 2GB")
    ap.add_argument("--placement-group-strategy", default=None,
                    choices=["PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"])
    ap.add_argument("--conf", action="append", default=[], metavar="K=V",
                    help="config entry (repeatable), e.g. raydp.tpu.x=y")
    ap.add_argument("--py-files", default=None, metavar="PATHS",
                    help="comma-separated .py files, .zip archives or "
                         "directories added to the driver's import path "
                         "(parity: spark-submit --py-files through "
                         "bin/raydp-submit)")
    ap.add_argument("--env", action="append", default=[], metavar="K=V",
                    help="extra environment for the script (repeatable)")
    ap.add_argument("script", help="python script to run")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed through to the script")
    return ap


def _parse_kv(items: List[str], flag: str) -> dict:
    out = {}
    for item in items:
        key, sep, value = item.partition("=")
        if not sep or not key:
            raise SystemExit(f"rdt-submit: {flag} expects K=V, got {item!r}")
        out[key] = value
    return out


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if not os.path.exists(args.script):
        raise SystemExit(f"rdt-submit: script not found: {args.script}")

    submit = {
        "app_name": args.name,
        "num_executors": args.num_executors,
        "executor_cores": args.executor_cores,
        "executor_memory": args.executor_memory,
        "placement_group_strategy": args.placement_group_strategy,
        "configs": _parse_kv(args.conf, "--conf"),
    }
    env = dict(os.environ)
    env.update(_parse_kv(args.env, "--env"))
    stage_dir = None
    if args.py_files:
        # Bare .py files are staged into one scratch dir and only that dir
        # goes on the path — putting a file's parent dir up would expose
        # every sibling module (and can shadow installed packages), which
        # spark-submit's --py-files never does. Zips and directories go on
        # the path directly.
        entries = []
        staged = {}  # basename → source path; a silent overwrite would make
        #              the LAST listed file win, inverting path precedence
        try:
            for raw in args.py_files.split(","):
                raw = raw.strip()
                if not raw:  # trailing/doubled comma must not resolve to cwd
                    continue
                p = os.path.abspath(raw)
                if not os.path.exists(p):
                    raise SystemExit(
                        f"rdt-submit: --py-files entry not found: {p}")
                if p.endswith(".py"):
                    base = os.path.basename(p)
                    prev = staged.get(base)
                    if prev is not None and prev != p:
                        raise SystemExit(
                            f"rdt-submit: --py-files lists two files named "
                            f"{base!r} ({prev} and {p}); module names must "
                            "be unique")
                    if stage_dir is None:
                        stage_dir = tempfile.mkdtemp(prefix="rdt-pyfiles-")
                        entries.append(stage_dir)
                    staged[base] = p
                    shutil.copy2(p, stage_dir)
                else:
                    entries.append(p)
        except BaseException:
            # a bad LATER entry must not leak the dir staged so far (the
            # normal-path cleanup lives in the wait() finally below, which
            # is never reached on a staging abort)
            if stage_dir is not None:
                shutil.rmtree(stage_dir, ignore_errors=True)
            raise
        seen = dict.fromkeys(entries)  # dedupe, keep order
        env["PYTHONPATH"] = os.pathsep.join(
            list(seen) + [env.get("PYTHONPATH", "")]).rstrip(os.pathsep)
    env[ENV_SUBMIT] = json.dumps(
        {k: v for k, v in submit.items() if v not in (None, {})})

    proc = subprocess.Popen(
        [sys.executable, args.script] + list(args.script_args),
        env=env, start_new_session=True)

    def _forward(signum, _frame):
        try:
            os.killpg(proc.pid, signum)
        except (ProcessLookupError, PermissionError):
            pass

    old = {s: signal.signal(s, _forward)
           for s in (signal.SIGINT, signal.SIGTERM)}
    try:
        return proc.wait()
    finally:
        for s, handler in old.items():
            signal.signal(s, handler)
        if stage_dir is not None:
            shutil.rmtree(stage_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
