"""Generic cluster ABCs: the external-engine plug surface.

Parity: the reference keeps its Spark bring-up behind engine-agnostic ABCs so
other data engines can ride the same actor substrate ("such as SparkCluster,
FlinkCluster" — reference services.py:22-90 ``Cluster``/``ClusterMaster``,
implemented by ``SparkCluster``/``RayClusterMaster``). This module is that
surface for the TPU build: a master-service + worker-gang lifecycle contract
over the actor runtime, with the built-in ETL engine expressed through it
(:class:`EtlCluster`, which :class:`~raydp_tpu.etl.session.Session` drives) —
so a different engine plugs in by subclassing ``Cluster`` exactly as the
reference intends, inheriting supervised actors, placement, and the
distributed object store without touching the session machinery.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, List, Optional

from raydp_tpu.log import get_logger
from raydp_tpu.runtime.actor import ActorHandle

logger = get_logger("cluster")


class ClusterMaster(ABC):
    """The master service of an engine (reference services.py:74-90)."""

    @abstractmethod
    def start_up(self) -> None:
        """Create/boot the master service."""

    @abstractmethod
    def get_master_url(self) -> str:
        """How workers address the master (e.g. a named-actor name)."""

    @abstractmethod
    def get_host(self) -> str:
        """The host the master runs on."""

    @abstractmethod
    def stop(self) -> None:
        """Tear the master service down."""


class Cluster(ABC):
    """A master + worker-gang lifecycle on the actor runtime
    (reference services.py:22-72).

    Subclasses implement ``_set_up_master`` / ``_set_up_worker`` /
    ``get_cluster_url`` / ``stop``; ``add_worker`` wraps worker bring-up with
    the reference's fail-safe contract (a failed worker tears the cluster
    down rather than leaking a half-started gang).
    """

    def __init__(self, master_resources_requirement: Optional[Dict[str, float]]):
        # the master lives beside the driver; workers are counted
        self._num_nodes = 0
        self._set_up_master(master_resources_requirement or {}, {})

    @abstractmethod
    def _set_up_master(self, resources: Dict[str, float],
                       kwargs: Dict[Any, Any]) -> None:
        """Set up the master service."""

    def add_worker(self, resources_requirement: Dict[str, float],
                   **kwargs: Any) -> None:
        """Add one worker; on failure stop the whole cluster and re-raise
        (reference services.py:40-52)."""
        try:
            self._set_up_worker(resources_requirement, kwargs)
            self._num_nodes += 1
        except BaseException:
            self.stop()
            raise

    @abstractmethod
    def _set_up_worker(self, resources: Dict[str, float],
                       kwargs: Dict[str, Any]) -> None:
        """Set up one worker service."""

    @property
    def num_workers(self) -> int:
        return self._num_nodes

    @abstractmethod
    def get_cluster_url(self) -> str:
        """The cluster address workers/clients connect to."""

    @abstractmethod
    def stop(self) -> None:
        """Stop every service of this cluster."""


class EtlClusterMaster(ClusterMaster):
    """The built-in engine's master: one named EtlMaster actor (the role
    RayClusterMaster plays for the reference's Spark engine)."""

    def __init__(self, app_name: str, resources: Dict[str, float],
                 max_concurrency: int = 8):
        self._app_name = app_name
        self._resources = dict(resources)
        self._max_concurrency = max_concurrency
        self.handle: Optional[ActorHandle] = None

    @property
    def name(self) -> str:
        return f"{self._app_name}_MASTER"

    def start_up(self) -> None:
        from raydp_tpu.etl.master import EtlMaster
        from raydp_tpu.runtime import get_runtime

        self.handle = get_runtime().create_actor(
            EtlMaster, (self._app_name,), name=self.name,
            resources=self._resources, max_restarts=0,
            max_concurrency=self._max_concurrency)

    def get_master_url(self) -> str:
        return self.name  # named-actor registry IS the address space

    def get_host(self) -> str:
        from raydp_tpu.runtime import get_runtime
        rt = get_runtime()
        rec = getattr(rt, "records", {}).get(
            self.handle.actor_id) if self.handle else None
        return rec.address[0] if rec is not None and rec.address else "127.0.0.1"

    def stop(self) -> None:
        if self.handle is not None:
            try:
                self.handle.kill(no_restart=True)
            except Exception:
                pass
            self.handle = None


class EtlCluster(Cluster):
    """The built-in ETL engine expressed through the generic ABCs; the
    Session drives its lifecycle through this object, so an external engine
    subclassing :class:`Cluster` slots into the same machinery."""

    def __init__(self, app_name: str,
                 master_resources: Optional[Dict[str, float]] = None):
        self.app_name = app_name
        self.master: Optional[EtlClusterMaster] = None
        self.workers: List[ActorHandle] = []
        self._worker_index = 0
        super().__init__(master_resources)

    # -- master ---------------------------------------------------------------
    def _set_up_master(self, resources: Dict[str, float],
                       kwargs: Dict[Any, Any]) -> None:
        self.master = EtlClusterMaster(self.app_name, resources)
        self.master.start_up()

    # -- workers --------------------------------------------------------------
    def _set_up_worker(self, resources: Dict[str, float],
                       kwargs: Dict[str, Any]) -> None:
        from raydp_tpu.etl.executor import EtlExecutor
        from raydp_tpu.runtime import get_runtime

        i = self._worker_index
        self._worker_index += 1
        handle = get_runtime().create_actor(
            EtlExecutor, (self.master.name,),
            name=f"rdt-executor-{self.app_name}-{i}",
            resources=dict(resources),
            max_restarts=kwargs.get("max_restarts", -1),
            max_concurrency=kwargs.get("max_concurrency", 2),
            env={"JAX_PLATFORMS": "cpu"},  # ETL never grabs TPU chips
            placement_group=kwargs.get("placement_group"),
            bundle_index=kwargs.get("bundle_index"),
            block=kwargs.get("block", True),
        )
        self.workers.append(handle)

    def remove_worker(self, handle: Optional[ActorHandle] = None
                      ) -> Optional[ActorHandle]:
        """Shrink by one — dynamic allocation's kill side. ``handle`` picks
        a specific worker (the graceful-drain reap path); default is the
        newest."""
        if not self.workers:
            return None
        if handle is None:
            handle = self.workers.pop()
        elif handle in self.workers:
            self.workers.remove(handle)
        else:
            return None
        self._num_nodes = max(0, self._num_nodes - 1)
        try:
            handle.kill(no_restart=True)
        except Exception:
            pass
        return handle

    def get_cluster_url(self) -> str:
        return self.master.get_master_url() if self.master else ""

    def stop(self, cleanup_master: bool = True) -> None:
        for handle in self.workers:
            try:
                handle.kill(no_restart=True)
            except Exception:
                pass
        self.workers = []
        self._num_nodes = 0
        if cleanup_master and self.master is not None:
            self.master.stop()
            self.master = None
