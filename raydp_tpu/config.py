"""Configuration namespace for raydp_tpu.

The reference concentrates every tunable in a flat string-keyed conf under the
``spark.ray.*`` namespace (reference: core/raydp-main/src/main/java/org/apache/spark/
raydp/SparkOnRayConfigs.java:4-127, consumed at context.py:119-140 and
ray_cluster.py:126-189). We keep the same shape — a flat ``str -> str`` conf with a
``raydp.tpu.*`` namespace and typed getters — so user programs can pass opaque
configs through ``init(configs={...})`` exactly like ``init_spark``.
"""

from __future__ import annotations

from typing import Dict, Optional

from raydp_tpu.utils import parse_memory_size

# -- config keys (parity with SparkOnRayConfigs.java) --------------------------------
NAMESPACE = "raydp.tpu"

# executor actor resources, e.g. raydp.tpu.executor.actor.resource.cpu = 1.5
EXECUTOR_ACTOR_RESOURCE_PREFIX = f"{NAMESPACE}.executor.actor.resource"
# master actor resources (SparkOnRayConfigs.java: spark.ray.raydp_spark_master.actor.resource.*)
MASTER_ACTOR_RESOURCE_PREFIX = f"{NAMESPACE}.master.actor.resource"
# per-fetch-task resources for the recoverable dataset reader
# (reference: dataset.py:195-200, spark.ray.raydp_recoverable_fetch.task.resource.*)
RECOVERABLE_FETCH_TASK_RESOURCE_PREFIX = f"{NAMESPACE}.recoverable_fetch.task.resource"

PLACEMENT_GROUP_KEY = f"{NAMESPACE}.placement_group"
PLACEMENT_GROUP_BUNDLE_INDEXES_KEY = f"{NAMESPACE}.bundle_indexes"

EXECUTOR_RESTARTS_KEY = f"{NAMESPACE}.executor.max_restarts"   # default -1 (infinite)
OBJECT_STORE_MEMORY_KEY = f"{NAMESPACE}.object_store.memory"
OBJECT_STORE_DIR_KEY = f"{NAMESPACE}.object_store.dir"
LOG_DIR_KEY = f"{NAMESPACE}.log.dir"
LOG_LEVEL_KEY = f"{NAMESPACE}.log.level"
SHUFFLE_PARTITIONS_KEY = f"{NAMESPACE}.sql.shuffle.partitions"
BATCH_MAX_ROWS_KEY = f"{NAMESPACE}.arrow.batch.max_rows"
HEARTBEAT_INTERVAL_S_KEY = f"{NAMESPACE}.failure.heartbeat_interval_s"
HEARTBEAT_TIMEOUT_S_KEY = f"{NAMESPACE}.failure.heartbeat_timeout_s"
TRACE_DIR_KEY = f"{NAMESPACE}.trace.dir"
NATIVE_OBJECT_STORE_KEY = f"{NAMESPACE}.object_store.native"   # use C++ store core
#: shared-memory budget before sealed objects LRU-spill to disk; defaults to
#: the arena size (plasma eviction parity). "0" disables spilling.
SPILL_BUDGET_KEY = f"{NAMESPACE}.object_store.shm_budget"
SPILL_DIR_KEY = f"{NAMESPACE}.object_store.spill_dir"

_DEFAULTS: Dict[str, str] = {
    EXECUTOR_RESTARTS_KEY: "-1",
    SHUFFLE_PARTITIONS_KEY: "8",
    BATCH_MAX_ROWS_KEY: "65536",
    HEARTBEAT_INTERVAL_S_KEY: "1.0",
    HEARTBEAT_TIMEOUT_S_KEY: "10.0",
    LOG_LEVEL_KEY: "INFO",
    NATIVE_OBJECT_STORE_KEY: "auto",
}


class Config:
    """Flat string conf with typed getters (shape parity with Spark's ``SparkConf``)."""

    def __init__(self, configs: Optional[Dict[str, str]] = None):
        self._conf: Dict[str, str] = dict(_DEFAULTS)
        if configs:
            for k, v in configs.items():
                self._conf[str(k)] = str(v)

    def set(self, key: str, value) -> "Config":
        self._conf[key] = str(value)
        return self

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._conf.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self._conf.get(key)
        return default if v is None else int(v)

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self._conf.get(key)
        return default if v is None else float(v)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._conf.get(key)
        if v is None:
            return default
        return v.strip().lower() in ("1", "true", "yes", "on")

    def get_memory(self, key: str, default: int = 0) -> int:
        v = self._conf.get(key)
        return default if v is None else parse_memory_size(v)

    def with_prefix(self, prefix: str) -> Dict[str, str]:
        """All entries under ``prefix.``, keyed by the suffix.

        Mirrors how the reference collects actor resources from
        ``spark.ray.raydp_spark_executor.actor.resource.*``
        (RayCoarseGrainedSchedulerBackend.scala:203-228).
        """
        p = prefix if prefix.endswith(".") else prefix + "."
        return {k[len(p):]: v for k, v in self._conf.items() if k.startswith(p)}

    def resource_map(self, prefix: str) -> Dict[str, float]:
        return {name: float(v) for name, v in self.with_prefix(prefix).items()}

    def items(self):
        return self._conf.items()

    def copy(self) -> "Config":
        c = Config()
        c._conf = dict(self._conf)
        return c
