"""Session lifecycle: ``raydp_tpu.init`` / ``raydp_tpu.stop``.

Parity with the reference's ``raydp.init_spark`` / ``raydp.stop_spark``
(context.py:182-254): a lock-guarded global singleton context, placement-group
pre-allocation of one ``{CPU, memory}`` bundle per executor, ordered teardown, and
``atexit`` cleanup (context.py:257). Instead of launching a JVM gateway and a Spark
driver, ``init`` boots the built-in actor runtime, creates the ETL master actor, and
gang-starts executor actors; the returned :class:`~raydp_tpu.etl.session.Session` is
the DataFrame entry point (the SparkSession analogue).
"""

from __future__ import annotations

import atexit
import threading
from typing import Dict, List, Optional, Union

from raydp_tpu import config as cfg
from raydp_tpu.config import Config
from raydp_tpu.log import get_logger
from raydp_tpu.utils import parse_memory_size

logger = get_logger("context")

_context_lock = threading.RLock()
_global_context: Optional["_Context"] = None


class _Context:
    """Holds the runtime + ETL session for one ``init()``...``stop()`` span."""

    def __init__(
        self,
        app_name: str,
        num_executors: int,
        executor_cores: int,
        executor_memory: Union[str, int],
        placement_group_strategy: Optional[str],
        configs: Optional[Dict[str, str]],
        virtual_nodes: Optional[List[Dict[str, float]]],
        address: Optional[str] = None,
    ):
        self.app_name = app_name
        self.num_executors = num_executors
        self.executor_cores = executor_cores
        self.executor_memory = parse_memory_size(executor_memory)
        self.placement_group_strategy = placement_group_strategy
        self.config = Config(configs)
        self.virtual_nodes = virtual_nodes
        self.address = address
        self.session = None
        self._placement_group = None
        self._kept_data = False  # a stop(cleanup_data=False) happened

    def get_or_create_session(self):
        if self.session is not None:
            return self.session
        from raydp_tpu.etl.session import Session
        from raydp_tpu.runtime import init_runtime

        if self.address is not None:
            # attach/client mode: join a standalone head's cluster instead of
            # booting an in-process runtime (parity: Ray-client mode,
            # reference conftest.py:77-140). Placement groups are created on
            # the HEAD's resource model over RPC, exactly like the
            # reference's pg pre-allocation under Ray client
            # (reference context.py:119-140).
            from raydp_tpu.runtime.client import ClientContext
            from raydp_tpu.runtime.head import adopt_runtime
            runtime = ClientContext(self.address)
            adopt_runtime(runtime)
            self._preallocate_group(runtime)
            self.session = Session(
                app_name=self.app_name,
                num_executors=self.num_executors,
                executor_cores=self.executor_cores,
                executor_memory=self.executor_memory,
                config=self.config,
                placement_group=self._placement_group,
            )
            self.session.start()
            return self.session

        runtime = init_runtime(config=self.config, virtual_nodes=self.virtual_nodes)
        self._preallocate_group(runtime)

        self.session = Session(
            app_name=self.app_name,
            num_executors=self.num_executors,
            executor_cores=self.executor_cores,
            executor_memory=self.executor_memory,
            config=self.config,
            placement_group=self._placement_group,
        )
        self.session.start()
        return self.session

    def _preallocate_group(self, runtime) -> None:
        """One {CPU, memory} bundle per executor (parity: context.py:119-140);
        works against the in-process ResourceManager and the client-mode RPC
        proxy alike."""
        if self.placement_group_strategy is None:
            return
        bundles = [
            {"CPU": float(self.executor_cores),
             "memory": float(self.executor_memory)}
            for _ in range(self.num_executors)
        ]
        group = runtime.resource_manager.create_group(
            bundles, self.placement_group_strategy)
        self._placement_group = group
        self.config.set(cfg.PLACEMENT_GROUP_KEY, group.group_id)
        self.config.set(
            cfg.PLACEMENT_GROUP_BUNDLE_INDEXES_KEY,
            ",".join(str(b.index) for b in group.bundles),
        )

    def stop(self, cleanup_data: bool = True) -> None:
        """Teardown order parity (context.py:152-169): master shutdown → session
        stop → remove placement group → runtime shutdown (unless data is kept)."""
        from raydp_tpu.runtime import get_runtime, runtime_initialized, shutdown_runtime

        self._kept_data = not cleanup_data
        if self.session is not None:
            self.session.stop(cleanup_data=cleanup_data)
            if cleanup_data:
                self.session = None
        if runtime_initialized():
            if self._placement_group is not None:
                get_runtime().resource_manager.remove_group(
                    self._placement_group.group_id)
                self._placement_group = None
            if cleanup_data:
                shutdown_runtime()


def _submit_overrides() -> Dict:
    """Configuration packaged by ``rdt-submit`` (parity: conf flowing from
    bin/raydp-submit into the session). Explicit ``init`` arguments win;
    submitted values fill anything the script left at its default."""
    import json

    from raydp_tpu import knobs

    raw = knobs.get_raw("RDT_SUBMIT_ARGS")
    if not raw:
        return {}
    try:
        return json.loads(raw)
    except ValueError:
        logger.warning("ignoring malformed RDT_SUBMIT_ARGS")
        return {}


def init(
    app_name: str,
    num_executors: Optional[int] = None,
    executor_cores: Optional[int] = None,
    executor_memory: Union[str, int, None] = None,
    placement_group_strategy: Optional[str] = None,
    configs: Optional[Dict[str, str]] = None,
    virtual_nodes: Optional[List[Dict[str, float]]] = None,
    address: Optional[str] = None,
):
    """Start the framework and return the ETL :class:`Session`.

    Signature parity with ``raydp.init_spark`` (context.py:182-254); defaults:
    1 executor × 1 core × 1GB. Under ``rdt-submit``, submitted values replace
    the defaults of any argument not set explicitly here. Extra,
    TPU-build-specific knob: ``virtual_nodes`` registers logical nodes to simulate
    a multi-host topology in tests (the reference's tests get this from
    ``ray.cluster_utils.Cluster``, test_spark_cluster.py:90-110).

    ``address="host:port"`` attaches to a standalone head
    (``python -m raydp_tpu.runtime.head --listen``) instead of booting an
    in-process runtime — the Ray-client-mode analogue. The head, its actors,
    and stored data outlive this driver; ``stop(cleanup_data=False)`` leaves
    even this session's master alive for the next driver to read.
    """
    # re-arm the fault plane from the CURRENT env: the process-local registry
    # caches RDT_FAULTS on first check(), so a spec exported between two
    # sessions of one driver process would otherwise never load for
    # driver-side sites (rpc.call, store.get) and silently inject nothing.
    # Rules armed via faults.inject() before init survive (only env rules
    # reload)
    from raydp_tpu import faults
    faults.reset()

    sub = _submit_overrides()
    app_name = app_name or sub.get("app_name") or "raydp-tpu"
    if num_executors is None:
        num_executors = int(sub.get("num_executors", 1))
    if executor_cores is None:
        executor_cores = int(sub.get("executor_cores", 1))
    if executor_memory is None:
        executor_memory = sub.get("executor_memory", "1GB")
    if placement_group_strategy is None:
        placement_group_strategy = sub.get("placement_group_strategy")
    if address is None:
        address = sub.get("address")
    merged_configs = dict(sub.get("configs", {}))
    merged_configs.update(configs or {})
    configs = merged_configs or None

    global _global_context
    with _context_lock:
        if _global_context is not None:
            raise RuntimeError("raydp_tpu is already initialized; call stop() first")
        try:
            _global_context = _Context(
                app_name, num_executors, executor_cores, executor_memory,
                placement_group_strategy, configs, virtual_nodes,
                address=address)
            return _global_context.get_or_create_session()
        except BaseException:
            if _global_context is not None:
                try:
                    _global_context.stop()
                finally:
                    _global_context = None
            raise


def stop(cleanup_data: bool = True) -> None:
    """Stop the session. With ``cleanup_data=False`` the object store (and any
    datasets whose ownership was transferred to the master) survives, parity with
    ``stop_spark(cleanup_data=False)`` (context.py:152-162, dataset.py:146-158)."""
    global _global_context
    with _context_lock:
        if _global_context is not None:
            try:
                _global_context.stop(cleanup_data)
            finally:
                if cleanup_data:
                    _global_context = None


def active_session():
    with _context_lock:
        return _global_context.session if _global_context is not None else None


def _atexit_stop() -> None:
    """Process-exit sweep. Honors an earlier explicit
    ``stop(cleanup_data=False)``: the implicit exit must NOT reap the master
    that call deliberately kept — in attach mode that master (and the data it
    owns on the standalone head) is exactly what the next driver reads
    (parity: ownership survives driver exit, reference dataset.py:137-158)."""
    global _global_context
    with _context_lock:
        ctx = _global_context
        if ctx is None:
            return
        try:
            if ctx._kept_data:
                from raydp_tpu.runtime import (
                    runtime_initialized, shutdown_runtime,
                )
                if runtime_initialized():
                    shutdown_runtime()  # client mode: detach only
            else:
                ctx.stop(True)
        except Exception:
            pass
        finally:
            _global_context = None


atexit.register(_atexit_stop)  # parity: context.py:257
