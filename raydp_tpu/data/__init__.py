"""raydp_tpu.data — the ETL↔training data plane.

Parity: the reference's L4 conversion layer (SURVEY.md §1) — Spark DataFrame ↔ Ray
Dataset through Arrow IPC in the object store (spark/dataset.py), including the
recoverable path (``from_spark_recoverable``/``release``, dataset.py:172-237), the
reverse ``to_spark`` path with master-held objects (dataset.py:239-313), and the
balanced per-rank sharding kernel (utils.py:149-222). The TPU-specific tail is
:mod:`feed`: Arrow blocks → pinned host numpy → ``jax.device_put`` with a
``NamedSharding`` so batches land already sharded over the mesh's data axis.
"""

from raydp_tpu.data.bridges import to_tf_dataset, to_torch_dataset
from raydp_tpu.data.dataset import (
    DistributedDataset,
    from_frame,
    from_frame_recoverable,
    release,
    to_frame,
)
from raydp_tpu.data.feed import DeviceEpochCache, DeviceFeed, ShardSpec

__all__ = [
    "DistributedDataset",
    "from_frame",
    "from_frame_recoverable",
    "release",
    "to_frame",
    "DeviceEpochCache",
    "DeviceFeed",
    "ShardSpec",
    "to_torch_dataset",
    "to_tf_dataset",
]
