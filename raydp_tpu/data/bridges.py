"""Framework bridges: a :class:`DistributedDataset` as a torch / tf.data feed.

Parity surface for reference users who train OUTSIDE the built-in estimators:
the reference hands its dataset to torch as an ``IterableDataset`` + prefetching
DataLoader (torch/torch_ml_dataset.py:30-110) and to TF via ``dataset.to_tf``
feeding ``model.fit`` (tf/estimator.py:179-199). Here both bridges sit on the
same host feed the estimators use (:class:`~raydp_tpu.data.feed.HostBatchIterator`
— decoded-block caching, within-block shuffling, balanced shard plans), so a
user migrating an external torch/TF training loop keeps the data-plane
semantics of the native path.

These bridges are HOST-side by design: they exist for foreign training loops.
TPU training should use the estimators (or :class:`DeviceFeed` /
:class:`DeviceEpochCache`), which place batches under the mesh sharding.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from raydp_tpu.data.feed import HostBatchIterator, ShardSpec, epoch_seed

__all__ = ["to_torch_dataset", "to_tf_dataset"]


def _columns_spec(feature_columns: Sequence[str], label_column: Optional[str],
                  feature_dtype, label_dtype):
    spec = {"features": (list(feature_columns), feature_dtype)}
    if label_column is not None:
        spec["label"] = (label_column, label_dtype)
    return spec


def _shard(ds, world_size: int, rank: int, shuffle: bool, seed: int):
    if world_size <= 1:
        return None
    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    plans = ds.split_shards(world_size, shuffle=shuffle, seed=seed)
    return ShardSpec(parts=plans[rank])


def to_torch_dataset(ds, feature_columns: Sequence[str],
                     label_column: Optional[str] = None,
                     batch_size: int = 64,
                     shuffle: bool = False,
                     seed: int = 0,
                     feature_dtype=np.float32,
                     label_dtype=np.float32,
                     drop_last: bool = False,
                     world_size: int = 1,
                     rank: int = 0):
    """The dataset as a ``torch.utils.data.IterableDataset`` of already-batched
    ``(features, label)`` CPU tensor pairs (``features`` alone without a
    ``label_column``).

    Mirrors the reference's ``TorchMLDataset`` contract
    (torch/torch_ml_dataset.py:30-67): iterable, optional shuffling, sized via
    ``len()``. Batches are cut here (pass the result to a ``DataLoader`` with
    ``batch_size=None``), so the balanced shard plan and decoded-block cache
    of the native feed apply unchanged; ``world_size``/``rank`` select one
    balanced shard for DDP-style consumers (``divide_blocks`` parity,
    reference utils.py:149-222).

    Determinism note: with multi-worker loaders the per-epoch shuffle signal
    is derived from torch's worker seeding convention (``info.seed -
    info.id`` = the loader's per-epoch base seed), so the shuffle order is
    reproducible across runs only when the ``DataLoader``'s ``generator`` is
    explicitly seeded; workers always AGREE within a run either way (the
    stripe split needs all workers on one order). A custom ``worker_init_fn``
    that reseeds torch does not break agreement, only cross-run
    reproducibility. The native ``DeviceFeed.set_epoch`` path has no such
    dependence.
    """
    import torch
    from torch.utils.data import IterableDataset

    shard = _shard(ds, world_size, rank, shuffle, seed)
    columns = _columns_spec(feature_columns, label_column,
                            feature_dtype, label_dtype)
    rows = shard.num_rows() if shard is not None \
        else sum(ds.block_sizes())
    n_batches = rows // batch_size if drop_last \
        else -(-rows // batch_size)

    class _TorchBridge(IterableDataset):
        def __init__(self):
            super().__init__()
            self._epoch = 0

        def __iter__(self):
            from torch.utils.data import get_worker_info
            info = get_worker_info()
            # per-epoch reseed — the external-loop analogue of
            # DeviceFeed.set_epoch; without it every epoch replays
            # byte-identical batch order. The epoch signal must vary per
            # epoch and be IDENTICAL across loader workers (the stripe split
            # below needs all workers walking one order). Two worker modes:
            # fresh forks per epoch (counter resets, but the DataLoader's
            # per-epoch base seed info.seed - info.id varies) and
            # persistent_workers (base seed fixed, but this dataset copy
            # lives on and its counter advances) — the SUM covers both.
            epoch_sig, self._epoch = self._epoch, self._epoch + 1
            if info is not None:
                epoch_sig += int(info.seed) - int(info.id)
            it_seed = epoch_seed(seed, epoch_sig) if shuffle else seed
            it = HostBatchIterator(
                ds, batch_size, columns, shard=shard, shuffle=shuffle,
                seed=it_seed, drop_remainder=drop_last)

            def _tensor(a):
                # the host feed serves read-only views of its frozen decode
                # cache; from_numpy would share that memory and let an
                # in-place consumer mutation (feats.sub_(...)) silently
                # poison later epochs — copy unless already writeable-owned
                a = np.ascontiguousarray(a)
                if not a.flags.writeable:
                    a = a.copy()
                return torch.from_numpy(a)

            # every worker walks the SAME order and takes every N-th batch
            # (a stripe split): without it each of N workers would yield the
            # whole dataset, N× data per epoch
            for i, batch in enumerate(it):
                if info is not None and i % info.num_workers != info.id:
                    continue
                feats = _tensor(batch["features"])
                if label_column is None:
                    yield feats
                else:
                    yield feats, _tensor(batch["label"])

        def __len__(self):
            return n_batches

    return _TorchBridge()


def to_tf_dataset(ds, feature_columns: Sequence[str],
                  label_column: Optional[str] = None,
                  batch_size: int = 64,
                  shuffle: bool = False,
                  seed: int = 0,
                  feature_dtype=np.float32,
                  label_dtype=np.float32,
                  drop_last: bool = False,
                  world_size: int = 1,
                  rank: int = 0):
    """The dataset as a batched ``tf.data.Dataset`` of ``(features, label)``
    (``features`` alone without a ``label_column``) — what the reference's
    TF path feeds ``model.fit`` (tf/estimator.py:179-199).

    Built with ``from_generator`` over the native host feed; the last batch is
    ragged unless ``drop_last`` (declared via a ``None`` leading dim in the
    output signature).
    """
    import tensorflow as tf

    shard = _shard(ds, world_size, rank, shuffle, seed)
    columns = _columns_spec(feature_columns, label_column,
                            feature_dtype, label_dtype)
    n_features = len(feature_columns)
    f_spec = tf.TensorSpec(shape=(None, n_features) if n_features > 1
                           else (None,), dtype=tf.as_dtype(np.dtype(
                               feature_dtype)))
    if label_column is None:
        signature = f_spec
    else:
        signature = (f_spec, tf.TensorSpec(
            shape=(None,), dtype=tf.as_dtype(np.dtype(label_dtype))))

    epoch_box = [0]

    def _gen():
        # from_generator re-invokes this per epoch (model.fit / .repeat()):
        # vary the shuffle seed each time, like DeviceFeed.set_epoch
        epoch, epoch_box[0] = epoch_box[0], epoch_box[0] + 1
        it_seed = epoch_seed(seed, epoch) if shuffle else seed
        it = HostBatchIterator(ds, batch_size, columns, shard=shard,
                               shuffle=shuffle, seed=it_seed,
                               drop_remainder=drop_last)
        for batch in it:
            if label_column is None:
                yield batch["features"]
            else:
                yield batch["features"], batch["label"]

    return tf.data.Dataset.from_generator(_gen, output_signature=signature)
