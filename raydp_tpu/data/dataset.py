"""DistributedDataset: Arrow blocks in the object store, with lineage recovery.

Parity map (reference python/raydp/spark/dataset.py):

- :func:`from_frame` — the eager push path (deprecated ``fromSparkRDD``,
  ObjectStoreWriter.scala:104-152): materialize every partition into the store.
- :func:`from_frame_recoverable` — ``from_spark_recoverable`` (dataset.py:172-222):
  persist the frame into executor block caches, then fetch each partition through
  the executor data-plane with infinite-retry semantics; a lost block recomputes
  from its lineage recipe (recache protocol, RayDPExecutor.scala:312-355).
- :func:`release` — ``release_spark_recoverable`` (dataset.py:224-237).
- :func:`to_frame` — ``ray_dataset_to_spark_dataframe`` (dataset.py:239-313): the
  master actor holds the blocks (``add_objects``/``get_object``,
  ray_cluster_master.py:222-226) so they outlive the dataset producer.
- ownership transfer — ``get_raydp_master_owner`` (dataset.py:137-158): blocks are
  written owned by the master so ``stop(cleanup_data=False)`` keeps them.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from raydp_tpu.log import get_logger
from raydp_tpu.runtime.object_store import ObjectRef, get_client
from raydp_tpu.utils import divide_blocks

logger = get_logger("data.dataset")


@dataclass
class BlockMeta:
    num_rows: int
    # exactly one of `ref` / fetch recipe is the access path
    ref: Optional[ObjectRef] = None
    cache_key: Optional[str] = None
    executor: Optional[str] = None
    recover: Optional[bytes] = None  # cloudpickled lineage Task


class DistributedDataset:
    """An immutable list of Arrow blocks resolvable from any session process."""

    def __init__(self, blocks: List[BlockMeta], schema: pa.Schema,
                 owner: Optional[str] = None,
                 frame_id: Optional[str] = None, session=None):
        self._blocks = blocks
        self._schema = schema
        self._owner = owner
        self._frame_id = frame_id   # set for recoverable datasets
        self._session = session

    # ---- basic accessors ----------------------------------------------------
    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def num_blocks(self) -> int:
        return len(self._blocks)

    def count(self) -> int:
        return sum(b.num_rows for b in self._blocks)

    def block_sizes(self) -> List[int]:
        return [b.num_rows for b in self._blocks]

    # ---- block access (the hot fetch path, dataset.py:54-84) ----------------
    def get_block_ref(self, i: int, max_retries: int = 8) -> ObjectRef:
        """Resolve block ``i`` to an object-store ref, fetching/recovering as
        needed. Retries route around restarting executors (``max_retries=-1``
        spirit, dataset.py:54 — bounded here to fail eventually)."""
        meta = self._blocks[i]
        if meta.ref is not None:
            return meta.ref
        assert meta.cache_key is not None and self._session is not None
        last_err: Optional[Exception] = None
        for attempt in range(max_retries):
            try:
                executor = self._resolve_executor(meta, attempt)
                out = executor.get_block(meta.cache_key, meta.recover,
                                         self._owner)
                meta.ref = out["ref"]
                if meta.num_rows < 0:
                    meta.num_rows = out["num_rows"]
                return meta.ref
            except Exception as e:  # noqa: BLE001 - retry any transport failure
                last_err = e
                import time
                time.sleep(0.5)
        raise RuntimeError(
            f"could not fetch block {i} ({meta.cache_key})") from last_err

    def _resolve_executor(self, meta: BlockMeta, attempt: int = 0):
        from raydp_tpu.runtime import get_runtime
        rt = get_runtime()
        handle = rt.get_actor(meta.executor) if meta.executor else None
        if handle is None:
            # executor gone for good: fan recovery out across live executors
            # (hash spread + attempt rotation) instead of serializing all
            # recovery through one actor (the reference schedules fetch tasks
            # anywhere, dataset.py:203-220)
            if self._session is not None and self._session.executors:
                import zlib
                pool = self._session.executors
                # crc32, not hash(): str hashes are per-process randomized,
                # and every reader process should converge on the same
                # executor per block so a lost block is recovered once
                idx = (zlib.crc32(meta.cache_key.encode()) + attempt) % len(pool)
                handle = pool[idx]
            else:
                raise RuntimeError(f"no executor to serve block {meta.cache_key}")
        return handle

    def get_block(self, i: int, zero_copy: bool = False) -> pa.Table:
        """Fetch block ``i``. ``zero_copy=True`` decodes in place over shared
        memory — valid only while the dataset is not released; the device feed
        uses it because each batch is consumed (device_put) before the next
        fetch."""
        return get_client().get(self.get_block_ref(i), zero_copy=zero_copy)

    def blocks(self) -> List[pa.Table]:
        return [self.get_block(i) for i in range(self.num_blocks())]

    def to_arrow(self) -> pa.Table:
        if not self._blocks:
            return self._schema.empty_table()
        return pa.concat_tables(self.blocks(), promote_options="permissive")

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def take(self, n: int) -> List[dict]:
        out: List[dict] = []
        for i in range(self.num_blocks()):
            out.extend(self.get_block(i).slice(0, n - len(out)).to_pylist())
            if len(out) >= n:
                break
        return out

    # ---- transforms ---------------------------------------------------------
    def random_shuffle(self, seed: Optional[int] = None) -> "DistributedDataset":
        """Uniform random shuffle across ALL rows (the reference's estimators
        call ``ds.random_shuffle()`` before training, torch/estimator.py:335-338,
        where ray.data shuffles executor-side).

        With a live session this runs as distributed shuffle tasks on the
        executors (map: random bucketing; reduce: in-partition permutation) —
        the driver moves only refs, never rows. Without a session (e.g. a
        dataset rebuilt from :meth:`portable` inside an SPMD rank) it falls
        back to a local two-level shuffle.
        """
        if self._session is not None and self.num_blocks() > 0:
            refs = [self.get_block_ref(i) for i in range(self.num_blocks())]
            schema_bytes = self._schema.serialize().to_pybytes()
            new_refs, rows = self._session.engine.random_shuffle_refs(
                refs, schema_bytes, seed, owner=self._owner)
            blocks = [BlockMeta(num_rows=n, ref=r)
                      for r, n in zip(new_refs, rows)]
            return DistributedDataset(blocks, self._schema, self._owner,
                                      session=self._session)
        rng = np.random.RandomState(seed if seed is not None else 0)
        order = rng.permutation(self.num_blocks())
        client = get_client()
        new_blocks: List[BlockMeta] = []
        for i in order:
            table = self.get_block(int(i))
            perm = rng.permutation(table.num_rows)
            shuffled = table.take(pa.array(perm))
            ref = client.put_arrow(shuffled, owner=self._owner)
            new_blocks.append(BlockMeta(num_rows=shuffled.num_rows, ref=ref))
        return DistributedDataset(new_blocks, self._schema, self._owner,
                                  session=self._session)

    def split_shards(self, world_size: int, shuffle: bool = False,
                     seed: Optional[int] = None
                     ) -> List[List[Tuple[int, int, int]]]:
        """Balanced shard plan: per rank, ``(block_index, offset, length)`` with
        equal per-rank sample counts (the ``divide_blocks`` kernel,
        utils.py:149-222 — offsets here since a rank may take part of a block).

        With MORE ranks than blocks — where ``divide_blocks`` has no whole
        block per rank and the reference repartitions first
        (test_torch_sequential.py:23-54) — the plan falls back to contiguous
        row ranges: rank ``r`` reads rows ``[r·per, (r+1)·per)`` of the
        concatenated dataset, wrapping past the end so every rank still gets
        exactly ``ceil(total/world)`` samples (the SPMD no-short-rank rule).
        """
        sizes = self.block_sizes()
        if world_size > len(sizes):
            total = sum(sizes)
            if total == 0:
                return [[] for _ in range(world_size)]
            per = -(-total // world_size)
            starts = np.cumsum([0] + list(sizes))
            # shuffle here is coarse, like divide_blocks' block shuffle: a
            # seeded rotation of the global row space plus a permutation of
            # the rank→slice mapping, so ranks draw different data each epoch
            # (per-row shuffling belongs to the feed's in-batch shuffle)
            rotation = 0
            order = np.arange(world_size)
            if shuffle:
                rng = np.random.RandomState(seed if seed is not None else 0)
                rotation = int(rng.randint(total))
                order = rng.permutation(world_size)

            def runs(start: int, stop: int) -> List[Tuple[int, int, int]]:
                out: List[Tuple[int, int, int]] = []
                row = start
                while row < stop:
                    r = row % total
                    b = int(np.searchsorted(starts, r, side="right")) - 1
                    take = int(min(stop - row, starts[b + 1] - r))
                    out.append((b, r - int(starts[b]), take))
                    row += take
                return out

            return [runs(int(order[r]) * per + rotation,
                         (int(order[r]) + 1) * per + rotation)
                    for r in range(world_size)]
        assignment = divide_blocks(sizes, world_size,
                                   shuffle=shuffle, shuffle_seed=seed)
        plans: List[List[Tuple[int, int, int]]] = []
        for rank in range(world_size):
            taken: Dict[int, int] = {}
            plan: List[Tuple[int, int, int]] = []
            for block_idx, n in assignment[rank]:
                off = taken.get(block_idx, 0)
                size = self._blocks[block_idx].num_rows
                if off >= size:
                    off = 0  # duplicated block (wraparound): restart from the top
                take = min(n, size - off)
                plan.append((block_idx, off, take))
                taken[block_idx] = off + take
                if take < n:
                    plan.append((block_idx, 0, n - take))
                    taken[block_idx] = n - take
            plans.append(plan)
        return plans

    # ---- portability --------------------------------------------------------
    def portable(self) -> Dict:
        """A picklable descriptor another session process (e.g. an SPMD rank)
        can rebuild this dataset from. Forces every block into the object
        store first, so readers need only a store client — no session, no
        executors (parity: the holder-actor handoff, dataset.py:239-313)."""
        refs = [self.get_block_ref(i) for i in range(self.num_blocks())]
        return {
            "refs": refs,
            "rows": self.block_sizes(),
            "schema": self._schema.serialize().to_pybytes(),
        }

    @staticmethod
    def from_portable(payload: Dict) -> "DistributedDataset":
        """Rebuild from :meth:`portable` in a process with a live store client."""
        schema = pa.ipc.read_schema(pa.py_buffer(payload["schema"]))
        blocks = [BlockMeta(num_rows=n, ref=r)
                  for r, n in zip(payload["refs"], payload["rows"])]
        return DistributedDataset(blocks, schema)

    # ---- lifecycle ----------------------------------------------------------
    def release(self) -> None:
        """Drop recoverable blocks + fetched refs
        (parity: ``release_spark_recoverable``, dataset.py:224-237)."""
        if self._frame_id is not None and self._session is not None:
            self._session.release_cached(self._frame_id)
        refs = [b.ref for b in self._blocks if b.ref is not None]
        if refs:
            try:
                get_client().free(refs)
            except Exception:
                pass
        self._blocks = []

    def transfer_to_master(self) -> None:
        """Re-home fetched blocks to the master actor so they outlive executors
        and ``stop(cleanup_data=False)`` (parity: dataset.py:137-158)."""
        if self._session is None:
            return
        refs = [b.ref for b in self._blocks if b.ref is not None]
        if refs:
            get_client().transfer_ownership(refs, self._session.master_name)


# ==== conversions ==================================================================
def from_frame(df, owner: Optional[str] = None) -> DistributedDataset:
    """Eager conversion: materialize every partition into the object store."""
    session = df._session
    owner = owner or session.master_name
    refs, schema_bytes, num_rows = session.engine.materialize(df._plan,
                                                              owner=owner)
    blocks = [BlockMeta(num_rows=n, ref=r) for r, n in zip(refs, num_rows)]
    schema = pa.ipc.read_schema(pa.py_buffer(schema_bytes))
    return DistributedDataset(blocks, schema, owner, session=session)


def from_frame_recoverable(df, fetch: bool = True) -> DistributedDataset:
    """Recoverable conversion: persist in executor caches, fetch via data plane.

    Blocks fetched lazily (or eagerly with ``fetch=True`` to mirror the
    reference's immediate per-partition fetch tasks, dataset.py:203-220)."""
    from raydp_tpu.etl import plan as P

    session = df._session
    cached_df = df.persist()
    plan: P.CachedScan = cached_df._plan
    blocks = [
        BlockMeta(num_rows=-1, cache_key=key, executor=ex, recover=rec)
        for key, ex, rec in zip(plan.cache_keys, plan.executors,
                                plan.recover_tasks)
    ]
    schema = (pa.ipc.read_schema(pa.py_buffer(plan.schema))
              if plan.schema else df.schema)
    ds = DistributedDataset(blocks, schema, session.master_name,
                            frame_id=plan.frame_id, session=session)
    if fetch:
        for i in range(ds.num_blocks()):
            ds.get_block_ref(i)  # fetch records num_rows from the executor
    return ds


def release(ds: DistributedDataset) -> None:
    ds.release()


def to_frame(ds: DistributedDataset, session=None):
    """Dataset → DataFrame; the master holds the block refs
    (parity: dataset.py:239-313 ``_convert_by_udf`` holder-actor path)."""
    from raydp_tpu.etl import plan as P
    from raydp_tpu.etl.frame import DataFrame

    session = session or ds._session
    if session is None:
        raise ValueError("to_frame needs a live session")
    refs = [ds.get_block_ref(i) for i in range(ds.num_blocks())]
    holder_id = f"ds-{uuid.uuid4().hex[:10]}"
    session.master.add_objects(holder_id, refs)
    get_client().transfer_ownership(refs, session.master_name)
    schema_bytes = ds.schema.serialize().to_pybytes()
    return DataFrame(session, P.InMemory(refs, schema_bytes), schema=ds.schema)
