"""DeviceFeed: Arrow blocks → device-sharded ``jax.Array`` batches.

This is the TPU-specific tail of the data plane, replacing the reference's
``dataset.to_torch`` + DataLoader feed (torch/estimator.py:226-241) and its
background-prefetch trick (``PrefetchedDataLoader``, torch_ml_dataset.py:69-108).
Design for the hardware: batches are assembled host-side as contiguous numpy
(decode is zero-copy out of shared memory wherever Arrow allows), then placed with
``jax.device_put`` under a ``NamedSharding`` over the mesh's data axis, so the
train step's inputs are already distributed and XLA inserts no gather. Shapes are
static (``drop_remainder``) — a changing batch dimension would retrace/recompile
under jit.

The streaming pipeline is ASYNC and DOUBLE-BUFFERED (:class:`DevicePrefetcher`):
a host stage keeps ``prefetch`` decoded batches ahead, and a device stage keeps
``prefetch_to_device`` already-``device_put`` batches ahead, so the H2D transfer
(and the chained path's stack assembly) for batch ``k+1`` overlaps the jitted
compute of batch ``k``. The reference prefetches only *host* batches; pipelining
the device side is what removes ``device_put`` from the step critical path.
Per-phase walls (``decode``/``stage``/``h2d``) accumulate in
:class:`PipelineTimings` and surface in the estimators' epoch reports.

Multi-host: each process feeds its own shard and the global array is built with
``jax.make_array_from_process_local_data`` — the per-host ``device_put`` endpoint
of SURVEY.md §2.5's "TPU-native equivalent".
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import pyarrow as pa

from raydp_tpu import knobs, metrics, profiler
from raydp_tpu.log import get_logger

logger = get_logger("data.feed")


@dataclass
class ShardSpec:
    """What one data-parallel rank reads: ``(block_index, offset, length)``."""

    parts: List[Tuple[int, int, int]] = field(default_factory=list)

    def num_rows(self) -> int:
        return sum(n for _, _, n in self.parts)


ColumnSpec = Union[str, Sequence[str]]

#: batch-dict key carrying the per-row validity mask under pad-and-mask mode
#: (1.0 = real row, 0.0 = padding). Present on EVERY batch a padding feed
#: yields — a constant pytree structure keeps the jitted step at one
#: compilation — and threaded by the estimators into loss/metric
#: accumulators so padded rows contribute nothing.
MASK_KEY = "__mask__"


def pad_batch(batch: Dict[str, np.ndarray], batch_size: int
              ) -> Dict[str, np.ndarray]:
    """Zero-pad a ragged host batch up to ``batch_size`` rows and attach the
    validity mask. Shapes come out static (one XLA program) and divisible by
    any data-axis extent that divides ``batch_size`` — the alternative the
    pre-pad feed took was silently DROPPING the tail rows under a >1 data
    axis."""
    rows = int(next(iter(batch.values())).shape[0])
    pad = batch_size - rows
    if pad < 0:
        raise ValueError(f"batch of {rows} rows exceeds batch_size "
                         f"{batch_size}")
    mask = np.zeros(batch_size, np.float32)
    mask[:rows] = 1.0
    if pad:
        batch = {n: np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0)
            for n, a in batch.items()}
        metrics.inc("train_padded_rows_total", pad)
    else:
        batch = dict(batch)
    batch[MASK_KEY] = mask
    return batch


def epoch_seed(base: int, epoch: int) -> int:
    """Deterministic per-epoch shuffle seed — THE derivation every feed path
    shares (DeviceFeed.set_epoch and both external-loop bridges), so the
    bridges cannot drift from the native data-plane semantics."""
    return (base + epoch * 1000003) % (2**31 - 1)


def _normalize_columns(columns: Dict[str, Tuple[ColumnSpec, np.dtype]]
                       ) -> Dict[str, Tuple[Tuple[str, ...], np.dtype]]:
    return {
        name: ((cols,) if isinstance(cols, str) else tuple(cols), np.dtype(dt))
        for name, (cols, dt) in columns.items()
    }


def _as_numpy(table: pa.Table, columns: Sequence[str], dtype) -> np.ndarray:
    """Stack columns into [rows, len(columns)] (or [rows] for one column).

    Multi-column decode goes through the native staging kernel when eligible
    (csrc/feed/stage.cpp: cast+interleave fused into one pass per column,
    straight from the Arrow data buffers — SURVEY.md §7 step 2's "Arrow ↔
    host buffer staging"); null-bearing/non-primitive columns and missing
    toolchains fall back to the numpy path below, output-identical
    (tests/test_native_stage.py)."""
    if len(columns) > 1:
        from raydp_tpu.native.stage import stage_table
        staged = stage_table(table, columns, dtype)
        if staged is not None:
            return staged
    arrays = []
    for c in columns:
        col = table.column(c)
        arrays.append(col.to_numpy(zero_copy_only=False).astype(dtype, copy=False))
    if len(arrays) == 1:
        return arrays[0]
    return np.stack(arrays, axis=1)


class HostBatchIterator:
    """Yields host-side numpy batch dicts from a dataset (or one shard of it).

    Decoded blocks are cached across epochs (``cache_decoded``, on by
    default, bounded by ``RDT_FEED_CACHE_MB``): Arrow→numpy decode + dtype
    cast is the dominant host cost of an epoch once the train step is fast,
    and multi-epoch training re-reads the same immutable blocks. Per-epoch
    shuffling permutes indices over the cached arrays instead of re-decoding.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        columns: Dict[str, Tuple[ColumnSpec, np.dtype]],
        shard: Optional[ShardSpec] = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = True,
        cache_decoded: bool = True,
        cache_cap_bytes: Optional[int] = None,
        pad_remainder: bool = False,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.columns = _normalize_columns(columns)
        self.shard = shard
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder and not pad_remainder
        #: pad-and-mask mode: the ragged tail pads to a full batch and EVERY
        #: batch carries :data:`MASK_KEY` (constant pytree structure — one
        #: jit compilation); wins over drop_remainder
        self.pad_remainder = pad_remainder
        self.cache_decoded = cache_decoded
        # per-iterator budget (train and eval feeds each get their own); env
        # read at construction so callers can tune it after import
        self.cache_cap_bytes = cache_cap_bytes if cache_cap_bytes is not None \
            else int(float(knobs.get("RDT_FEED_CACHE_MB")) * (1 << 20))
        self._decoded: Dict[int, Dict[str, np.ndarray]] = {}
        self._cache_bytes = 0
        self._sizes: Optional[List[int]] = None

    def _block_sizes(self) -> List[int]:
        if self._sizes is None:
            self._sizes = list(self.dataset.block_sizes())
        return self._sizes

    def _parts(self) -> List[Tuple[int, int, int]]:
        if self.shard is not None:
            return list(self.shard.parts)
        return [(i, 0, n) for i, n in enumerate(self._block_sizes())]

    def _block_rows(self, block_idx: int) -> int:
        return self._block_sizes()[block_idx]

    def _decode_block(self, block_idx: int) -> Dict[str, np.ndarray]:
        """Decode (and maybe cache) ALL rows of a block."""
        cached = self._decoded.get(block_idx)
        if cached is not None:
            return cached
        table = self.dataset.get_block(block_idx, zero_copy=True)
        arrays = {name: _as_numpy(table, cols, dt)
                  for name, (cols, dt) in self.columns.items()}
        if self.cache_decoded:
            size = sum(a.nbytes for a in arrays.values())
            if self._cache_bytes + size <= self.cache_cap_bytes:
                # own the bytes: a zero-copy view into the store must not be
                # cached past this iteration (the block could be freed)
                arrays = {n: (a if a.flags["OWNDATA"] else a.copy())
                          for n, a in arrays.items()}
                for a in arrays.values():
                    # batches served from the cache are views; freezing the
                    # cache turns an in-place consumer mutation (which would
                    # silently poison later epochs) into a loud error
                    a.setflags(write=False)
                self._decoded[block_idx] = arrays
                self._cache_bytes += size
        return arrays

    def _decode_slice(self, block_idx: int, off: int,
                      length: int) -> Dict[str, np.ndarray]:
        """Decode just ``[off, off+length)`` — used for partial shard parts
        so a rank neither decodes nor budgets rows it never reads."""
        table = self.dataset.get_block(block_idx,
                                       zero_copy=True).slice(off, length)
        return {name: _as_numpy(table, cols, dt)
                for name, (cols, dt) in self.columns.items()}

    def __iter__(self):
        rng = np.random.RandomState(self.seed)
        parts = self._parts()
        if self.shuffle:
            rng.shuffle(parts)
        buffers: Dict[str, List[np.ndarray]] = {n: [] for n in self.columns}
        buffered = 0
        for block_idx, off, length in parts:
            full_block = off == 0 and length == self._block_rows(block_idx)
            if full_block or block_idx in self._decoded:
                arrays = self._decode_block(block_idx)
                if self.shuffle and length > 1:
                    idx = off + rng.permutation(length)
                    sel = {n: a[idx] for n, a in arrays.items()}
                else:
                    sel = {n: a[off:off + length] for n, a in arrays.items()}
            else:
                sel = self._decode_slice(block_idx, off, length)
                if self.shuffle and length > 1:
                    idx = rng.permutation(length)
                    sel = {n: a[idx] for n, a in sel.items()}
            for name in self.columns:
                buffers[name].append(sel[name])
            buffered += length
            while buffered >= self.batch_size:
                batch, buffers, buffered = self._cut_batch(buffers, buffered)
                yield pad_batch(batch, self.batch_size) \
                    if self.pad_remainder else batch
        if buffered > 0 and not self.drop_remainder:
            batch = {n: np.concatenate(v, axis=0) for n, v in buffers.items()}
            yield pad_batch(batch, self.batch_size) \
                if self.pad_remainder else batch

    def _cut_batch(self, buffers, buffered):
        joined = {n: (np.concatenate(v, axis=0) if len(v) > 1 else v[0])
                  for n, v in buffers.items()}
        batch = {n: a[: self.batch_size] for n, a in joined.items()}
        rest = {n: [a[self.batch_size:]] for n, a in joined.items()}
        return batch, rest, buffered - self.batch_size


def process_local_batch_rows(sharding, global_batch: int) -> Tuple[int, int]:
    """The contiguous ``[start, stop)`` slice of a ``(global_batch,)`` array
    that THIS process's devices address under ``sharding``.

    This is what a gang rank must feed ``make_array_from_process_local_data``:
    with the batch sharded over a >1 data axis spanning processes it is a
    proper slice; with the batch replicated across processes (size-1 data axis
    — pure fsdp/expert meshes) it is the full ``(0, global_batch)`` range on
    every process.
    """
    idx_map = sharding.addressable_devices_indices_map((global_batch,))
    intervals = set()
    for idx in idx_map.values():
        sl = idx[0] if idx else slice(None)
        intervals.add((sl.start or 0,
                       global_batch if sl.stop is None else sl.stop))
    lo = min(s for s, _ in intervals)
    hi = max(e for _, e in intervals)
    cur = lo
    for s, e in sorted(intervals):
        if s > cur:
            raise ValueError(
                f"process-local batch rows are not contiguous under {sharding}"
                f": gap at [{cur}, {s})")
        cur = max(cur, e)
    return int(lo), int(hi)


class GangShardIterator:
    """Per-rank host batches that compose into globally-consistent batches.

    Global batch ``k`` covers dataset rows ``[k*B, (k+1)*B)`` in block order —
    exactly the batches a single-process :class:`HostBatchIterator` with
    ``shuffle=False`` cuts — and rank ``r`` of ``w`` yields its addressable
    slice of each: ``row_range`` (derived from the batch sharding via
    :func:`process_local_batch_rows`) when given, else the equal split
    ``[r*B/w, (r+1)*B/w)``. All ranks permute the *batch order* with the same
    seed (no within-block shuffling), so every rank walks the same global
    batch sequence and ``jax.make_array_from_process_local_data`` assembles
    the intended global array. This is the multi-host analogue of the
    reference's per-worker dataset shard (torch/estimator.py:226-241 via
    ``divide_blocks``), strengthened to give bit-identical global batches for
    any world size.
    """

    def __init__(
        self,
        dataset,
        global_batch: int,
        world_size: int,
        rank: int,
        columns: Dict[str, Tuple[ColumnSpec, np.dtype]],
        shuffle: bool = False,
        seed: int = 0,
        row_range: Optional[Tuple[int, int]] = None,
    ):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} out of range for world {world_size}")
        if row_range is None:
            if global_batch % world_size != 0:
                raise ValueError(
                    f"global batch {global_batch} not divisible by world size "
                    f"{world_size}")
            per = global_batch // world_size
            row_range = (rank * per, (rank + 1) * per)
        lo, hi = row_range
        if not (0 <= lo < hi <= global_batch):
            raise ValueError(f"row_range {row_range} out of range for "
                             f"global batch {global_batch}")
        self.dataset = dataset
        self.global_batch = global_batch
        self.world_size = world_size
        self.rank = rank
        self.columns = _normalize_columns(columns)
        self.shuffle = shuffle
        self.seed = seed
        self.row_range = (int(lo), int(hi))
        self.per_rank = int(hi) - int(lo)
        self._starts = np.cumsum([0] + list(dataset.block_sizes()))
        self.total = int(self._starts[-1])
        # decoded-block cache across epochs (HostBatchIterator's trick):
        # without it every rank re-runs Arrow→numpy decode for every batch
        # of every epoch — the dominant per-epoch host cost of a gang rank
        self._decoded: Dict[int, Dict[str, np.ndarray]] = {}
        self._cache_bytes = 0
        self._cache_cap = int(float(knobs.get("RDT_FEED_CACHE_MB"))
                              * (1 << 20))

    def __len__(self) -> int:
        return self.total // self.global_batch

    def _runs(self, start: int, stop: int) -> List[Tuple[int, int, int]]:
        """Global row range → list of (block_index, offset, length) runs."""
        runs: List[Tuple[int, int, int]] = []
        b = int(np.searchsorted(self._starts, start, side="right")) - 1
        row = start
        while row < stop:
            blk_end = int(self._starts[b + 1])
            take = min(stop, blk_end) - row
            runs.append((b, row - int(self._starts[b]), take))
            row += take
            b += 1
        return runs

    def _decoded_nbytes(self, rows: int) -> int:
        """Exact decoded size of ``rows`` rows under this iterator's fixed-
        width column specs — lets cache eligibility be decided WITHOUT
        decoding the block first."""
        return rows * sum(len(cols) * dt.itemsize
                          for cols, dt in self.columns.values())

    def _decode_run(self, b: int, off: int,
                    length: int) -> Dict[str, np.ndarray]:
        """Rows ``[off, off+length)`` of block ``b``: served from the decoded
        cache when the block fits the ``RDT_FEED_CACHE_MB`` budget; otherwise
        only the requested slice is decoded (``table.slice`` is zero-copy),
        so an over-cap gang feed pays O(batch) — not O(block) — Arrow→numpy
        work per batch (mirrors ``HostBatchIterator._decode_slice``)."""
        cached = self._decoded.get(b)
        if cached is None and (self._cache_bytes
                               + self._decoded_nbytes(self._block_rows(b))
                               <= self._cache_cap):
            table = self.dataset.get_block(b, zero_copy=True)
            arrays = {name: _as_numpy(table, cols, dt)
                      for name, (cols, dt) in self.columns.items()}
            # own the bytes (a zero-copy view into the store must not be
            # cached past this iteration) and freeze them so an in-place
            # consumer mutation fails loudly instead of poisoning epochs
            arrays = {n: (a if a.flags["OWNDATA"] else a.copy())
                      for n, a in arrays.items()}
            for a in arrays.values():
                a.setflags(write=False)
            cached = self._decoded[b] = arrays
            self._cache_bytes += sum(a.nbytes for a in arrays.values())
        if cached is not None:
            return {n: a[off:off + length] for n, a in cached.items()}
        table = self.dataset.get_block(b, zero_copy=True).slice(off, length)
        return {name: _as_numpy(table, cols, dt)
                for name, (cols, dt) in self.columns.items()}

    def _block_rows(self, b: int) -> int:
        return int(self._starts[b + 1] - self._starts[b])

    def __iter__(self):
        order = np.arange(len(self))
        if self.shuffle:
            np.random.RandomState(self.seed).shuffle(order)
        for k in order:
            start = int(k) * self.global_batch + self.row_range[0]
            parts = []
            for b, off, length in self._runs(start, start + self.per_rank):
                parts.append(self._decode_run(b, off, length))
            if len(parts) == 1:
                yield parts[0]
            else:
                yield {n: np.concatenate([p[n] for p in parts], axis=0)
                       for n in self.columns}


class DeviceEpochCache:
    """The whole dataset resident in device memory: epoch = ONE dispatch.

    TPU-first feed design for datasets that fit an HBM budget (the reference's
    tabular workloads are tens of MB against 16 GB of HBM): decode every block
    once, concatenate to contiguous host arrays, and ``device_put`` them under
    the mesh's batch sharding. The train loop then runs a whole epoch as a
    single jitted ``lax.scan`` whose body *slices batches on device* — with
    per-epoch shuffling as an on-device ``jax.random.permutation`` — so the
    steady-state host cost of an epoch is one dispatch and one scalar fetch.

    This replaces, for resident datasets, three O(dataset)-per-epoch host
    costs the streaming path pays: Arrow→numpy feed assembly, the per-epoch
    executor-side re-shuffle, and one dispatch round trip per chained step
    (~64 ms each on a remote-tunnel backend). The streaming
    :class:`DeviceFeed` remains the path for datasets above the budget and
    for multi-process gangs (where each process owns only its shard).
    """

    def __init__(self, dataset, columns: Dict[str, Tuple[ColumnSpec, np.dtype]],
                 mesh=None):
        import jax

        cols = _normalize_columns(columns)
        host: Dict[str, List[np.ndarray]] = {n: [] for n in cols}
        for i in range(dataset.num_blocks()):
            table = dataset.get_block(i, zero_copy=True)
            for name, (cnames, dt) in cols.items():
                host[name].append(_as_numpy(table, cnames, dt))
        joined = {n: (np.concatenate(v, axis=0) if len(v) > 1 else v[0])
                  for n, v in host.items()}
        self.num_rows = int(next(iter(joined.values())).shape[0])
        self.nbytes = sum(a.nbytes for a in joined.values())
        self.mesh = mesh
        if mesh is not None:
            # REPLICATED across the mesh: the row count need not divide the
            # data axes (a row-sharded layout would require it), and the
            # eligibility budget already bounds the per-device bytes. The
            # train loop's per-batch sharding constraint re-distributes each
            # sliced batch over the data axes
            from jax.sharding import NamedSharding, PartitionSpec
            self.sharding = NamedSharding(mesh, PartitionSpec())
            self.arrays = {n: jax.device_put(a, self.sharding)
                           for n, a in joined.items()}
        else:
            self.sharding = None
            self.arrays = {n: jax.device_put(a) for n, a in joined.items()}
        # one host row for shape/dtype-driven model init; the big host copies
        # are dropped once resident on device
        self.init_row = {n: a[:1].copy() for n, a in joined.items()}

    def make_epoch_fn(self, step, batch_size: int, shuffle: bool,
                      batch_sharding=None, seq_sharding=None):
        """Build THE resident epoch program both estimators jit — one source
        for the permutation/slice/constraint/scan logic so the flax and keras
        twins cannot drift.

        ``step(carry, batch) -> carry`` is the caller's train step in scan
        form. Returns ``(epoch_fn, steps_per_epoch)`` with
        ``epoch_fn(carry, data, key) -> carry``: one whole epoch —
        per-epoch on-device permutation when ``shuffle`` (a true uniform row
        shuffle), batches sliced/gathered on device, each constrained onto
        the mesh's batch sharding — ndim >= 2 leaves onto ``seq_sharding``
        when one is given, so declared sequence dims spread over the mesh's
        ``seq`` axis. Callers jit it with the carry donated and
        ``data``/``key`` left alone (the resident arrays are reused every
        epoch).
        """
        import jax
        import jax.numpy as jnp
        from jax import lax

        n_rows, B = self.num_rows, batch_size
        steps_per_epoch = n_rows // B

        def epoch_fn(carry, data, key):
            perm = jax.random.permutation(key, n_rows) if shuffle else None

            def body(carry, s):
                if perm is not None:
                    idx = lax.dynamic_slice(perm, (s * B,), (B,))
                    batch = {n: jnp.take(a, idx, axis=0)
                             for n, a in data.items()}
                else:
                    batch = {n: lax.dynamic_slice_in_dim(a, s * B, B, 0)
                             for n, a in data.items()}
                if batch_sharding is not None:
                    if seq_sharding is not None:
                        batch = {
                            n: lax.with_sharding_constraint(
                                a, seq_sharding if a.ndim >= 2
                                else batch_sharding)
                            for n, a in batch.items()}
                    else:
                        batch = lax.with_sharding_constraint(batch,
                                                             batch_sharding)
                return step(carry, batch), ()

            carry, _ = lax.scan(body, carry, jnp.arange(steps_per_epoch))
            return carry

        return epoch_fn, steps_per_epoch

    @staticmethod
    def cap_bytes() -> int:
        return int(float(knobs.get("RDT_DEVICE_CACHE_MB")) * (1 << 20))

    @staticmethod
    def estimate_bytes(dataset,
                       columns: Dict[str, Tuple[ColumnSpec, np.dtype]]) -> int:
        rows = sum(dataset.block_sizes())
        per_row = sum(len(cnames) * np.dtype(dt).itemsize
                      for cnames, dt in _normalize_columns(columns).values())
        return rows * per_row

    @classmethod
    def eligible(cls, dataset,
                 columns: Dict[str, Tuple[ColumnSpec, np.dtype]],
                 batch_size: int, drop_last: bool) -> bool:
        """THE residency gate — the single decision every call site (fit, the
        fit_on_frame shuffle-skip, the keras twin) must share, or a drifted
        copy could e.g. skip the dataset-level shuffle while fit() streams.
        Requires: opted in, single process (a gang rank only holds its shard —
        global batches there need the per-rank feed), static full batches
        (``drop_last`` with at least one batch of rows), and decoded arrays
        within the HBM budget."""
        import jax

        if not knobs.get("RDT_DEVICE_CACHE"):
            return False
        if not drop_last or jax.process_count() > 1:
            return False
        cap = cls.cap_bytes()  # outside the try: a malformed
        # RDT_DEVICE_CACHE_MB should raise loudly, not silently stream
        try:
            if sum(dataset.block_sizes()) < batch_size:
                return False
            return cls.estimate_bytes(dataset, columns) <= cap
        except Exception:  # noqa: BLE001 - unknown size: stream
            return False


class PipelineTimings:
    """Thread-safe per-phase wall accumulator for the feed pipeline.

    Phases (surfaced per epoch as ``decode_time_s``/``stage_time_s``/
    ``h2d_time_s`` by both estimators, aggregated into bench.py's detail
    record):

    - ``decode`` — host batch production: Arrow→numpy decode (native staging
      kernel included) plus the host iterator's own batch assembly.
    - ``stage``  — dispatch-stack assembly (the chained path's ``np.stack``).
    - ``h2d``    — device placement: ``jax.device_put`` /
      ``make_array_from_process_local_data`` under the feed's sharding.

    The timers run on the pipeline's background threads, so phase walls
    OVERLAP the consumer's dispatch wall by design — pipeline wall-clock
    under the sum of phase walls is the overlap win, measured directly by
    ``benchmarks/host_decode_bench.py --overlap``.
    """

    KEYS = ("decode", "stage", "h2d")

    def __init__(self):
        self._lock = threading.Lock()
        self._acc = {k: 0.0 for k in self.KEYS}

    def add(self, key: str, dt: float) -> None:
        with self._lock:
            self._acc[key] += dt
        # the registry twin: the same observation flows into the typed
        # metrics plane so metrics_report() sees feed phases without the
        # estimators re-publishing their epoch dicts
        metrics.observe("feed_phase_seconds", dt, label=key)

    def take(self) -> Dict[str, float]:
        """Snapshot AND reset — each epoch reports its own split."""
        with self._lock:
            out = dict(self._acc)
            for k in self._acc:
                self._acc[k] = 0.0
        return out


class DevicePrefetcher:
    """Bounded async stage of the device-feed pipeline (double buffering).

    Pulls items from ``src`` on a background thread, applies ``fn`` (the
    device stage passes ``jax.device_put`` under the feed's sharding), and
    keeps up to ``depth`` results queued ahead of the consumer, so staging +
    H2D for batch ``k+1`` overlap the jitted compute of batch ``k``. The
    bounded queue IS the backpressure: the producer can run at most
    ``depth + 1`` items ahead. Producer exceptions re-raise in the consumer;
    closing (or abandoning) the iterator stops the thread — an estimator
    error cannot leak one producer per epoch. Single-use: one ``iter()`` per
    instance.

    ``pull_key``/``work_key`` name the :class:`PipelineTimings` phases the
    ``next(src)`` pull and the ``fn`` call accumulate into (the host stage
    times its pulls as ``decode``; the device stage's placement is timed by
    the feed so the sync path measures identically).
    """

    _DONE = object()

    def __init__(self, src, fn=None, depth: int = 2, timings=None,
                 pull_key: Optional[str] = None,
                 work_key: Optional[str] = None,
                 name: str = "devicefeed-prefetch"):
        self._src = src
        self._fn = fn
        self._timings = timings
        self._pull_key = pull_key
        self._work_key = work_key
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        # the prefetch thread must trace under the constructing context
        # (a serve replica's staging pipeline, an estimator's feed) — a
        # plain Thread would drop the contextvar at the handoff
        self._ctx = profiler.capture()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._started = False

    def _run(self):
        with profiler.activate(self._ctx):
            self._run_inner()

    def _run_inner(self):
        try:
            src = iter(self._src)
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(src)
                except StopIteration:
                    break
                if self._timings is not None and self._pull_key:
                    self._timings.add(self._pull_key,
                                      time.perf_counter() - t0)
                if self._fn is not None:
                    t1 = time.perf_counter()
                    item = self._fn(item)
                    if self._timings is not None and self._work_key:
                        self._timings.add(self._work_key,
                                          time.perf_counter() - t1)
                if not self._put(item):
                    break
            self._put(self._DONE)  # no-op if stopped
        except BaseException as e:  # noqa: BLE001 - re-raised by the consumer
            self._put(e)
        finally:
            if self._stop.is_set():
                # stopped early: close() may already have run (and given up
                # after its join timeout if THIS thread was mid-fn), so the
                # upstream close falls to us — otherwise a chained host
                # stage would keep decoding into its full queue forever
                self._close_src()

    def _put(self, item) -> bool:
        """Blocking put that stays responsive to :meth:`close` (the timeout
        only ticks while the queue is FULL, i.e. the pipeline is ahead)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _close_src(self) -> None:
        """Best-effort upstream cleanup: a generator src (e.g. the chained
        host stage's output) closes its own stage in its finally. Both the
        consumer's close() and the producer's finally may race here —
        generator.close() raises on the loser, swallowed below."""
        src_close = getattr(self._src, "close", None)
        if src_close is not None:
            try:
                src_close()
            except Exception:  # noqa: BLE001 - already shutting down
                pass

    def __iter__(self):
        if self._started:
            raise RuntimeError("DevicePrefetcher is single-use")
        self._started = True
        self._thread.start()
        try:
            while True:
                item = self._q.get()
                if item is self._DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            self.close()

    def _drain(self) -> None:
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def close(self) -> None:
        """Stop the producer and release queued buffers (idempotent)."""
        self._stop.set()
        self._drain()  # unblocks a producer waiting on a full queue
        if self._started and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        self._drain()  # a mid-put producer may have landed one more item
        if not self._thread.is_alive():
            # thread gone (or never started): upstream close is on us; a
            # still-running thread (join timeout: mid-fn on a slow
            # device_put) closes upstream itself in _run's finally
            self._close_src()


class DeviceFeed:
    """Async double-buffered iterator of device-sharded batches.

    Two background stages feed the consumer: host decode (``prefetch``
    decoded batches ahead — the reference ``PrefetchedDataLoader``'s trick)
    and device placement (``prefetch_to_device`` already-placed batches
    ahead, so H2D for batch ``k+1`` overlaps the compute of batch ``k``;
    ``0`` restores synchronous placement — bit-identical results either way,
    tests/test_feed_pipeline.py). ``timings`` carries the per-phase
    decode/stage/h2d split the estimators report per epoch."""

    def __init__(
        self,
        dataset,
        batch_size: int,
        columns: Dict[str, Tuple[ColumnSpec, np.dtype]],
        mesh=None,
        data_axis: Optional[str] = None,
        shard: Optional[ShardSpec] = None,
        shuffle: bool = True,
        seed: int = 0,
        prefetch: int = 2,
        drop_remainder: bool = True,
        host_iter=None,
        prefetch_to_device: Optional[int] = None,
        pad_remainder: bool = False,
        seq: bool = False,
    ):
        import jax
        self._jax = jax
        self.mesh = mesh
        self.data_axis = data_axis
        self.host_iter = host_iter if host_iter is not None else HostBatchIterator(
            dataset, batch_size, columns, shard=shard, shuffle=shuffle,
            seed=seed, drop_remainder=drop_remainder,
            pad_remainder=pad_remainder)
        self.prefetch = max(1, prefetch)
        if prefetch_to_device is None:
            prefetch_to_device = int(knobs.get("RDT_PREFETCH_TO_DEVICE"))
        #: already-placed batches kept ahead of the consumer (0 = place
        #: synchronously on the consumer thread)
        self.prefetch_to_device = max(0, int(prefetch_to_device))
        self.timings = PipelineTimings()
        self._shardings = None
        #: seq-extended sharding for ndim >= 2 batch leaves (None when the
        #: mesh has no >1 ``seq`` extent or the caller left ``seq`` off):
        #: declared sequence dims stage onto the ``seq`` axis at placement,
        #: so long-context activations never land whole on one device
        self._seq_sharding = None
        if mesh is not None:
            if data_axis is None:
                # the batch's true sharding spans data AND fsdp axes; using
                # only "data" on a pure-fsdp mesh would be a (size-1-axis)
                # replicated sharding, and in gang mode each process would
                # then assemble a DIFFERENT "replicated" array from its own
                # rows — silently inconsistent global batches
                from raydp_tpu.parallel.mesh import batch_sharding, seq_extent
                self._sharding = batch_sharding(mesh)
                if seq and seq_extent(mesh) > 1:
                    self._seq_sharding = batch_sharding(mesh, seq=True)
            else:
                from jax.sharding import NamedSharding, PartitionSpec
                self._sharding = NamedSharding(mesh, PartitionSpec(data_axis))
        else:
            self._sharding = None

    def set_epoch(self, epoch: int) -> None:
        """Reseed per-epoch so shuffling differs across epochs deterministically."""
        if not hasattr(self, "_base_seed"):
            self._base_seed = self.host_iter.seed
        self.host_iter.seed = epoch_seed(self._base_seed, epoch + 1)

    def _place(self, batch: Dict[str, np.ndarray], sharding=None,
               seq_sharding=None, min_seq_ndim: int = 2):
        jax = self._jax
        if sharding is None:
            sharding, seq_sharding = self._sharding, self._seq_sharding
        if sharding is None:
            return {n: jax.device_put(a) for n, a in batch.items()}

        def pick(a):
            # only leaves with a dim past the batch axes carry a sequence
            # dim (labels/masks are 1-D and keep the plain data sharding)
            return seq_sharding if (seq_sharding is not None
                                    and a.ndim >= min_seq_ndim) else sharding

        if jax.process_count() > 1:
            return {
                n: jax.make_array_from_process_local_data(pick(a), a)
                for n, a in batch.items()
            }
        return {n: jax.device_put(a, pick(a)) for n, a in batch.items()}

    def _host_batches(self):
        """Host batches decoded ``prefetch`` ahead on a background thread;
        the pull wall (Arrow→numpy decode, native staging kernel included)
        accumulates as the ``decode`` phase."""
        return iter(DevicePrefetcher(
            self.host_iter, depth=self.prefetch, timings=self.timings,
            pull_key="decode", name="devicefeed-host"))

    def _timed_place(self, batch, sharding=None, **kw):
        t0 = time.perf_counter()
        out = self._place(batch, sharding=sharding, **kw)
        self.timings.add("h2d", time.perf_counter() - t0)
        return out

    def _placed(self, items, place_fn):
        """Run ``place_fn`` over ``items`` — through the async
        :class:`DevicePrefetcher` stage when ``prefetch_to_device`` > 0,
        inline otherwise. Same values in the same order either way; the
        async stage only moves the work off the consumer's critical path."""
        if self.prefetch_to_device <= 0:
            for item in items:
                yield place_fn(item)
            return
        yield from DevicePrefetcher(
            items, fn=place_fn, depth=self.prefetch_to_device,
            name="devicefeed-device")

    def __iter__(self):
        yield from self._placed(self._host_batches(), self._timed_place)

    def chained(self, k: int):
        """Yield ``(placed_stack, n)``: up to ``k`` host batches stacked on a
        new leading (scan) dim and placed with ONE transfer — the inputs of a
        ``lax.scan``-chained train dispatch. On a remote-tunnel backend each
        dispatch+fetch costs a full round trip (~64 ms measured), so chaining
        k steps divides that overhead by k. The scan dim is unsharded; the
        batch dim keeps the feed's data sharding. A smaller final stack (the
        epoch remainder) compiles once more and is otherwise fine.

        With ``prefetch_to_device`` > 0 the stack assembly (the ``stage``
        phase) AND the placement run on the device-prefetch thread, so both
        overlap the consumer's dispatched compute."""
        if k <= 1:
            for batch in self:
                yield batch, 1
            return
        stacked_sharding = stacked_seq = None
        if self._sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            stacked_sharding = NamedSharding(
                self.mesh, PartitionSpec(None, *tuple(self._sharding.spec)))
            if self._seq_sharding is not None:
                stacked_seq = NamedSharding(
                    self.mesh,
                    PartitionSpec(None, *tuple(self._seq_sharding.spec)))

        def _rows(b: Dict[str, np.ndarray]) -> int:
            return next(iter(b.values())).shape[0]

        def _stack(buf):
            t0 = time.perf_counter()
            stacked = {n: np.stack([b[n] for b in buf]) for n in buf[0]}
            self.timings.add("stage", time.perf_counter() - t0)
            return stacked, len(buf)

        def _stacks():
            buf: List[Dict[str, np.ndarray]] = []
            for batch in self._host_batches():
                if buf and _rows(batch) != _rows(buf[0]):
                    # ragged batch (the drop_remainder=False epoch tail): it
                    # cannot stack with full batches — flush what we have,
                    # then let it travel alone
                    yield _stack(buf)
                    buf = []
                buf.append(batch)
                if len(buf) == k:
                    yield _stack(buf)
                    buf = []
            if buf:
                yield _stack(buf)

        def _place_stack(item):
            stacked, n = item
            # the stack dim shifts everything right: a seq dim now sits at
            # axis 2, and a stacked 1-D label is ndim-2 — hence the 3 floor
            return self._timed_place(stacked, sharding=stacked_sharding,
                                     seq_sharding=stacked_seq,
                                     min_seq_ndim=3), n

        yield from self._placed(_stacks(), _place_stack)
