"""raydp_tpu.etl — the Arrow-native distributed DataFrame engine.

This is the build's answer to the reference's embedded Spark: the reference runs
stock Spark with its executors hosted in Ray actors (SURVEY.md §1 L2;
RayAppMaster.scala, RayDPExecutor.scala); we provide a from-scratch, Arrow-native
engine with the DataFrame surface the reference's examples actually use
(select/filter/withColumn/groupBy-agg/join/randomSplit/read.csv/parquet — see
examples/data_process.py, examples/pytorch_nyctaxi.py). Partitions are Arrow
tables; compute is ``pyarrow.compute`` on executor actors; wide operators hash-
shuffle through the shared-memory object store; cached frames are recoverable via
lineage (the ``prepareRecoverableRDD`` dance, ObjectStoreWriter.scala:164-204).
"""

from raydp_tpu.etl.expressions import col, lit, when
from raydp_tpu.etl.frame import DataFrame
from raydp_tpu.etl.session import Session
from raydp_tpu.etl import functions

__all__ = ["col", "lit", "when", "DataFrame", "Session", "functions"]
