"""Elastic executor pool: the driver-side autoscale controller.

RayDP's core cluster-lifecycle capability is elastic executor semantics —
executors join and leave a live session without losing work (PAPER.md §(a),
``requestExecutors`` / ``killExecutors`` in the reference's dynamic
allocation). :class:`PoolAutoscaler` is that controller for this runtime:
a thread that samples :meth:`ExecutorPool.load` once per tick and grows or
shrinks the pool between ``RDT_POOL_MIN`` and ``RDT_POOL_MAX``:

- **grow** when queued demand (outstanding tasks beyond what the pool has
  in flight) persists for ``RDT_POOL_SCALE_UP_S`` — a sustained window, so
  a recovery-induced spike (lineage rounds resubmitting a stage) never
  spawns an executor by itself. New executors spawn through the session's
  ordinary launch path (the node agent on remote nodes) and are admitted
  only after the ``RDT_EXECUTOR_WAIT_S`` readiness probe absorbs their
  import warm-up — a half-started executor never enters rotation.
- **shrink** when the pool has been fully idle (zero busy, zero queued)
  for ``RDT_POOL_IDLE_S``, by GRACEFUL DRAIN (:meth:`Engine.
  retire_executor` via :meth:`Session.retire_executor`): out of rotation,
  in-flight work finishes, cached blocks re-home or abandon to lineage,
  then the node agent reaps the process.
- **hysteresis**: ``RDT_POOL_COOLDOWN_S`` after any scale event, plus the
  sustained windows above, so scale-up and the load it sheds cannot chase
  each other. One signal pierces BOTH dampeners: PARKED admission demand.
  Admission parks an action only after the backlog bound is already
  exceeded, so the demand is proven — a post-shrink cooldown that kept
  parked work waiting would be self-inflicted queueing delay.
- **predictive sizing**: a grow decision targets the demand it can see
  instead of stepping +1 — one slot per parked admission, and (when
  ``RDT_POOL_BYTES_PER_EXEC`` is set) enough executors for the AQE
  plane's measured per-stage bytes. Each tick also feeds those measured
  bytes to the store's budget derivation (:meth:`Engine.
  derive_store_budgets`), so eviction pressure tracks the plan the
  engine is actually running.

The ``pool.scale`` fault site fires at every scale decision (key:
``"up"``/``"down"``); ``delay`` models a slow spawn/control plane.

Every knob is re-read per tick, so tests and benches flip cadence at
runtime (the per-action contract of doc/dev_lint.md).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from raydp_tpu import faults, knobs, metrics
from raydp_tpu.log import get_logger

logger = get_logger("etl.autoscale")


class PoolAutoscaler:
    """Grow/shrink a session's executor pool from its scheduling load.

    Construct via :meth:`Session.autoscale`. ``events`` is a bounded
    in-order record of every scale decision ({ts, direction, size, reason})
    — what the scale bench and tests assert on.
    """

    def __init__(self, session, min_size: Optional[int] = None,
                 max_size: Optional[int] = None):
        self._session = session
        self._min_arg = min_size
        self._max_arg = max_size
        mn, mx = self._bounds()
        if mx < max(1, mn):
            raise ValueError(
                f"autoscale needs max_size >= min_size >= 1 (got min={mn}, "
                f"max={mx}); set RDT_POOL_MAX or pass max_size=")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cooldown_until = 0.0
        self._queued_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._parked_since: Optional[float] = None
        self.events: List[Dict[str, Any]] = []
        self._events_cap = 256

    # ---- knob views (re-read per tick) --------------------------------------
    def _bounds(self) -> tuple:
        mn = self._min_arg if self._min_arg is not None \
            else int(knobs.get("RDT_POOL_MIN"))
        mx = self._max_arg if self._max_arg is not None \
            else int(knobs.get("RDT_POOL_MAX"))
        return max(1, mn), mx

    def set_bounds(self, min_size: Optional[int] = None,
                   max_size: Optional[int] = None) -> None:
        """Adjust the live controller's bounds (effective next tick; a
        ``None`` leaves that bound as it was)."""
        old = (self._min_arg, self._max_arg)
        if min_size is not None:
            self._min_arg = min_size
        if max_size is not None:
            self._max_arg = max_size
        mn, mx = self._bounds()
        if mx < max(1, mn):
            self._min_arg, self._max_arg = old
            raise ValueError(
                f"autoscale needs max_size >= min_size >= 1 (got min={mn}, "
                f"max={mx})")
        logger.info("pool autoscaler bounds now min=%d, max=%d", mn, mx)

    # ---- lifecycle ----------------------------------------------------------
    def start(self) -> "PoolAutoscaler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="rdt-pool-autoscaler")
        self._thread.start()
        logger.info("pool autoscaler started (min=%d, max=%d)",
                    *self._bounds())
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(
                max(0.05, float(knobs.get("RDT_POOL_SCALE_INTERVAL_S")))):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - the controller must survive
                logger.exception("autoscale tick failed; continuing")

    # ---- one decision -------------------------------------------------------
    def _tick(self) -> None:
        engine = self._session.engine
        if engine is None:
            return  # session not started (or already torn down)
        pool = engine.pool
        # AQE store-budget feed: re-derive per-host budgets from the stage
        # ledger's measured bytes (no-op when RDT_STORE_AQE_BUDGET is off,
        # the ledger is empty, or the measurement has not changed); getattr:
        # unit harnesses drive the controller against bare engine stubs
        derive = getattr(engine, "derive_store_budgets", None)
        if derive is not None:
            derive()
        load = pool.load()
        now = time.monotonic()
        live = load["live"]
        metrics.set_gauge("pool_size", live)
        mn, mx = self._bounds()
        # sustained-signal windows update even inside the cooldown, so a
        # queue that built up DURING the cooldown acts the moment it ends
        if load["queued"] > 0:
            self._queued_since = self._queued_since or now
            self._idle_since = None
        elif load["busy"] == 0:
            self._idle_since = self._idle_since or now
            self._queued_since = None
        else:
            self._queued_since = None
            self._idle_since = None
        parked = int(load.get("parked", 0) or 0)
        if parked > 0:
            self._parked_since = self._parked_since or now
        else:
            self._parked_since = None
        # PARKED admission demand pierces both dampeners (the post-scale
        # cooldown and the sustained-queue window): admission parks an
        # action only once the backlog bound is already exceeded, so the
        # demand signal is proven — the hysteresis that protects against
        # recovery spikes does not apply. One PRIOR tick of parked demand
        # is still required (strictly older than this tick), so the gap
        # between a finished grow and admission's unpark can't double-spawn.
        parked_grow = (parked > 0 and live < mx
                       and self._parked_since is not None
                       and self._parked_since < now)
        if now < self._cooldown_until and not parked_grow:
            return
        if parked_grow or (self._queued_since is not None and live < mx
                           and now - self._queued_since
                           >= float(knobs.get("RDT_POOL_SCALE_UP_S"))):
            self._grow(load, live)
        elif self._idle_since is not None and live > mn \
                and now - self._idle_since \
                >= float(knobs.get("RDT_POOL_IDLE_S")):
            self._shrink(load, live)

    def _note(self, direction: str, size: int, reason: str) -> None:
        self._cooldown_until = time.monotonic() + \
            float(knobs.get("RDT_POOL_COOLDOWN_S"))
        self._queued_since = None
        self._idle_since = None
        self._parked_since = None
        ev = {"ts": time.time(), "direction": direction, "size": size,
              "reason": reason}
        self.events.append(ev)
        del self.events[:-self._events_cap]
        metrics.record_event("pool_scale", direction=direction, size=size,
                            reason=reason)

    def _apply_scale_fault(self, key: str, live: int) -> None:
        """Fire the pool.scale site; an injected raise still pays the
        cooldown (the documented contract: the decision fails and retries
        after the cooldown, never every tick)."""
        rule = faults.check("pool.scale", key=key)
        if rule is None:
            return
        try:
            faults.apply(rule, "pool.scale")
        except Exception:
            self._note(f"{key}-failed", live, "injected fault")
            raise

    def _grow(self, load: Dict[str, Any], live: int) -> None:
        self._apply_scale_fault("up", live)
        target = self._grow_target(load, live)
        reason = (f"queued={load['queued']} busy={load['busy']} "
                  f"parked={load.get('parked', 0)} target={target}")
        logger.info("autoscale: growing pool %d -> %d (%s)",
                    live, target, reason)
        grown = 0
        for _ in range(target - live):
            handle = self._session._grow_executor()
            if handle is None:
                # spawn/readiness failed: stop here and cool down so a
                # broken control plane is retried at the hysteresis
                # cadence, not every tick
                break
            grown += 1
            metrics.inc("pool_scaled_up_total")
        if grown == 0:
            self._note("up-failed", live, reason)
            return
        self._note("up", live + grown, reason)

    def _grow_target(self, load: Dict[str, Any], live: int) -> int:
        """Predictive pool size for one grow decision: at least the classic
        +1 step, raised to one free slot per PARKED admission (none of them
        is released until capacity exists) and — when the operator sized
        ``RDT_POOL_BYTES_PER_EXEC`` — to enough executors for the AQE
        plane's measured per-stage bytes. Always capped at the max bound."""
        _, mx = self._bounds()
        target = live + 1
        parked = int(load.get("parked", 0) or 0)
        if parked > 0:
            target = max(target, live + parked)
        per_exec = int(knobs.get("RDT_POOL_BYTES_PER_EXEC") or 0)
        measure = getattr(self._session.engine, "measured_stage_bytes", None)
        if per_exec > 0 and measure is not None:
            measured = int(measure() or 0)
            if measured > 0:
                target = max(target, -(-measured // per_exec))
        return min(mx, max(target, live + 1))

    def _shrink(self, load: Dict[str, Any], live: int) -> None:
        victim = self._session._shrink_candidate()
        if victim is None:
            return
        self._apply_scale_fault("down", live)
        logger.info("autoscale: draining idle executor %s (pool %d -> %d)",
                    victim, live, live - 1)
        try:
            self._session.retire_executor(victim)
        except Exception:
            logger.warning("autoscale drain of %s failed", victim,
                           exc_info=True)
            self._note("down-failed", live, "idle")
            return
        metrics.inc("pool_scaled_down_total")
        self._note("down", live - 1, "idle")
