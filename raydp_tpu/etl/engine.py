"""The driver-side execution engine: plan → stages → tasks on executor actors.

This plays the role Spark's driver plays for the reference: it splits the plan at
wide operators, schedules partition tasks onto executor actors with locality (a
cached block's task prefers the executor holding it, like ``getBlockLocations``
routing in ObjectStoreWriter.scala:196-202), bounds in-flight work per executor,
and retries failed tasks — possible on any executor because tasks are lineage
recipes (SURVEY.md §5 failure-detection subsystem).
"""

from __future__ import annotations

import collections
import math
import os
import threading
import uuid
from concurrent.futures import FIRST_COMPLETED, wait
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from raydp_tpu import profiler
from raydp_tpu.etl import optimizer as O
from raydp_tpu.etl import plan as P
from raydp_tpu.etl import tasks as T
from raydp_tpu.etl.expressions import col as _col
from raydp_tpu.log import get_logger
from raydp_tpu.runtime.actor import ActorHandle
from raydp_tpu.runtime.object_store import ObjectRef, get_client
from raydp_tpu.runtime.rpc import ConnectionLost, RemoteError

logger = get_logger("etl.engine")


class StageError(RuntimeError):
    pass


def _root_limit(node: P.PlanNode) -> Optional[int]:
    """The global row cap when the plan's root is a ``Limit`` (possibly under
    other per-row-preserving narrow ops). The compiled LimitStep truncates each
    partition; the action applies the exact global cut."""
    while isinstance(node, (P.Rename,)):
        node = node.child
    return node.n if isinstance(node, P.Limit) else None


# deterministic application failures: retrying replays the same exception, so
# fail fast with the original error instead of burning the retry budget
_NO_RETRY_EXC_TYPES = {
    "KeyError", "ValueError", "TypeError", "AttributeError", "IndexError",
    "ZeroDivisionError", "ArrowInvalid", "ArrowNotImplementedError",
    "ArrowKeyError", "ArrowTypeError",
}


class ExecutorPool:
    """Round-robin scheduler over executor actor handles with retry.

    Retry parity: the reference's fetch tasks run with ``max_retries=-1``
    (dataset.py:54) and executor actors revive with ``maxRestarts=-1``; we retry a
    bounded-but-generous number of times, re-resolving the actor between attempts
    (a restarted actor keeps its name at a new address).
    """

    def __init__(self, executors: List[ActorHandle], max_task_retries: int = 8,
                 hosts_by_name: Optional[Dict[str, str]] = None):
        if not executors:
            raise ValueError("executor pool is empty")
        self.executors = list(executors)
        self.by_name = {h.name: h for h in executors}
        self.max_task_retries = max_task_retries
        #: executor name → data-plane host id (machine), for locality routing
        self.hosts_by_name: Dict[str, str] = dict(hosts_by_name or {})
        self._names_by_host: Dict[str, List[str]] = {}
        for h in self.executors:
            if h.name and h.name in self.hosts_by_name:
                self._names_by_host.setdefault(
                    self.hosts_by_name[h.name], []).append(h.name)
        self._rr = 0
        self._local_rr: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _next_executor(self) -> ActorHandle:
        with self._lock:
            h = self.executors[self._rr % len(self.executors)]
            self._rr += 1
            return h

    def multi_host(self) -> bool:
        """True when executors span machines — only then is locality routing
        worth overriding round-robin balance."""
        return len(set(self.hosts_by_name.values())) > 1

    def pick_local(self, host_id: str) -> Optional[str]:
        """An executor on ``host_id`` (round-robin among that machine's
        executors for balance), or None when none runs there."""
        names = self._names_by_host.get(host_id)
        if not names:
            return None
        with self._lock:
            i = self._local_rr.get(host_id, 0)
            self._local_rr[host_id] = i + 1
        return names[i % len(names)]

    def run_tasks(
        self,
        tasks: Sequence[T.Task],
        preferred: Optional[Sequence[Optional[str]]] = None,
        max_inflight_per_executor: int = 4,
    ) -> List[Dict[str, Any]]:
        """Run tasks, preserving order of results; blocks until all complete."""
        n = len(tasks)
        results: List[Optional[Dict[str, Any]]] = [None] * n
        attempts = [0] * n
        max_inflight = max(1, max_inflight_per_executor * len(self.executors))
        pending: Dict[Any, Tuple[int, str]] = {}
        next_idx = 0

        def _submit(i: int):
            name = None
            if preferred is not None and preferred[i] is not None \
                    and attempts[i] == 0:
                name = preferred[i]
            handle = self.by_name.get(name) if name else None
            if handle is None:
                handle = self._next_executor()
            payload = cloudpickle.dumps(tasks[i])
            try:
                fut = handle.submit("run_task", payload)
            except (ConnectionLost, OSError) as e:
                raise StageError(f"cannot reach executor {handle.name}: {e}") from e
            pending[fut] = (i, handle.name or "")

        while next_idx < n and len(pending) < max_inflight:
            _submit(next_idx)
            next_idx += 1

        while pending:
            done, _ = wait(list(pending.keys()), return_when=FIRST_COMPLETED)
            for fut in done:
                i, ename = pending.pop(fut)
                err = fut.exception()
                if err is None:
                    results[i] = fut.result()
                else:
                    attempts[i] += 1
                    if (isinstance(err, RemoteError)
                            and err.exc_type in _NO_RETRY_EXC_TYPES):
                        raise StageError(
                            f"task {tasks[i].task_id} failed: {err}") from err
                    if attempts[i] > self.max_task_retries:
                        raise StageError(
                            f"task {tasks[i].task_id} failed after "
                            f"{attempts[i]} attempts: {err}") from err
                    logger.warning("task %s failed on %s (attempt %d): %s",
                                   tasks[i].task_id, ename, attempts[i],
                                   str(err).splitlines()[0] if str(err) else err)
                    _submit(i)
            while next_idx < n and len(pending) < max_inflight:
                _submit(next_idx)
                next_idx += 1
        return results  # type: ignore[return-value]


class Engine:
    """Thread-safe: shuffle intermediates are tracked in a per-action list
    threaded through compilation (two concurrent actions on one session must
    not cross-free each other's intermediates — the reference's Spark driver
    supports concurrent actions)."""

    def __init__(self, pool: ExecutorPool, shuffle_partitions: int = 8,
                 owner: Optional[str] = None):
        self.pool = pool
        self.shuffle_partitions = shuffle_partitions
        self.owner = owner
        self._report_lock = threading.Lock()
        # bounded per-engine shuffle-stage ledger (one entry per wide-op
        # stage); benchmarks and tests read it through shuffle_stage_report()
        self._stage_reports: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=256)

    # ---- shuffle accounting -------------------------------------------------
    def _record_stage(self, label: str, results: Sequence[Dict[str, Any]],
                      num_buckets: int) -> None:
        """Aggregate map-task shuffle counters into one stage entry and emit
        a driver-side trace span carrying the totals as args."""
        rows = sum(int(r.get("num_rows", 0)) for r in results)
        nbytes = sum(int(r.get("shuffle_bytes", 0)) for r in results)
        rows_in = sum(int(r.get("shuffle_rows_in", r.get("num_rows", 0)))
                      for r in results)
        bytes_in = sum(int(r.get("shuffle_bytes_in", 0)) for r in results)
        entry = {"stage": label, "maps": len(results),
                 "buckets": num_buckets,
                 "rows_in": rows_in, "bytes_in": bytes_in,
                 "rows_shuffled": rows, "bytes_shuffled": nbytes}
        with self._report_lock:
            self._stage_reports.append(entry)
        with profiler.trace(f"shuffle:{label}", "etl", maps=len(results),
                            buckets=num_buckets, rows_in=rows_in,
                            bytes_in=bytes_in, rows_shuffled=rows,
                            bytes_shuffled=nbytes):
            pass

    def shuffle_stage_report(self) -> List[Dict[str, Any]]:
        """Per-stage shuffle ledger: one dict per wide-op stage executed by
        this engine ({stage, maps, buckets, rows_in, bytes_in, rows_shuffled,
        bytes_shuffled}); in = entering the shuffle stage (before map-side
        partial aggregation), shuffled = what crossed the object store."""
        with self._report_lock:
            return [dict(e) for e in self._stage_reports]

    def reset_shuffle_stage_report(self) -> None:
        with self._report_lock:
            self._stage_reports.clear()

    @staticmethod
    def _optimized(node: P.PlanNode) -> P.PlanNode:
        """Plan rewrite applied at every action entry point; the naive
        compile-verbatim path survives under RDT_ETL_OPTIMIZER=0."""
        return O.optimize(node)

    def _num_buckets(self) -> int:
        """Reduce-side bucket count for wide operators: capped by the
        configured shuffle parallelism, scaled to the executor pool."""
        return min(self.shuffle_partitions, max(1, len(self.pool.executors) * 2))

    @staticmethod
    def _gather_buckets(results: Sequence[Dict[str, Any]], num_buckets: int,
                        temps: List[ObjectRef]) -> List[List[ObjectRef]]:
        """Transpose map-task shuffle outputs (map × bucket → bucket × map),
        registering every intermediate ref in ``temps``."""
        buckets: List[List[ObjectRef]] = [[] for _ in range(num_buckets)]
        for r in results:
            for b, ref in enumerate(r["bucket_refs"]):
                buckets[b].append(ref)
                temps.append(ref)
        return buckets

    @staticmethod
    def _free(temps: List[ObjectRef]) -> None:
        if temps:
            try:
                get_client().free(temps)
            except Exception:
                logger.warning("failed to free %d shuffle intermediates", len(temps))

    # ---- public entry points ------------------------------------------------
    def materialize(self, node: P.PlanNode, owner: Optional[str] = None
                    ) -> Tuple[List[ObjectRef], Optional[bytes], List[int]]:
        """Execute the plan; return per-partition (refs, schema bytes, row counts)."""
        temps: List[ObjectRef] = []
        try:
            return self._materialize_inner(self._optimized(node), owner, temps)
        finally:
            self._free(temps)

    def _materialize_inner(self, node: P.PlanNode, owner: Optional[str],
                           temps: List[ObjectRef]):
        tasks, preferred = self._compile(node, temps)
        tasks = [t.with_output(output=T.RETURN_REF, owner=owner or self.owner)
                 for t in tasks]
        results = self.pool.run_tasks(tasks, preferred)
        refs = [r["ref"] for r in results]
        schema = results[0]["schema"] if results else None
        num_rows = [r["num_rows"] for r in results]
        return refs, schema, num_rows

    def collect(self, node: P.PlanNode) -> pa.Table:
        temps: List[ObjectRef] = []
        try:
            tasks, preferred = self._compile(self._optimized(node), temps)
            tasks = [t.with_output(output=T.COLLECT) for t in tasks]
            results = self.pool.run_tasks(tasks, preferred)
            tables = [pa.ipc.open_stream(pa.py_buffer(r["ipc"])).read_all()
                      for r in results]
            out = pa.concat_tables(tables, promote_options="permissive")
            limit = _root_limit(node)
            return out.slice(0, limit) if limit is not None else out
        finally:
            self._free(temps)

    def count(self, node: P.PlanNode) -> int:
        temps: List[ObjectRef] = []
        try:
            tasks, preferred = self._compile(self._optimized(node), temps)
            tasks = [t.with_output(output=T.ROWCOUNT) for t in tasks]
            results = self.pool.run_tasks(tasks, preferred)
            total = sum(r["num_rows"] for r in results)
            limit = _root_limit(node)
            return min(total, limit) if limit is not None else total
        finally:
            self._free(temps)

    def cache(self, node: P.PlanNode, frame_id: str) -> P.CachedScan:
        """Materialize into executor block caches with lineage recipes.

        Parity: ``prepareRecoverableRDD`` = persist + count + pin + locations map
        (ObjectStoreWriter.scala:164-204). The returned ``CachedScan`` carries,
        per partition: the cache key, the executor that holds it, and the pickled
        recipe that can rebuild it anywhere. Shuffle intermediates feeding the
        cached plan are pinned (not freed) because the lineage recipes reference
        them — they are released with the frame (the GC-pin of
        ObjectStoreWriter.scala:175-177).
        """
        temps: List[ObjectRef] = []
        try:
            tasks, preferred = self._compile(self._optimized(node), temps)
            cache_tasks, recover_blobs, keys = [], [], []
            for i, t in enumerate(tasks):
                key = f"block_{frame_id}_{i}"
                recover = t.with_output(output=T.RETURN_REF)
                recover_blobs.append(cloudpickle.dumps(recover))
                keys.append(key)
                cache_tasks.append(t.with_output(output=T.CACHE, cache_key=key))
            results = self.pool.run_tasks(cache_tasks, preferred)
        except BaseException:
            self._free(temps)
            raise
        executors = [r["executor"] for r in results]
        schema = results[0]["schema"] if results else None
        # temps stay pinned: the lineage recipes reference them
        return P.CachedScan(frame_id=frame_id, cache_keys=keys,
                            executors=executors, recover_tasks=recover_blobs,
                            schema=schema, pinned_refs=temps)

    def random_shuffle_refs(self, refs: Sequence[ObjectRef],
                            schema_bytes: Optional[bytes],
                            seed: Optional[int],
                            owner: Optional[str] = None,
                            ) -> Tuple[List[ObjectRef], List[int]]:
        """Executor-side uniform shuffle of materialized blocks.

        Two stages over the store data plane — map: seeded random bucketing
        of each block (:func:`tasks.random_buckets`); reduce: concat each
        bucket + in-partition permutation (:class:`tasks.LocalShuffleStep`).
        The driver handles ONLY refs: no row ever crosses the driver process
        (the reference's shuffle is likewise distributed — ray.data
        random_shuffle at torch/estimator.py:335-338). Returns (refs, rows)
        per output block; intermediates are freed before returning.
        """
        temps: List[ObjectRef] = []
        try:
            nb = max(1, len(refs))
            base = 0 if seed is None else int(seed)
            map_tasks = [
                self._task(T.ArrowRefSource([r], schema=schema_bytes))
                .with_output(output=T.SHUFFLE, num_buckets=nb,
                             shuffle_seed=(base * 1_000_003 + i) & 0x7FFFFFFF,
                             owner=self.owner)
                for i, r in enumerate(refs)
            ]
            results = self.pool.run_tasks(
                map_tasks, self._locality([[r] for r in refs]))
            self._record_stage("random-shuffle", results, nb)
            buckets = self._gather_buckets(results, nb, temps)
            reduce_tasks = [
                self._task(T.ArrowRefSource(bucket, schema=schema_bytes),
                           [T.LocalShuffleStep(
                               (base * 9_176 + 77 + b) & 0x7FFFFFFF)])
                .with_output(output=T.RETURN_REF, owner=owner or self.owner)
                for b, bucket in enumerate(buckets)
            ]
            out = self.pool.run_tasks(reduce_tasks, self._locality(buckets))
            return [r["ref"] for r in out], [r["num_rows"] for r in out]
        finally:
            self._free(temps)

    def num_partitions(self, node: P.PlanNode) -> int:
        temps: List[ObjectRef] = []
        try:
            tasks, _ = self._compile(self._optimized(node), temps)
            return len(tasks)
        finally:
            self._free(temps)

    # ---- compilation --------------------------------------------------------
    def _compile(self, node: P.PlanNode, temps: List[ObjectRef]
                 ) -> Tuple[List[T.Task], List[Optional[str]]]:
        """Return (tasks, preferred-executor-per-task); shuffle intermediates
        created along the way are appended to ``temps`` (per-action list)."""
        if isinstance(node, P.RangeScan):
            per = math.ceil((node.stop - node.start) / max(node.step, 1)
                            / node.num_partitions)
            tasks = []
            for i in range(node.num_partitions):
                lo = node.start + i * per * node.step
                hi = min(node.start + (i + 1) * per * node.step, node.stop)
                tasks.append(self._task(T.RangeSource(lo, hi, node.step, node.column)))
            return tasks, [None] * len(tasks)

        if isinstance(node, P.CsvScan):
            return self._compile_csv(node)

        if isinstance(node, P.ParquetScan):
            return self._compile_parquet(node)

        if isinstance(node, P.InMemory):
            tasks = [self._task(T.ArrowRefSource([ref], schema=node.schema))
                     for ref in node.refs]
            return tasks, self._locality([[ref] for ref in node.refs])

        if isinstance(node, P.CachedScan):
            tasks, preferred = [], []
            for key, executor, recover in zip(
                    node.cache_keys, node.executors, node.recover_tasks):
                rec_task: T.Task = cloudpickle.loads(recover)
                tasks.append(self._task(T.CachedSource(key, rec_task)))
                preferred.append(executor)
            return tasks, preferred

        # ---- narrow unary: fuse into child's task chains ----
        narrow = {
            P.Project: lambda n: T.ProjectStep(n.columns),
            P.Filter: lambda n: T.FilterStep(n.predicate),
            P.DropNa: lambda n: T.DropNaStep(n.subset),
            P.Limit: lambda n: T.LimitStep(n.n),
            P.Rename: lambda n: T.RenameStep(n.mapping),
        }
        for cls, make in narrow.items():
            if isinstance(node, cls):
                tasks, preferred = self._compile(node.child, temps)
                step = make(node)
                return [t.with_output(steps=t.steps + [step]) for t in tasks], preferred

        if isinstance(node, P.Sample):
            tasks, preferred = self._compile(node.child, temps)
            out = [t.with_output(steps=t.steps + [
                T.SampleStep(node.fraction, node.seed, i)])
                for i, t in enumerate(tasks)]
            return out, preferred

        if isinstance(node, P.SplitSelect):
            tasks, preferred = self._compile(node.child, temps)
            out = [t.with_output(steps=t.steps + [
                T.SplitSelectStep(node.lo, node.hi, node.seed, i)])
                for i, t in enumerate(tasks)]
            return out, preferred

        # ---- wide: execute child, shuffle through the object store ----
        if isinstance(node, P.Repartition):
            return self._compile_repartition(node, temps)

        if isinstance(node, P.GroupAgg):
            return self._compile_groupagg(node, temps)

        if isinstance(node, P.Join):
            return self._compile_join(node, temps)

        if isinstance(node, P.Sort):
            return self._compile_sort(node, temps)

        if isinstance(node, P.Distinct):
            return self._compile_distinct(node, temps)

        if isinstance(node, P.WindowOp):
            return self._compile_window(node, temps)

        if isinstance(node, P.Union):
            all_tasks, all_pref = [], []
            for child in node.inputs:
                tasks, preferred = self._compile(child, temps)
                all_tasks.extend(tasks)
                all_pref.extend(preferred)
            return all_tasks, all_pref

        raise TypeError(f"unknown plan node {type(node).__name__}")

    # ---- leaves -------------------------------------------------------------
    def _task(self, source: T.Step, steps: Optional[List[T.Step]] = None) -> T.Task:
        return T.Task(task_id=f"t-{uuid.uuid4().hex[:10]}", source=source,
                      steps=steps or [])

    def _locality(self, ref_lists: Sequence[Sequence[Optional[ObjectRef]]]
                  ) -> List[Optional[str]]:
        """Preferred executor per ref-reading task: one on the machine holding
        the most input bytes. One bulk ``locations`` RPC; a no-op on
        single-machine pools so round-robin balance is untouched. Parity:
        preferred locations from block owner addresses
        (RayDatasetRDD.scala:48-56, RayDPExecutor.scala:271-287)."""
        if not self.pool.multi_host():
            return [None] * len(ref_lists)
        try:
            seen: Dict[str, ObjectRef] = {}
            for refs in ref_lists:
                for r in refs:
                    if r is not None:
                        seen[r.id] = r
            locs = get_client().locations(list(seen.values()))
        except Exception:
            return [None] * len(ref_lists)
        preferred: List[Optional[str]] = []
        for refs in ref_lists:
            weight: Dict[str, int] = {}
            for r in refs:
                host = locs.get(r.id) if r is not None else None
                if host is not None:
                    weight[host] = weight.get(host, 0) + max(r.size, 1)
            if not weight:
                preferred.append(None)
                continue
            best = max(weight, key=weight.get)
            preferred.append(self.pool.pick_local(best))
        return preferred

    def _compile_csv(self, node: P.CsvScan):
        tasks = []
        headerless = bool((node.options or {}).get("column_names"))
        for path in node.paths:
            size = os.path.getsize(path)
            if headerless:
                header = b""  # first line is data (column names via options)
            else:
                with open(path, "rb") as f:
                    header = f.readline()
            body = size - len(header)
            nparts = node.num_partitions or max(
                1, min(self.shuffle_partitions, body // (8 << 20) + 1))
            per = math.ceil(body / nparts) if body > 0 else 1
            for i in range(nparts):
                start = len(header) + i * per
                end = min(len(header) + (i + 1) * per, size)
                if start >= size:
                    break
                tasks.append(self._task(T.CsvSliceSource(
                    path, start if i > 0 else 0, end, header, node.options)))
        return tasks, [None] * len(tasks)

    def _compile_parquet(self, node: P.ParquetScan):
        import pyarrow.parquet as pq
        tasks = []
        for path in node.paths:
            f = pq.ParquetFile(path)
            for rg in range(f.num_row_groups):
                tasks.append(self._task(T.ParquetSource(path, [rg], node.columns)))
            if f.num_row_groups == 0:
                tasks.append(self._task(T.ParquetSource(path, None, node.columns)))
        return tasks, [None] * len(tasks)

    # ---- wide operators -----------------------------------------------------
    def _shuffle_children(self, node: P.PlanNode, num_buckets: int,
                          keys: Optional[List[str]], temps: List[ObjectRef],
                          range_key=None, pre_steps: Optional[List[T.Step]] = None,
                          label: str = "shuffle",
                          ) -> Tuple[List[List[ObjectRef]], Optional[bytes]]:
        """Execute ``node`` with SHUFFLE output; transpose map×bucket → bucket×map.

        ``pre_steps`` run on each map task AFTER the narrow chain and BEFORE
        bucketing (the hook map-side partial aggregation uses); ``label`` names
        the stage in the engine's shuffle ledger."""
        tasks, preferred = self._compile(node, temps)
        extra = list(pre_steps or [])
        tasks = [t.with_output(steps=t.steps + extra,
                               shuffle_pre_steps=len(extra),
                               output=T.SHUFFLE, num_buckets=num_buckets,
                               shuffle_keys=keys, range_key=range_key,
                               owner=self.owner)
                 for t in tasks]
        results = self.pool.run_tasks(tasks, preferred)
        self._record_stage(label, results, num_buckets)
        schema = results[0]["schema"] if results else None
        return self._gather_buckets(results, num_buckets, temps), schema

    def _compile_repartition(self, node: P.Repartition, temps: List[ObjectRef]):
        n = node.num_partitions
        if not node.shuffle:
            # coalesce: group existing partitions without moving rows by key
            refs, schema, _ = self._materialize_inner(node.child, None, temps)
            temps.extend(refs)
            groups = [[refs[i] for i in g]
                      for g in np.array_split(np.arange(len(refs)), n)
                      if len(g) > 0]
            tasks = [self._task(T.ArrowRefSource(group, schema=schema))
                     for group in groups]
            return tasks, self._locality(groups)
        buckets, schema = self._shuffle_children(node.child, n, keys=None,
                                                 temps=temps, label="repartition")
        tasks = [self._task(T.ArrowRefSource(bucket, schema=schema))
                 for bucket in buckets]
        return tasks, self._locality(buckets)

    def _compile_groupagg(self, node: P.GroupAgg, temps: List[ObjectRef]):
        nb = self._num_buckets()
        decomposable = all(f in O.DECOMPOSABLE_AGGS for _, f, _ in node.aggs)
        if O.enabled() and decomposable:
            # two-phase aggregation: partials computed map-side BEFORE the
            # shuffle, so one row per (map task, key) crosses the store; the
            # reduce side merges partials (mean = sum-of-sums / sum-of-counts)
            partials, merges = T.decompose_aggs(node.aggs)
            buckets, schema = self._shuffle_children(
                node.child, nb, keys=node.keys, temps=temps,
                pre_steps=[T.GroupAggPartialStep(node.keys, partials)],
                label="groupagg-partial")
            tasks = [self._task(T.ArrowRefSource(bucket, schema=schema),
                                [T.GroupAggMergeStep(node.keys, merges)])
                     for bucket in buckets]
            return tasks, self._locality(buckets)
        buckets, schema = self._shuffle_children(node.child, nb, keys=node.keys,
                                                 temps=temps, label="groupagg")
        tasks = [self._task(T.ArrowRefSource(bucket, schema=schema),
                            [T.GroupAggStep(node.keys, node.aggs)])
                 for bucket in buckets]
        return tasks, self._locality(buckets)

    def _compile_join(self, node: P.Join, temps: List[ObjectRef]):
        nb = self._num_buckets()
        left_buckets, lschema = self._shuffle_children(node.left, nb, node.keys,
                                                       temps, label="join-left")
        right_buckets, rschema = self._shuffle_children(node.right, nb,
                                                        node.right_keys, temps,
                                                        label="join-right")
        tasks = []
        for lb, rb in zip(left_buckets, right_buckets):
            tasks.append(self._task(
                T.ArrowRefSource(lb, schema=lschema),
                [T.HashJoinStep(rb, node.keys, node.right_keys, node.how,
                                right_schema=rschema)]))
        # a join task reads BOTH sides' buckets: weight locality over them
        return tasks, self._locality([list(lb) + list(rb) for lb, rb
                                      in zip(left_buckets, right_buckets)])

    def _compile_sort(self, node: P.Sort, temps: List[ObjectRef]):
        """Range-partitioned sort on the COMPOSITE key: materialize the child
        ONCE, sample boundary key-tuples from EVERY block on the executors
        (any orderable type — no numeric cast), range-shuffle those refs by
        lexicographic comparison, locally sort each range. Composite
        boundaries keep the partitioning balanced even when the first key has
        few distinct values (per-key boundaries would collapse there)."""
        keys = node.keys
        key_names = [k for k, _ in keys]
        refs, schema, num_rows = self._materialize_inner(node.child, None, temps)
        temps.extend(refs)

        # boundary sample: a bounded uniform sample over ALL blocks, taken by
        # the executors — sampling only the first blocks skews the range
        # boundaries on sorted or clustered input. Only the key columns
        # travel back to the driver.
        nb = self._num_buckets()
        total = sum(num_rows)
        target = max(1000, 100 * nb)
        frac = min(1.0, target / total) if total else 0.0
        sample_tasks = [
            self._task(T.ArrowRefSource([ref], schema=schema),
                       [T.SampleStep(frac, seed=0, partition_index=i),
                        T.ProjectStep([(k, _col(k)) for k in key_names])]
                       ).with_output(output=T.COLLECT)
            for i, (ref, n) in enumerate(zip(refs, num_rows)) if n > 0
        ]
        sampled = []
        if sample_tasks:
            for r in self.pool.run_tasks(sample_tasks):
                tbl = pa.ipc.open_stream(pa.py_buffer(r["ipc"])).read_all()
                if tbl.num_rows:
                    sampled.append(tbl)
        boundaries: List[Tuple] = []
        if sampled:
            sample = pa.concat_tables(sampled, promote_options="permissive")
            # rows with a null or NaN key need no boundary: both always sort
            # at the extreme (and either as a boundary value would poison
            # every comparison — NaN > x and NaN == x are both false)
            for k in key_names:
                column = sample.column(k)
                sample = sample.filter(pc.is_valid(column))
                column = sample.column(k)
                if pa.types.is_floating(column.type) and sample.num_rows:
                    sample = sample.filter(pc.invert(pc.is_nan(column)))
            if sample.num_rows:
                sample = sample.sort_by(keys)
                qpos = [int(q * (sample.num_rows - 1))
                        for q in np.linspace(0, 1, nb + 1)[1:-1]]
                cols = {k: sample.column(k) for k in key_names}
                for p in qpos:
                    tup = tuple(cols[k][p].as_py() for k in key_names)
                    if not boundaries or tup != boundaries[-1]:
                        boundaries.append(tup)

        shuffle_tasks = [
            self._task(T.ArrowRefSource([ref], schema=schema)).with_output(
                output=T.SHUFFLE, num_buckets=len(boundaries) + 1,
                range_key=(list(keys), boundaries),
                owner=self.owner)
            for ref in refs
        ]
        results = self.pool.run_tasks(shuffle_tasks)
        self._record_stage("sort-range", results, len(boundaries) + 1)
        buckets = self._gather_buckets(results, len(boundaries) + 1, temps)
        # buckets come out in global sort order for any direction mix (the
        # composite comparison honors per-key direction; nulls sort last)
        tasks = [self._task(T.ArrowRefSource(bucket, schema=schema),
                            [T.LocalSortStep(node.keys)])
                 for bucket in buckets]
        return tasks, self._locality(buckets)

    def _compile_distinct(self, node: P.Distinct, temps: List[ObjectRef]):
        """distinct / dropDuplicates: hash-shuffle on the key columns (the
        ``["*"]`` sentinel = full row, resolved executor-side), then local
        first-per-key dedupe — equal keys share a bucket, so local dedupe is
        globally exact."""
        nb = self._num_buckets()
        keys = list(node.subset) if node.subset else ["*"]
        buckets, schema = self._shuffle_children(node.child, nb, keys=keys,
                                                 temps=temps, label="distinct")
        tasks = [self._task(T.ArrowRefSource(bucket, schema=schema),
                            [T.DistinctStep(node.subset)])
                 for bucket in buckets]
        return tasks, self._locality(buckets)

    def _compile_window(self, node: P.WindowOp, temps: List[ObjectRef]):
        """Window function: equal partition keys share a bucket (hash
        shuffle), so per-bucket sorted evaluation is globally exact. Without
        partition keys everything collapses to one task (Spark's "No
        Partition Defined" single-partition path).

        Adjacent WindowOps over the SAME partition keys collapse into one
        shuffle feeding a chain of WindowSteps (innermost first) — Spark
        likewise evaluates same-spec window functions in a single exchange;
        the doc example chains three columns over one spec and must not pay
        three shuffles of the whole dataset."""
        def _step(w: P.WindowOp) -> T.WindowStep:
            return T.WindowStep(list(w.partition_keys), list(w.order_keys),
                                w.out_name, w.fn, w.arg_col,
                                w.offset, w.default)

        steps = [_step(node)]
        child = node.child
        while (isinstance(child, P.WindowOp)
               and list(child.partition_keys) == list(node.partition_keys)):
            steps.append(_step(child))
            child = child.child
        steps.reverse()  # innermost (first-defined) column computes first

        if node.partition_keys:
            nb = self._num_buckets()
            buckets, schema = self._shuffle_children(
                child, nb, keys=list(node.partition_keys), temps=temps,
                label="window")
            tasks = [self._task(T.ArrowRefSource(bucket, schema=schema),
                                list(steps))
                     for bucket in buckets]
            return tasks, self._locality(buckets)
        refs, schema, _ = self._materialize_inner(child, None, temps)
        temps.extend(refs)
        tasks = [self._task(T.ArrowRefSource(list(refs), schema=schema),
                            list(steps))]
        return tasks, self._locality([list(refs)])

    # ---- driver-merged summaries -------------------------------------------
    def describe(self, node: P.PlanNode, cols: List[str]) -> Dict[str, Dict]:
        """count/mean/stddev/min/max per column: executors reduce each
        partition to one row of moment partials (DescribeStep); the driver
        merges K tiny rows, never the data. Sample stddev (ddof=1), matching
        Spark's ``describe``."""
        temps: List[ObjectRef] = []
        try:
            # describe reads only `cols`: expose that to the optimizer by
            # narrowing the plan root, so scans and shuffles below prune too
            narrowed = (P.Project(node, [(c, _col(c)) for c in cols])
                        if O.enabled() else node)
            tasks, preferred = self._compile(self._optimized(narrowed), temps)
            tasks = [t.with_output(steps=t.steps + [T.DescribeStep(cols)],
                                   output=T.COLLECT)
                     for t in tasks]
            results = self.pool.run_tasks(tasks, preferred)
        finally:
            self._free(temps)
        agg = {c: {"count": 0, "sum": 0.0, "sumsq": 0.0,
                   "min": None, "max": None} for c in cols}
        for r in results:
            tbl = pa.ipc.open_stream(pa.py_buffer(r["ipc"])).read_all()
            row = {name: tbl.column(name)[0].as_py()
                   for name in tbl.column_names}
            for c in cols:
                a = agg[c]
                a["count"] += int(row[f"{c}:count"])
                a["sum"] += float(row[f"{c}:sum"])
                a["sumsq"] += float(row[f"{c}:sumsq"])
                for fn, key in ((min, "min"), (max, "max")):
                    v = row[f"{c}:{key}"]
                    if v is not None:
                        a[key] = v if a[key] is None else fn(a[key], v)
        out: Dict[str, Dict] = {}
        for c, a in agg.items():
            n = a["count"]
            mean = a["sum"] / n if n else None
            if n > 1:
                var = max(0.0, (a["sumsq"] - a["sum"] ** 2 / n) / (n - 1))
                std = math.sqrt(var)
            else:
                std = None
            out[c] = {"count": n, "mean": mean, "stddev": std,
                      "min": a["min"], "max": a["max"]}
        return out
