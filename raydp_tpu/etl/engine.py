"""The driver-side execution engine: plan → stages → tasks on executor actors.

This plays the role Spark's driver plays for the reference: it splits the plan at
wide operators, schedules partition tasks onto executor actors with locality (a
cached block's task prefers the executor holding it, like ``getBlockLocations``
routing in ObjectStoreWriter.scala:196-202), bounds in-flight work per executor,
and retries failed tasks — possible on any executor because tasks are lineage
recipes (SURVEY.md §5 failure-detection subsystem).
"""

from __future__ import annotations

import collections
import contextlib
import heapq
import math
import os
import random
import re
import threading
import time
import uuid
from concurrent.futures import FIRST_COMPLETED, wait
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from raydp_tpu import faults, knobs, metrics, profiler
from raydp_tpu.etl import optimizer as O
from raydp_tpu.etl import plan as P
from raydp_tpu.etl import tasks as T
from raydp_tpu.etl.expressions import col as _col
from raydp_tpu.log import get_logger
from raydp_tpu.runtime.actor import ActorHandle
from raydp_tpu.runtime.object_store import HEAD_HOST, ObjectRef, get_client
from raydp_tpu.runtime.rpc import ConnectionLost, RemoteError

logger = get_logger("etl.engine")


class StageError(RuntimeError):
    pass


class AdmissionRejected(StageError):
    """An action parked at admission control (its queued demand would push
    the pool's backlog past ``RDT_POOL_MAX_QUEUED``) and the backlog never
    drained within ``RDT_ADMIT_TIMEOUT_S``. Typed and NO-RETRY by contract:
    re-submitting the same action against the same overloaded pool replays
    the rejection — callers should shed load or raise the bound."""


class ObjectsLostError(StageError):
    """A stage task read intermediates whose store blobs are gone (host died,
    payload dropped). Retrying the consumer replays the miss, so the pool
    fails the stage immediately and hands the engine the lost ids for lineage
    recovery (regenerate producers → patch consumer refs → resubmit)."""

    def __init__(self, message: str, lost_ids: Sequence[str]):
        super().__init__(message)
        self.lost_ids = list(lost_ids)
        #: completed per-task results at abort time (index-aligned, None =
        #: unfinished) — recovery resubmits only the unfinished tasks instead
        #: of redoing the whole stage per round
        self.partial: Optional[List[Optional[Dict[str, Any]]]] = None


#: object ids travel inside ``RemoteError`` messages (see
#: ``object_store.ObjectLostError``); ids are 32 hex chars (token_hex(16))
_OBJECT_ID_RE = re.compile(r"\b[0-9a-f]{32}\b")


def _lost_ids_of(err: RemoteError) -> List[str]:
    """Lost object ids carried by a remote ObjectLostError: the structured
    ``object_id`` field when present, falling back to the 32-hex tokens in
    the message text (a peer running older code)."""
    oid = getattr(err, "object_id", None)
    if oid:
        return [oid]
    return _OBJECT_ID_RE.findall(err.message or "")

#: task-retry backoff: exponential with full jitter, replacing the old
#: immediate hot-loop resubmit (a restarting executor or a transient store
#: hiccup needs breathing room, and jitter de-synchronizes sibling retries)
_RETRY_BACKOFF_BASE_S = 0.05
_RETRY_BACKOFF_CAP_S = 2.0

#: how long an executor marked unreachable is skipped by task placement
#: before being probed again (restarts re-register under the same name)
_DOWN_TTL_S = 10.0


def _backoff_delay(attempt: int, rng: random.Random,
                   base: float = _RETRY_BACKOFF_BASE_S,
                   cap: float = _RETRY_BACKOFF_CAP_S) -> float:
    """Exponential backoff with jitter for the ``attempt``-th retry
    (1-based): ``min(cap, base * 2^(attempt-1) * U(0.5, 1.5))`` — the cap is
    a hard bound on the returned delay, jitter included."""
    return min(cap,
               base * (2 ** max(0, attempt - 1)) * (0.5 + rng.random()))


def _result_refs(r: Dict[str, Any]) -> List[ObjectRef]:
    """Store refs a task result carries (per-bucket shuffle blobs, ONE
    consolidated shuffle blob, and/or RETURN_REF)."""
    refs = list(r.get("bucket_refs") or [])
    if r.get("consolidated_ref") is not None:
        refs.append(r["consolidated_ref"])
    if r.get("ref") is not None:
        refs.append(r["ref"])
    return refs


def _consolidate_enabled() -> bool:
    """Consolidated-map-output kill switch; read per action (driver side)
    and carried on each task, so a mid-session toggle never mixes formats
    within one stage. Same pattern as ``RDT_ETL_OPTIMIZER``."""
    return bool(knobs.get("RDT_SHUFFLE_CONSOLIDATE"))


def _pipeline_enabled() -> bool:
    """Pipelined (push-based) shuffle kill switch, default ON; read per
    action like ``RDT_ETL_AQE``. The mode requires the consolidated
    per-bucket index, so ``RDT_SHUFFLE_CONSOLIDATE=0`` cleanly disables it
    too (doc/etl.md "Pipelined shuffle")."""
    return bool(knobs.get("RDT_SHUFFLE_PIPELINE"))


def _free_result_refs(results: Sequence[Optional[Dict[str, Any]]]) -> None:
    """Free every output in a failed stage's completed results — they will
    never reach a caller, so left alone they would orphan in the store."""
    orphans = [ref for r in results if r is not None for ref in _result_refs(r)]
    if orphans:
        try:
            get_client().free(orphans)
        except Exception:
            logger.warning("failed to free %d orphaned outputs of a "
                           "failed stage", len(orphans))


#: how long a failing stage waits for its in-flight tasks before abandoning
#: them (their outputs would otherwise be orphaned in the store)
_DRAIN_TIMEOUT_S = 30.0


def _recovery_enabled() -> bool:
    """Lineage recovery kill switch; read per action so tests can flip it."""
    return bool(knobs.get("RDT_LINEAGE_RECOVERY"))


def _recovery_rounds() -> int:
    """Recovery attempts per stage (each round may regenerate several blobs)."""
    return int(knobs.get("RDT_LINEAGE_ROUNDS"))


def _recovery_depth() -> int:
    """Max transitive producer-of-producer regeneration depth."""
    return int(knobs.get("RDT_LINEAGE_DEPTH"))


def _unreachable_grace_s() -> float:
    """How long a stage keeps probing for a reachable executor before failing.
    An executor restart is a process spawn plus the jax/pyarrow import storm —
    tens of seconds on a loaded machine — so "cannot reach" must not burn the
    task-retry budget (~7s of capped backoff): submits rotate to live
    executors immediately and only give up after this wall-clock grace."""
    return float(knobs.get("RDT_EXECUTOR_WAIT_S"))


# ---- speculation knobs (read per stage, so tests/benches can flip them) ----
def _speculation_enabled() -> bool:
    """Speculative-backup kill switch (default ON). Safe by construction:
    task reruns are byte-identical, so either copy's bytes are valid — the
    loser's distinct store blobs are drained and freed, never ledgered."""
    return bool(knobs.get("RDT_SPECULATION"))


def _speculation_quantile() -> float:
    """Completion fraction a stage must reach before backups are considered
    (LATE-style gate: a median runtime only means something once most of the
    stage has finished)."""
    return float(knobs.get("RDT_SPECULATION_QUANTILE"))


def _speculation_multiplier() -> float:
    """A pending attempt is a straggler when its runtime exceeds this
    multiple of the completed-task median."""
    return float(knobs.get("RDT_SPECULATION_MULTIPLIER"))


def _speculation_min_s() -> float:
    """Floor on the straggler threshold: sub-second stages never speculate
    just because their median is tiny."""
    return float(knobs.get("RDT_SPECULATION_MIN_S"))


class _Attempt:
    """One in-flight copy of a task: where it runs (stable executor identity
    + display name), when it was submitted, and whether it is a speculative
    backup of an attempt still running elsewhere."""

    __slots__ = ("i", "ident", "name", "started", "backup")

    def __init__(self, i: int, ident: str, name: str, started: float,
                 backup: bool):
        self.i = i
        self.ident = ident
        self.name = name
        self.started = started
        self.backup = backup


class _Producer:
    """Ledger entry: the serialized task that created a set of intermediates
    (all shuffle buckets of one map task, or one RETURN_REF block), in output
    order — rerunning the task yields byte-identical replacements because
    every task is a deterministic recipe (seeded sampling, stable hashing)."""

    __slots__ = ("task_bytes", "outputs", "label", "entry")

    def __init__(self, task_bytes: bytes, outputs: List[str], label: str):
        self.task_bytes = task_bytes
        self.outputs = outputs
        self.label = label
        #: the shuffle-report entry of the producing stage, bound by
        #: _record_stage — recovery attribution goes HERE, so two same-label
        #: stages in one action (two joins, two groupbys) stay distinct
        self.entry: Optional[Dict[str, Any]] = None


class _StreamStageRec:
    """Driver-side record of ONE pipelined shuffle stage: the background
    thread running its map stage, and the seals observed so far (what the
    driver itself published — only winning attempts' results reach it, so a
    speculation loser's seal never exists). ``seals`` feeds locality
    re-weighting for streaming reducers and the post-stage resolution of
    streaming sources into concrete ranges (cache recover recipes)."""

    def __init__(self, stage_key: str, label: str, num_maps: int):
        self.stage_key = stage_key
        self.label = label
        self.num_maps = num_maps
        self.start_ts = time.time()
        #: per map: (consolidated ref, per-bucket (off, size, rows) index)
        #: of the LATEST generation (a regenerated producer re-seals here)
        self.seals: List[Optional[Tuple[ObjectRef, list]]] = \
            [None] * num_maps  # guarded-by: _lock
        self.gens = [0] * num_maps  # guarded-by: _lock
        self.thread: Optional[threading.Thread] = None
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.results: Optional[List[Dict[str, Any]]] = None
        #: THIS stage's ledger entry, bound at _record_stage time —
        #: consumer attribution goes here, never through the label map
        #: (two same-label pipelined stages can be live concurrently)
        self.entry: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()

    def publish(self, map_id: int, ref: ObjectRef, index) -> None:
        """Record + push one seal notification (generation bumps on every
        publish, so a re-seal after lineage regeneration supersedes)."""
        with self._lock:
            self.gens[map_id] += 1
            gen = self.gens[map_id]
            self.seals[map_id] = (ref, list(index))
        if gen > 1:
            metrics.inc("stream_reseals_total")
            metrics.record_event("stream_reseal", stage=self.label,
                                 map_id=map_id, gen=gen, oid=ref.id)
        get_client().stream_publish(self.stage_key, map_id, gen, ref.id,
                                    int(ref.size or 0), list(index))

    def parts_for_bucket(self, bucket: int, sealed_only: bool = False
                         ) -> List[Tuple[ObjectRef, int, int]]:
        """This bucket's (ref, off, size) ranges from the seals seen so far
        (``sealed_only``) or from the COMPLETE stage (raises when a map has
        not sealed — resolution must never bake in a partial read)."""
        out = []
        with self._lock:
            for i, seal in enumerate(self.seals):
                if seal is None:
                    if sealed_only:
                        continue
                    raise RuntimeError(
                        f"stream stage {self.label} incomplete: map {i} "
                        "has not sealed")
                ref, index = seal
                off, size = int(index[bucket][0]), int(index[bucket][1])
                out.append((ref, off, size))
        return out


class _StreamBucket:
    """Driver-side placeholder for one reduce bucket of a pipelined stage —
    the barrier mode's ``(ref, off, size)`` triples do not exist yet. Never
    pickled: its executor-side twin is :class:`tasks.StreamingRangeSource`."""

    __slots__ = ("rec", "bucket")

    def __init__(self, rec: _StreamStageRec, bucket: int):
        self.rec = rec
        self.bucket = bucket

    def source(self, schema: Optional[bytes]) -> "T.StreamingRangeSource":
        return T.StreamingRangeSource(self.rec.stage_key, self.bucket,
                                      self.rec.num_maps, schema=schema)

    def parts_so_far(self) -> List[Tuple[ObjectRef, int, int]]:
        return self.rec.parts_for_bucket(self.bucket, sealed_only=True)


class _ActionTemps(list):
    """Per-action intermediate registry: the list half is the free-at-action-
    end set (what ``temps`` always was); ``lineage`` maps every intermediate
    object id to its producer so a lost blob can be regenerated mid-action."""

    def __init__(self):
        super().__init__()
        self.lineage: Dict[str, _Producer] = {}
        #: accumulated old-id → regenerated-ref patches from every recovery
        #: in this action; anything serialized for later use (e.g. cache
        #: recover recipes) must be patched through this map, or it would
        #: bake in ids whose blobs are already dead
        self.ref_patches: Dict[str, ObjectRef] = {}  # guarded-by: _patch_lock
        #: label → the report entry THIS action recorded (aliases the dict in
        #: the engine deque), so recovery attribution lands on this action's
        #: stage even when a concurrent action logged the same label later
        self.stage_entries: Dict[str, Dict[str, Any]] = {}
        #: pipelined map stages launched by this action (joined + their seal
        #: streams closed before the action frees its temps), by UNIQUE
        #: stage key — labels repeat within one action, keys never do
        self.streams: List[_StreamStageRec] = []
        self.stream_by_key: Dict[str, _StreamStageRec] = {}
        #: consolidated-blob oid → (stream rec, map_id): which publication a
        #: regenerated producer must RE-SEAL (same map_id, next generation)
        self.stream_pubs: Dict[str, Tuple[_StreamStageRec, int]] = {}
        #: guards ref_patches: with pipelining, a background map stage's
        #: recovery and the main thread's reduce-stage recovery can patch
        #: the SAME action concurrently (single-threaded before this)
        self._patch_lock = threading.Lock()

    def close_streams(self) -> None:
        """Join every pipelined map stage's background thread (their outputs
        are registered here and must not be freed under running writers),
        then drop the seal-stream ledgers — a drain-abandoned reducer still
        polling gets an abort instead of waiting forever."""
        if not self.streams:
            return
        streams, self.streams = self.streams, []
        for rec in streams:
            if rec.thread is not None:
                rec.thread.join()
            if rec.error is not None:
                logger.warning("pipelined map stage %r failed: %s",
                               rec.label, rec.error)
        try:
            get_client().stream_close([rec.stage_key for rec in streams])
        except Exception:
            pass

    def resolve_streams(self, task: T.Task) -> T.Task:
        """Rewrite a task's streaming sources into concrete ranged reads
        from the completed stages' seals — for recipes serialized to outlive
        this action (the stream ledger closes with it)."""
        if not self.stream_by_key:
            return task

        def _resolver(stage_key: str, bucket: int):
            rec = self.stream_by_key.get(stage_key)
            if rec is None:
                raise RuntimeError(f"unknown stream stage {stage_key}")
            return rec.parts_for_bucket(bucket)

        return T.resolve_stream_sources(task, _resolver)

    def apply_patches(self, mapping: Dict[str, ObjectRef]) -> None:
        """Fold a recovery round's old-id → fresh-ref mapping into the
        action's accumulated patches, collapsing transitively: an earlier
        round's patch target may ITSELF be what just got regenerated, and
        anything serialized later (cache recover recipes) must point at the
        live blob, not a dead intermediate generation."""
        with self._patch_lock:
            for k, v in self.ref_patches.items():
                if v.id in mapping:
                    self.ref_patches[k] = mapping[v.id]
            self.ref_patches.update(mapping)


def _root_limit(node: P.PlanNode) -> Optional[int]:
    """The global row cap when the plan's root is a ``Limit`` (possibly under
    other per-row-preserving narrow ops). The compiled LimitStep truncates each
    partition; the action applies the exact global cut."""
    while isinstance(node, (P.Rename,)):
        node = node.child
    return node.n if isinstance(node, P.Limit) else None


# deterministic application failures: retrying replays the same exception, so
# fail fast with the original error instead of burning the retry budget.
# ShuffleStreamAborted is deterministic too: a reducer polling an aborted
# seal stream replays the abort (which carries the map stage's real error).
_NO_RETRY_EXC_TYPES = {
    "KeyError", "ValueError", "TypeError", "AttributeError", "IndexError",
    "ZeroDivisionError", "ArrowInvalid", "ArrowNotImplementedError",
    "ArrowKeyError", "ArrowTypeError", "ShuffleStreamAborted",
    "AdmissionRejected",
}

#: how often the dispatch path re-evaluates store memory pressure (the
#: watermark check reads one stats() snapshot per interval, never per task)
_BACKPRESSURE_POLL_S = 0.5

#: the fallback tenant id of an untagged run_tasks call
_DEFAULT_TENANT = "default"


class ExecutorPool:
    """Straggler-resistant scheduler over executor actor handles with retry.

    Dispatch is **least-loaded**: each executor carries its own in-flight
    counter capped at ``max_inflight_per_executor`` (the old single global
    ``4 × pool`` cap let every task stack on one slow executor while its
    siblings idled); ties rotate round-robin, and a task's preferred
    (cache-local) executor is honored on every attempt — retries included —
    unless it is marked down or its queue is at cap, in which case the task
    hands off to the least-busy live executor instead of stacking.

    Retry parity: the reference's fetch tasks run with ``max_retries=-1``
    (dataset.py:54) and executor actors revive with ``maxRestarts=-1``; we retry a
    bounded-but-generous number of times, re-resolving the actor between attempts
    (a restarted actor keeps its name at a new address).
    """

    def __init__(self, executors: List[ActorHandle], max_task_retries: int = 8,
                 hosts_by_name: Optional[Dict[str, str]] = None):
        if not executors:
            raise ValueError("executor pool is empty")
        # membership is ELASTIC (drain/retire + autoscale): ``executors``,
        # ``_idents``, ``_ident_of``, ``by_name`` and the host maps are
        # immutable snapshots REPLACED atomically under ``_lock`` on every
        # membership change — readers that grabbed the old list keep a
        # consistent view, and no reader needs the lock
        self.executors = list(executors)
        self.by_name = {h.name: h for h in executors}
        self.max_task_retries = max_task_retries
        #: stable per-handle identity, index-aligned with ``executors`` —
        #: in-flight counters and the down map key on THIS, never on
        #: ``handle.name``: several unnamed executors would alias one ""
        #: entry, so one crash would mark them all down
        self._idents = [self._executor_ident(h) for h in self.executors]
        self._ident_of = {id(h): ident
                          for h, ident in zip(self.executors, self._idents)}
        #: executor name → data-plane host id (machine), for locality routing
        self.hosts_by_name: Dict[str, str] = dict(hosts_by_name or {})
        self._names_by_host: Dict[str, List[str]] = {}
        for h in self.executors:
            if h.name and h.name in self.hosts_by_name:
                self._names_by_host.setdefault(
                    self.hosts_by_name[h.name], []).append(h.name)
        self._rr = 0  # guarded-by: _lock
        self._local_rr: Dict[str, int] = {}  # guarded-by: _lock
        self._weight_rr = 0  # tie rotation for pick_weighted; guarded-by: _lock
        self._lock = threading.Lock()
        #: pool-WIDE in-flight per ident, across every concurrent run_tasks
        #: call — the drain protocol's quiesce signal and the autoscaler's
        #: busy signal (per-call caps still use each call's local counters)
        self._busy: Dict[str, int] = {}  # guarded-by: _lock
        #: ident → monotonic time marked unreachable. Pool-level (not
        #: per-call) so every concurrent stage shares the discovery, and a
        #: restart re-admission (mark_up) is observable session-wide
        self._down: Dict[str, float] = {}  # guarded-by: _lock
        #: ident → monotonic drain start; a draining executor accepts NO new
        #: dispatch but keeps its in-flight tasks until they finish/fail
        self._draining: Dict[str, float] = {}  # guarded-by: _lock
        #: outstanding tasks across all active run_tasks calls (queued +
        #: in-flight); demand - busy = the autoscaler's queue-depth signal
        self._demand = 0  # guarded-by: _lock
        # ---- multi-tenant fair sharing + admission (doc/etl.md "Fair
        # sharing and admission"): per-tenant twins of _busy/_demand, the
        # registered weights, and cumulative dispatch counts. busy/demand/
        # weight entries drop when a tenant goes fully idle; dispatched is
        # cumulative (bounded by the number of tenants ever seen).
        self._tenant_busy: Dict[str, int] = {}  # guarded-by: _lock
        self._tenant_demand: Dict[str, int] = {}  # guarded-by: _lock
        self._tenant_weight: Dict[str, float] = {}  # guarded-by: _lock
        self._tenant_dispatched: Dict[str, int] = {}  # guarded-by: _lock
        #: per-tenant demand registered by actions still PARKED at admission
        #: — included in _demand (the autoscaler must see it and grow to
        #: absorb it) but excluded from the admission backlog (two parked
        #: actions must not hold each other out past an already-drained
        #: queue) AND from the fair-share contention scan (a parked tenant
        #: cannot take the slot the gate would reserve for it — counting it
        #: would serialize every running tenant for the whole park)
        self._parked_by_tenant: Dict[str, int] = {}  # guarded-by: _lock
        #: FIFO of parked admissions (monotonic tickets, append order): a
        #: freed backlog admits the LONGEST-parked action first instead of
        #: whichever poll loop woke up luckiest (ROADMAP 3c)
        self._park_queue: List[int] = []  # guarded-by: _lock
        self._park_seq = 0  # guarded-by: _lock
        # ---- memory backpressure: hosts paused above the store
        # high-watermark (hysteresis: released below the low-watermark).
        # The cache tuple (expiry, frozenset) is swapped atomically and
        # read lock-free on the dispatch hot path.
        self._pressure_lock = threading.Lock()
        self._bp_active: set = set()  # guarded-by: _pressure_lock
        self._pressure_cache: Optional[Tuple[float, frozenset]] = None
        #: test/override hook: a callable returning {host_id: fraction of
        #: its store budget in shm}; None = read the store's stats()
        self.pressure_provider = None

    @staticmethod
    def _executor_ident(h) -> str:
        """Stable scheduling identity of a handle: the actor id when it has
        one, else the name, else the handle object itself (an anonymous
        stub in tests) — never a shared sentinel like ""."""
        aid = getattr(h, "actor_id", None)
        if aid:
            return str(aid)
        return h.name or f"anon-{id(h):x}"

    def _next_executor(self) -> ActorHandle:
        with self._lock:
            h = self.executors[self._rr % len(self.executors)]
            self._rr += 1
            return h

    # ---- elastic membership -------------------------------------------------
    def _swap_members(self, executors: List[ActorHandle],
                      hosts_by_name: Dict[str, str]) -> None:
        """Rebuild and atomically replace every membership snapshot.
        Caller holds ``_lock``."""
        idents = [self._executor_ident(h) for h in executors]
        names_by_host: Dict[str, List[str]] = {}
        for h in executors:
            if h.name and h.name in hosts_by_name:
                names_by_host.setdefault(hosts_by_name[h.name], []) \
                    .append(h.name)
        self.executors = executors
        self._idents = idents
        self._ident_of = {id(h): i for h, i in zip(executors, idents)}
        self.by_name = {h.name: h for h in executors}
        self.hosts_by_name = hosts_by_name
        self._names_by_host = names_by_host

    def add_executor(self, handle: ActorHandle,
                     host_id: Optional[str] = None) -> str:
        """Admit a new executor into rotation (autoscale grow / manual
        attach); returns its scheduling ident. Stages already running pick
        it up on their next dispatch pass."""
        with self._lock:
            if any(h is handle for h in self.executors):
                return self._ident_of[id(handle)]
            hosts = dict(self.hosts_by_name)
            if handle.name and host_id is not None:
                hosts[handle.name] = host_id
            self._swap_members(self.executors + [handle], hosts)
            ident = self._ident_of[id(handle)]
            # a re-added name sheds any stale down/drain state
            self._down.pop(ident, None)
            self._draining.pop(ident, None)
            size = len(self.executors) - len(self._draining)
        metrics.set_gauge("pool_size", size)
        logger.info("executor %s joined the pool (size %d)",
                    handle.name or ident, size)
        return ident

    def remove_executor(self, name: str) -> Optional[ActorHandle]:
        """Drop an executor from every membership snapshot (the last step of
        a drain — or an abrupt removal; in-flight attempts on it simply fail
        and retry elsewhere). Returns the removed handle, or None."""
        with self._lock:
            handle = self.by_name.get(name)
            if handle is None:
                return None
            ident = self._ident_of[id(handle)]
            rest = [h for h in self.executors if h is not handle]
            hosts = {n: hid for n, hid in self.hosts_by_name.items()
                     if n != name}
            self._swap_members(rest, hosts)
            self._draining.pop(ident, None)
            self._down.pop(ident, None)
            self._busy.pop(ident, None)
            size = len(self.executors) - len(self._draining)
        metrics.set_gauge("pool_size", size)
        logger.info("executor %s left the pool (size %d)", name, size)
        return handle

    def begin_drain(self, name: str) -> bool:
        """Take ``name`` out of dispatch rotation without touching its
        in-flight tasks. False when unknown or already draining; raises when
        the drain would leave zero live executors (the pool would wedge)."""
        with self._lock:
            handle = self.by_name.get(name)
            if handle is None:
                return False
            ident = self._ident_of[id(handle)]
            if ident in self._draining:
                return False
            live = [i for i in self._idents if i not in self._draining]
            if len(live) <= 1:
                raise ValueError(
                    f"cannot drain {name!r}: it is the last live executor")
            self._draining[ident] = time.monotonic()
            size = len(self.executors) - len(self._draining)
        metrics.set_gauge("pool_size", size)
        return True

    def cancel_drain(self, name: str) -> None:
        """Put a draining executor back into rotation (a failed retirement
        must not leave it unreachable-by-scheduler forever)."""
        with self._lock:
            handle = self.by_name.get(name)
            if handle is None:
                return
            self._draining.pop(self._ident_of[id(handle)], None)
            size = len(self.executors) - len(self._draining)
        metrics.set_gauge("pool_size", size)

    def wait_idle(self, name: str, timeout: float) -> bool:
        """Block until ``name`` has zero pool-wide in-flight tasks (its
        drain quiesce point) or ``timeout`` lapses; True = quiesced. An
        executor that crashed mid-drain quiesces too — its attempts fail
        and their completions decrement the same counter."""
        deadline = time.monotonic() + max(0.0, timeout)
        while True:
            with self._lock:
                handle = self.by_name.get(name)
                if handle is None:
                    return True
                busy = self._busy.get(self._ident_of[id(handle)], 0)
            if busy <= 0:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)

    def load(self) -> Dict[str, Any]:
        """Scheduling-load snapshot for the autoscale controller: member /
        live counts, pool-wide busy, queued demand (outstanding tasks not in
        flight), and per-executor busy by display name."""
        now = time.monotonic()
        with self._lock:
            members = list(zip(self.executors, self._idents))
            busy = dict(self._busy)
            draining = set(self._draining)
            down = {i for i, t in self._down.items()
                    if now - t < _DOWN_TTL_S}
            demand = self._demand
            tenants = {
                t: {"busy": self._tenant_busy.get(t, 0),
                    "demand": self._tenant_demand.get(t, 0),
                    "queued": max(0, self._tenant_demand.get(t, 0)
                                  - self._tenant_busy.get(t, 0)),
                    "weight": self._tenant_weight.get(t, 1.0),
                    "dispatched": self._tenant_dispatched.get(t, 0)}
                for t in set(self._tenant_demand) | set(self._tenant_busy)
                | set(self._tenant_dispatched)}
            parked = sum(self._parked_by_tenant.values())
        live = [i for _, i in members if i not in draining]
        busy_total = sum(busy.get(i, 0) for i in live)
        return {
            "size": len(members),
            "live": len(live),
            "down": len(down & set(live)),
            "draining": len(draining),
            "busy": busy_total,
            "queued": max(0, demand - sum(busy.values())),
            "parked": parked,
            "backpressured_hosts": sorted(self._pressured_hosts()),
            "per_executor_busy": {
                (h.name or i): busy.get(i, 0) for h, i in members},
            "tenants": tenants,
        }

    def draining_names(self) -> List[str]:
        with self._lock:
            draining = set(self._draining)
            return [h.name or i for h, i in zip(self.executors, self._idents)
                    if i in draining]

    def _dispatch_view(self) -> Tuple[List[Tuple[ActorHandle, str]], set]:
        """One-lock snapshot for a dispatch pass: dispatchable (handle,
        ident) pairs (draining members excluded, members on a
        memory-backpressured host excluded) plus the set of currently-down
        idents — the scheduling hot loops evaluate membership/downness
        against this copy instead of taking the pool lock once per member
        per pass. With EVERY host paused dispatch simply waits (graceful
        degradation: the queue holds, the autoscaler still sees demand, and
        the store drains below the low watermark instead of OOMing)."""
        now = time.monotonic()
        pressured = self._pressured_hosts()
        with self._lock:
            draining = self._draining
            hosts = self.hosts_by_name
            members = [(h, i) for h, i in zip(self.executors, self._idents)
                       if i not in draining
                       and (not pressured
                            or hosts.get(h.name or "", HEAD_HOST)
                            not in pressured)]
            down = {i for i, t in self._down.items()
                    if now - t < _DOWN_TTL_S}
        return members, down

    def _is_down(self, ident: str) -> bool:
        with self._lock:
            t = self._down.get(ident)
        return t is not None and time.monotonic() - t < _DOWN_TTL_S

    def _mark_down(self, ident: str, name: str) -> None:
        now = time.monotonic()
        with self._lock:
            t = self._down.get(ident)
            # transition computed under the SAME lock as the write: two
            # concurrent stages discovering one crash must record one
            # executor_down, not flood the bounded ring with duplicates
            transition = t is None or now - t >= _DOWN_TTL_S
            self._down[ident] = now
        if transition:
            # record the TRANSITION, not every probe of an already-down
            # executor — a 60s unreachable grace of backoff probes must
            # not flood the bounded flight-recorder ring
            metrics.inc("sched_executor_down_total", label=name)
            metrics.record_event("executor_down", executor=name)

    def _mark_up(self, ident: str, name: str) -> None:
        """A down-marked executor answered: re-admit it immediately (no TTL
        wait) and record the symmetric executor_up event, so a node-agent
        restart mid-action returns the pool to full width instead of the
        action finishing on the shrunken remainder."""
        with self._lock:
            was_down = self._down.pop(ident, None)
        if was_down is not None:
            metrics.inc("sched_executor_up_total", label=name)
            metrics.record_event("executor_up", executor=name)
            logger.info("executor %s is reachable again; re-admitted to "
                        "task placement", name)

    @staticmethod
    def _bump(counts: Dict[str, int], key: str, n: int) -> None:
        """Adjust one floor-at-zero counter map entry, dropping it at 0.
        Caller holds ``_lock``."""
        cur = counts.get(key, 0) + n
        if cur > 0:
            counts[key] = cur
        else:
            counts.pop(key, None)

    def _maybe_drop_tenant(self, tenant: str) -> None:  # guarded-by: _lock
        """Forget a tenant's weight once it carries no busy and no demand
        (its next action re-registers). Caller holds ``_lock``."""
        if not self._tenant_busy.get(tenant) \
                and not self._tenant_demand.get(tenant):
            self._tenant_weight.pop(tenant, None)

    def _busy_delta(self, ident: str, n: int,
                    tenant: Optional[str] = None) -> None:
        with self._lock:
            self._bump(self._busy, ident, n)
            if tenant is not None:
                self._bump(self._tenant_busy, tenant, n)
                self._maybe_drop_tenant(tenant)

    def _demand_delta(self, n: int, tenant: Optional[str] = None) -> None:
        with self._lock:
            self._demand = max(0, self._demand + n)
            if tenant is not None:
                self._bump(self._tenant_demand, tenant, n)
                self._maybe_drop_tenant(tenant)

    def _register_tenant(self, tenant: str, weight: float) -> None:
        with self._lock:
            self._tenant_weight[tenant] = weight

    def _note_dispatch(self, tenant: str) -> None:
        with self._lock:
            self._tenant_dispatched[tenant] = \
                self._tenant_dispatched.get(tenant, 0) + 1

    def _fair_ok(self, tenant: str) -> bool:
        """Deficit-weighted fair-share gate: may ``tenant`` take the next
        executor slot? Always yes without contention (no OTHER tenant has
        queued work). Under contention a tenant may dispatch only while its
        in-flight count stays within one task of ``weight × the minimum
        busy/weight share`` among the contending tenants — so the
        least-served (deficit) tenant always passes, per-tenant in-flight
        shares converge to the weight ratio, and an idle tenant's first
        task never waits behind a thousand queued batch tasks."""
        with self._lock:
            min_share = None
            for t, d in self._tenant_demand.items():
                if t == tenant:
                    continue
                b = self._tenant_busy.get(t, 0)
                if d - self._parked_by_tenant.get(t, 0) - b <= 0:
                    # nothing DISPATCHABLE queued: no claim on the next
                    # slot (admission-parked demand is excluded — a parked
                    # tenant cannot take the slot this gate would hold)
                    continue
                share = b / self._tenant_weight.get(t, 1.0)
                if min_share is None or share < min_share:
                    min_share = share
            if min_share is None:
                return True
            busy = self._tenant_busy.get(tenant, 0)
            return busy < self._tenant_weight.get(tenant, 1.0) \
                * min_share + 1

    def _admit(self, tenant: str, n: int) -> None:
        """Admission control (``RDT_POOL_MAX_QUEUED``): park this call while
        the pool's ADMITTED queued backlog plus its ``n`` tasks would exceed
        the bound. The caller has already registered its demand, so the
        autoscaler sees the parked work and can grow to absorb it (busy
        capacity up → backlog down → admitted). An empty backlog always
        admits — a single action larger than the bound must run, not wedge.
        Admission is FIFO in park order: freed backlog goes to the
        longest-parked action first, and a fresh arrival queues BEHIND
        already-parked actions instead of racing them for the slot.
        Past ``RDT_ADMIT_TIMEOUT_S`` the call fails with the typed no-retry
        :class:`AdmissionRejected`."""
        max_q = int(knobs.get("RDT_POOL_MAX_QUEUED"))
        if max_q <= 0 or n <= 0:
            return
        timeout = float(knobs.get("RDT_ADMIT_TIMEOUT_S"))
        deadline = time.monotonic() + max(0.0, timeout)
        parked = False
        ticket: Optional[int] = None
        try:
            while True:
                newly_parked = False
                with self._lock:
                    busy_total = sum(self._busy.values())
                    own = n if not parked else 0
                    backlog = max(
                        0, self._demand
                        - sum(self._parked_by_tenant.values())
                        - own - busy_total)
                    fits = backlog <= 0 or backlog + n <= max_q
                    # FIFO gate: freed backlog belongs to the queue head;
                    # an unparked newcomer counts as head only while nobody
                    # is parked at all (first parked, first admitted)
                    head = (self._park_queue[0] == ticket if parked
                            else not self._park_queue)
                    if fits and head:
                        if parked:
                            self._bump(self._parked_by_tenant, tenant, -n)
                            self._park_queue.remove(ticket)
                            parked = False
                        return
                    if not parked:
                        parked = newly_parked = True
                        ticket = self._park_seq
                        self._park_seq += 1
                        self._park_queue.append(ticket)
                        self._bump(self._parked_by_tenant, tenant, n)
                if newly_parked:
                    metrics.inc("pool_admission_parked_total", label=tenant)
                    logger.info(
                        "action of %d tasks (tenant %r) parked at "
                        "admission: pool backlog %d exceeds "
                        "RDT_POOL_MAX_QUEUED=%d", n, tenant, backlog, max_q)
                if time.monotonic() >= deadline:
                    metrics.inc("pool_admission_rejects_total", label=tenant)
                    metrics.record_event("admission_reject", tenant=tenant,
                                         tasks=n, backlog=backlog,
                                         max_queued=max_q)
                    raise AdmissionRejected(
                        f"admission of {n} tasks (tenant {tenant!r}) timed "
                        f"out after {timeout:.0f}s: pool backlog of "
                        f"{backlog} queued tasks exceeds "
                        f"RDT_POOL_MAX_QUEUED={max_q}")
                time.sleep(0.05)
        finally:
            if parked:
                with self._lock:
                    self._bump(self._parked_by_tenant, tenant, -n)
                    if ticket in self._park_queue:
                        self._park_queue.remove(ticket)

    # ---- memory backpressure ------------------------------------------------
    @staticmethod
    def _store_pressure() -> Dict[str, float]:
        """{host_id: shm bytes / budget} from the store's stats() — only
        hosts with a configured budget report (no budget, no watermark)."""
        stats = get_client().stats()
        shm = stats.get("host_shm") or {}
        return {h: shm.get(h, 0) / b
                for h, b in (stats.get("host_budgets") or {}).items() if b}

    def _pressured_hosts(self) -> frozenset:
        """Hosts currently paused for dispatch: above the store
        high-watermark, held until below the low-watermark (hysteresis).
        Evaluated at most once per ``_BACKPRESSURE_POLL_S``; the cached
        set is swapped atomically, so the dispatch hot path reads it
        lock-free."""
        high = float(knobs.get("RDT_STORE_HIGH_WATERMARK"))
        if high <= 0:
            return frozenset()
        now = time.monotonic()
        cached = self._pressure_cache
        if cached is not None and now < cached[0]:
            return cached[1]
        with self._pressure_lock:
            cached = self._pressure_cache
            if cached is not None and now < cached[0]:
                return cached[1]
            low = min(float(knobs.get("RDT_STORE_LOW_WATERMARK")), high)
            try:
                provider = self.pressure_provider or self._store_pressure
                fractions = provider() or {}
            except Exception:  # noqa: BLE001 - no store/runtime yet, or a
                # transient stats failure. Fail CLOSED: keep the previous
                # pause state — an overloaded store head timing out its own
                # stats RPC is exactly when resuming dispatch to a paused
                # host would be wrong. (A pool that never reached a store
                # has an empty _bp_active, so nothing is held paused.)
                out = frozenset(self._bp_active)
                self._pressure_cache = (now + _BACKPRESSURE_POLL_S, out)
                return out
            for host, frac in fractions.items():
                if host in self._bp_active:
                    if frac < low:
                        self._bp_active.discard(host)
                        metrics.record_event("backpressure", host=host,
                                             state="resume",
                                             pressure=round(frac, 3))
                        logger.info(
                            "store pressure on %s back under the low "
                            "watermark (%.2f < %.2f); dispatch resumed",
                            host, frac, low)
                elif frac >= high:
                    self._bp_active.add(host)
                    metrics.inc("pool_backpressure_total", label=host)
                    metrics.record_event("backpressure", host=host,
                                         state="pause",
                                         pressure=round(frac, 3))
                    logger.warning(
                        "store pressure on %s above the high watermark "
                        "(%.2f >= %.2f); pausing dispatch to its "
                        "executors until it drops below %.2f",
                        host, frac, high, low)
            # a host that stopped reporting (budget removed, node purged)
            # must not stay paused forever
            self._bp_active &= set(fractions)
            out = frozenset(self._bp_active)
            self._pressure_cache = (now + _BACKPRESSURE_POLL_S, out)
            return out

    def multi_host(self) -> bool:
        """True when executors span machines — only then is locality routing
        worth overriding round-robin balance."""
        return len(set(self.hosts_by_name.values())) > 1

    def pick_local(self, host_id: str) -> Optional[str]:
        """An executor on ``host_id`` (round-robin among that machine's
        executors for balance), or None when none runs there."""
        names = self._names_by_host.get(host_id)
        if not names:
            return None
        with self._lock:
            i = self._local_rr.get(host_id, 0)
            self._local_rr[host_id] = i + 1
        return names[i % len(names)]

    def pick_weighted(self, host_weights: Dict[str, float]
                      ) -> Optional[str]:
        """Preferred executor from per-host locality weights (data-gravity
        scheduling): hosts are tried in DESCENDING weight order and the
        heaviest one that still has a dispatchable member (not draining,
        not on a memory-backpressured host) wins — when the best host is
        draining, the runner-up (e.g. the machine holding a spilled
        copy) takes the task instead of an arbitrary executor. Hosts
        tied on weight rotate deterministically so tied placements
        spread. None when no weighted host is dispatchable (dispatch
        then falls back to least-loaded)."""
        if not host_weights:
            return None
        members, _ = self._dispatch_view()
        live_hosts = {self.hosts_by_name.get(h.name or "", HEAD_HOST)
                      for h, _ in members}
        with self._lock:
            rr = self._weight_rr
            self._weight_rr += 1
        ranked = sorted(host_weights.items(), key=lambda kv: -kv[1])
        i = 0
        while i < len(ranked):
            j = i
            while j < len(ranked) and ranked[j][1] == ranked[i][1]:
                j += 1
            tied = sorted(h for h, _ in ranked[i:j] if h in live_hosts)
            if tied:
                return self.pick_local(tied[rr % len(tied)])
            i = j
        return None

    def run_tasks(
        self,
        tasks: Sequence[T.Task],
        preferred: Optional[Sequence[Optional[str]]] = None,
        max_inflight_per_executor: int = 4,
        payloads: Optional[Sequence[bytes]] = None,
        sched_stats: Optional[Dict[str, Any]] = None,
        on_result: Optional[Any] = None,
        tenant: Optional[str] = None,
        tenant_weight: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """Run tasks, preserving order of results; blocks until all complete.

        Dispatch is least-loaded with per-executor in-flight caps (see the
        class docstring). Once the stage is past a completion quantile
        (``RDT_SPECULATION_QUANTILE``) and a pending attempt's runtime
        exceeds ``RDT_SPECULATION_MULTIPLIER`` × the completed-task median
        (floored by ``RDT_SPECULATION_MIN_S``), a **speculative backup** of
        the same serialized payload is submitted to a different live
        executor; the first finisher wins and the loser's outputs are
        drained and freed through the late-result path — byte-identical
        reruns make either copy's bytes valid, but each attempt writes its
        own store blobs, so only the winner's refs reach the caller (and
        through it the lineage ledger). ``RDT_SPECULATION=0`` disables
        backups.

        Failed attempts resubmit after exponential backoff with full jitter
        (never the old immediate hot loop). A task that read a LOST store
        blob fails the stage at once as :class:`ObjectsLostError` — retrying
        the consumer replays the miss; only lineage recovery (the engine's
        job) can fix it. Any stage abort first cancels queued retries, drains
        in-flight tasks, and frees the outputs the caller will never see.

        ``sched_stats``, when given, is updated in place with
        ``speculated`` / ``speculation_won`` counters and a
        ``per_executor_busy`` map (executor display name → peak in-flight
        during this call), merging across calls.

        ``on_result(i, result)`` fires as EACH task's winning result lands
        (index into ``tasks``) — the pipelined shuffle's seal-notification
        hook: the driver publishes a map's consolidated blob the moment it
        is decided, so only winners ever seal. Callback errors are logged,
        never fail the stage.

        ``tenant`` tags this stage's load for weighted fair sharing across
        concurrent callers (doc/etl.md "Fair sharing and admission"):
        per-tenant busy/demand twins of the pool signals, a deficit-
        weighted dispatch gate under contention, and admission control —
        the call parks while the pool's queued backlog would exceed
        ``RDT_POOL_MAX_QUEUED`` and fails typed (:class:`AdmissionRejected`,
        no-retry) past ``RDT_ADMIT_TIMEOUT_S``. ``tenant_weight`` defaults
        to ``RDT_POOL_TENANT_WEIGHT`` (re-read per call)."""
        n = len(tasks)
        tenant = tenant or _DEFAULT_TENANT
        if tenant_weight is None:
            tenant_weight = float(knobs.get("RDT_POOL_TENANT_WEIGHT"))
        tenant_weight = max(float(tenant_weight), 1e-3)
        results: List[Optional[Dict[str, Any]]] = [None] * n
        attempts = [0] * n
        cap = max(1, max_inflight_per_executor)
        pending: Dict[Any, _Attempt] = {}
        # per-CALL in-flight (the cap + busy-peak stats are per stage);
        # membership is elastic, so entries appear as executors are chosen
        inflight: Dict[str, int] = {}
        busy_peak: Dict[str, int] = {}
        copies = [0] * n             # live in-flight attempts per task
        retry_q: List[Tuple[float, int]] = []  # (due monotonic, task index)
        rng = random.Random()
        next_idx = 0
        done_cnt = 0
        durations: List[float] = []  # winning-attempt runtimes, for the median
        speculated: set = set()      # task indices that got a backup
        spec_won = 0
        spec_on = _speculation_enabled() and len(self.executors) > 1
        spec_gate = max(1, math.ceil(_speculation_quantile() * n))
        spec_mult = _speculation_multiplier()
        spec_min_s = _speculation_min_s()
        # serialize each task at most once (caller-provided payloads — e.g.
        # the engine's lineage ledger copies — are reused; retries and
        # speculative backups reuse the same bytes too)
        blobs: List[Optional[bytes]] = list(payloads) if payloads is not None \
            else [None] * n

        uprobe = [0] * n             # unreachable-submit probes per task
        unreach_since: List[Optional[float]] = [None] * n
        # down tracking lives on the POOL (shared across concurrent stages;
        # a node-agent restart re-admits via _mark_up on the first answer)
        _mark_down = self._mark_down

        def _any_capacity() -> bool:
            members, down = self._dispatch_view()
            any_live = live_free = probe_free = False
            for _h, ident in members:
                busy = inflight.get(ident, 0)
                if ident not in down:
                    any_live = True
                    if busy < cap:
                        live_free = True
                elif busy < cap:
                    probe_free = True
            if any_live:
                # a live executor at cap is BUSY, not gone: tasks wait for a
                # slot instead of probing a dead address (which would burn
                # their unreachable grace while the cluster is healthy)
                return live_free
            # every executor is down: free slots on them count — probing is
            # the only way to notice a restart (the down TTL expires and the
            # submit itself is the probe)
            return probe_free

        def _choose(i: int, exclude: Optional[str] = None,
                    probe: bool = True):
            """(handle, ident) to run task ``i`` on: the preferred executor
            whenever it is live, not draining, and below its cap — on EVERY
            attempt, so a transient failure no longer strands a cache-local
            task on remote hosts for the rest of its retries — else the
            least-loaded live executor below cap (round-robin tiebreak).
            Membership is read fresh per call: an executor the autoscaler
            added mid-stage is dispatchable at once, a draining/removed one
            never is. When every executor is down, a second pass
            (``probe=True``) returns a down-but-below-cap executor so the
            submit itself probes for a restart — but ONLY then: a live
            executor at its cap means the task should wait for a slot, not
            accrue unreachable grace against a dead address while the pool
            is merely busy; (None, None) = nothing to submit to right now."""
            members, down = self._dispatch_view()
            member_idents = {ident for _h, ident in members}
            if preferred is not None and preferred[i] is not None:
                h = self.by_name.get(preferred[i])
                if h is not None:
                    ident = self._ident_of.get(id(h))
                    if ident is not None and ident in member_idents \
                            and ident != exclude and ident not in down \
                            and inflight.get(ident, 0) < cap:
                        return h, ident
            k = len(members)
            if k == 0:
                return None, None
            with self._lock:
                start = self._rr
                self._rr += 1
            may_probe = probe and all(ident in down
                                      for _h, ident in members)
            best = None
            for allow_down in (False, True) if may_probe else (False,):
                for off in range(k):
                    h, ident = members[(start + off) % k]
                    busy = inflight.get(ident, 0)
                    if ident == exclude or busy >= cap:
                        continue
                    if (ident in down) != allow_down:
                        continue
                    if best is None or busy < best[2]:
                        best = (h, ident, busy)
                if best is not None:
                    break
            if best is None:
                return None, None
            return best[0], best[1]

        # pool-wide accounting (drain quiesce + autoscale + fair-share
        # signals), reconciled in the final ``finally`` so an abort/
        # abandonment can never leak a phantom busy count, queued demand,
        # or per-tenant load
        pool_acct: Dict[str, int] = {}

        def _pool_busy(ident: str, d: int) -> None:
            pool_acct[ident] = pool_acct.get(ident, 0) + d
            self._busy_delta(ident, d, tenant)

        def _register(fut, i: int, ident: str, name: str, backup: bool):
            pending[fut] = _Attempt(i, ident, name, time.monotonic(), backup)
            inflight[ident] = inflight.get(ident, 0) + 1
            _pool_busy(ident, +1)
            copies[i] += 1
            busy_peak[name] = max(busy_peak.get(name, 0), inflight[ident])
            self._note_dispatch(tenant)
            metrics.inc("sched_tasks_dispatched_total", label=name)
            metrics.inc("sched_tenant_dispatched_total", label=tenant)

        def _submit(i: int):
            handle, ident = _choose(i)
            if handle is None:
                # every queue is at cap (a race leftover — callers check
                # capacity first): try again shortly
                heapq.heappush(retry_q, (time.monotonic() + 0.05, i))
                return
            if blobs[i] is None:
                blobs[i] = cloudpickle.dumps(tasks[i])
            try:
                fut = handle.submit("run_task", blobs[i])
            except (ConnectionLost, OSError) as e:
                # a crashed executor's address refuses connections until the
                # supervisor re-homes it — and a restart is a process spawn
                # plus the jax import storm, tens of seconds under load. That
                # must not burn the task-retry budget: mark the executor
                # down, rotate, and keep probing within a wall-clock grace.
                now = time.monotonic()
                _mark_down(ident, handle.name or ident)
                if unreach_since[i] is None:
                    unreach_since[i] = now
                uprobe[i] += 1
                if now - unreach_since[i] > _unreachable_grace_s():
                    raise StageError(
                        f"no reachable executor for task "
                        f"{tasks[i].task_id} after {uprobe[i]} probes over "
                        f"{now - unreach_since[i]:.0f}s: {e}") from e
                delay = _backoff_delay(uprobe[i], rng)
                logger.warning("submit of task %s to %s failed (probe %d, "
                               "retry in %.2fs): %s", tasks[i].task_id,
                               handle.name or ident, uprobe[i], delay, e)
                heapq.heappush(retry_q, (now + delay, i))
                return
            unreach_since[i] = None
            uprobe[i] = 0
            # the submit reached it: a down-marked executor (a restart the
            # node agent finished mid-action) re-enters placement now
            self._mark_up(ident, handle.name or ident)
            if preferred is not None and preferred[i] is not None \
                    and (handle.name or ident) == preferred[i]:
                # data-gravity hit: the task landed where its bytes live
                metrics.inc("sched_locality_hits_total")
            _register(fut, i, ident, handle.name or ident, False)

        def _maybe_speculate(now: float) -> Optional[float]:
            """Submit backups for straggling attempts; return seconds until
            the next attempt becomes eligible (None = nothing to watch).
            Fairness-gated like any dispatch: a backup is extra load, and
            duplicating work while a contending tenant is under-served
            would amplify the overload speculation is meant to dodge."""
            if not spec_on or done_cnt < spec_gate or done_cnt >= n \
                    or not durations or not self._fair_ok(tenant):
                return None
            med = sorted(durations)[len(durations) // 2]
            threshold = max(spec_mult * med, spec_min_s)
            next_due = None
            for at in list(pending.values()):
                i = at.i
                if at.backup or results[i] is not None or i in speculated \
                        or blobs[i] is None:
                    continue
                age = now - at.started
                if age < threshold:
                    due = threshold - age
                    next_due = due if next_due is None else min(next_due, due)
                    continue
                handle, ident = _choose(i, exclude=at.ident, probe=False)
                if handle is None:
                    continue  # no DISTINCT live executor below cap right now
                try:
                    bfut = handle.submit("run_task", blobs[i])
                except (ConnectionLost, OSError):
                    _mark_down(ident, handle.name or ident)
                    continue
                speculated.add(i)
                _register(bfut, i, ident, handle.name or ident, True)
                with profiler.trace("speculate:submit", "etl",
                                    task_id=tasks[i].task_id,
                                    to=handle.name or ident,
                                    after_s=round(age, 3)):
                    pass
                logger.info("speculative backup of task %s submitted to %s "
                            "after %.2fs (median %.2fs)", tasks[i].task_id,
                            handle.name or ident, age, med)
            return next_due

        def _may_dispatch() -> bool:
            return _any_capacity() and self._fair_ok(tenant)

        # queued-demand signal for the autoscaler: outstanding tasks of this
        # call, decremented as each is decided, reconciled in the finally.
        # Registered BEFORE admission so a parked action's demand is what
        # the autoscaler grows for.
        self._register_tenant(tenant, tenant_weight)
        self._demand_delta(n, tenant)
        demand_left = n
        try:
            self._admit(tenant, n)
            while next_idx < n and _may_dispatch():
                _submit(next_idx)
                next_idx += 1

            while done_cnt < n:
                now = time.monotonic()
                while retry_q and retry_q[0][0] <= now and _may_dispatch():
                    _, i = heapq.heappop(retry_q)
                    if results[i] is None:
                        _submit(i)  # a backup may have won while it waited
                spec_due = _maybe_speculate(time.monotonic())
                if not pending:
                    if retry_q:
                        delay = max(0.0, min(
                            retry_q[0][0] - time.monotonic(),
                            _RETRY_BACKOFF_CAP_S))
                        if delay <= 0 and not _may_dispatch():
                            # a due retry with no slot (a full pool, or the
                            # fair-share gate): yield instead of spinning
                            delay = 0.05
                        time.sleep(delay)
                        continue
                    if next_idx < n:
                        if self._fair_ok(tenant):
                            _submit(next_idx)
                            next_idx += 1
                        else:
                            # fairness-parked with nothing in flight: wait
                            # for the contending tenant's share to move
                            time.sleep(0.05)
                        continue
                    break
                # a due retry only shortens the wait when a slot is free to
                # take it — otherwise timeout=0 would busy-spin against a
                # full pool (or the fair-share gate) until some in-flight
                # task completes; a pending speculation deadline shortens
                # it likewise
                timeout = max(0.0, retry_q[0][0] - time.monotonic()) \
                    if retry_q and _may_dispatch() else None
                if spec_due is not None:
                    timeout = spec_due if timeout is None \
                        else min(timeout, spec_due)
                if timeout is None and (next_idx < n or retry_q):
                    # work is queued: wake on a bounded poll so a capacity
                    # change the futures cannot signal — an executor the
                    # autoscaler just admitted, or a down TTL expiring —
                    # is dispatched to promptly, not after the next
                    # (possibly minutes-long) in-flight completion
                    timeout = 0.25
                done, _ = wait(list(pending.keys()), timeout=timeout,
                               return_when=FIRST_COMPLETED)
                for fut in done:
                    at = pending.pop(fut)
                    i = at.i
                    inflight[at.ident] = inflight.get(at.ident, 1) - 1
                    _pool_busy(at.ident, -1)
                    copies[i] -= 1
                    err = fut.exception()
                    if err is None:
                        # the executor answered: whatever marked it down is
                        # over — re-admit it to placement at once
                        self._mark_up(at.ident, at.name)
                    if results[i] is not None:
                        # a duplicate of an already-decided task: the
                        # speculation loser — drain it, free its outputs
                        if err is None:
                            self._free_loser_result(fut, results[i])
                        elif isinstance(err, ConnectionLost):
                            _mark_down(at.ident, at.name)
                        continue
                    if err is None:
                        r = fut.result()
                        results[i] = r
                        done_cnt += 1
                        demand_left -= 1
                        self._demand_delta(-1, tenant)
                        durations.append(time.monotonic() - at.started)
                        if on_result is not None:
                            try:
                                on_result(i, r)
                            except Exception:
                                logger.warning(
                                    "task-result callback failed for %s",
                                    tasks[i].task_id, exc_info=True)
                        if i in speculated:
                            r["_speculated"] = 1
                            if at.backup:
                                spec_won += 1
                                r["_speculation_won"] = 1
                                with profiler.trace(
                                        "speculate:win", "etl",
                                        task_id=tasks[i].task_id,
                                        on=at.name):
                                    pass
                                logger.info(
                                    "speculative backup of task %s won on "
                                    "%s", tasks[i].task_id, at.name)
                        continue
                    if isinstance(err, ConnectionLost) and at.ident:
                        # the executor died mid-task: steer the resubmit (and
                        # every sibling) away from it while it restarts
                        _mark_down(at.ident, at.name)
                    if isinstance(err, RemoteError) \
                            and err.exc_type == "ObjectLostError":
                        lost = _lost_ids_of(err)
                        raise ObjectsLostError(
                            f"task {tasks[i].task_id} read lost store "
                            f"objects {lost}: {err.message}", lost) from err
                    if (isinstance(err, RemoteError)
                            and err.exc_type in _NO_RETRY_EXC_TYPES):
                        raise StageError(
                            f"task {tasks[i].task_id} failed: {err}") from err
                    attempts[i] += 1
                    if copies[i] > 0:
                        # a sibling copy of this task is still in flight —
                        # it IS the retry; queuing another would triple-run
                        logger.warning(
                            "task %s attempt failed on %s; its speculative "
                            "sibling is still running", tasks[i].task_id,
                            at.name)
                        continue
                    if attempts[i] > self.max_task_retries:
                        raise StageError(
                            f"task {tasks[i].task_id} failed after "
                            f"{attempts[i]} attempts: {err}") from err
                    delay = _backoff_delay(attempts[i], rng)
                    logger.warning(
                        "task %s failed on %s (attempt %d, retry in %.2fs): %s",
                        tasks[i].task_id, at.name, attempts[i], delay,
                        str(err).splitlines()[0] if str(err) else err)
                    heapq.heappush(retry_q, (time.monotonic() + delay, i))
                while next_idx < n and _may_dispatch():
                    _submit(next_idx)
                    next_idx += 1
        except ObjectsLostError as e:
            # keep completed results: the engine reuses them after lineage
            # recovery (their outputs are its responsibility from here on).
            # Sibling consumers failing on OTHER lost blobs surface during
            # the drain — harvesting their ids lets one recovery round
            # regenerate everything a dead host took, not one blob per round.
            more = self._drain_merge(pending, results, retry_q)
            e.lost_ids = list(dict.fromkeys(e.lost_ids + more))
            e.partial = list(results)
            raise
        except Exception:
            # ANY stage failure (StageError or an unexpected driver-side
            # error, e.g. an injected rpc fault) runs the abort contract:
            # cancel queued retries, drain in-flight tasks, free outputs
            self._abort_stage(pending, results, retry_q)
            raise
        else:
            # every task is decided; losing duplicates may still be running —
            # do NOT wait for them (that would hand the straggler back its
            # hostage). Whenever each one lands, its outputs are freed and a
            # late cache-put dropped through the loser path.
            for fut, at in list(pending.items()):
                winner = results[at.i]
                fut.add_done_callback(
                    lambda f, w=winner: self._free_loser_result(f, w))
            pending.clear()
            if speculated:
                metrics.inc("sched_speculated_total", len(speculated))
            if spec_won:
                metrics.inc("sched_speculation_won_total", spec_won)
            if sched_stats is not None:
                sched_stats["speculated"] = \
                    sched_stats.get("speculated", 0) + len(speculated)
                sched_stats["speculation_won"] = \
                    sched_stats.get("speculation_won", 0) + spec_won
                peb = sched_stats.setdefault("per_executor_busy", {})
                for name, peak in busy_peak.items():
                    peb[name] = max(peb.get(name, 0), peak)
            return results  # type: ignore[return-value]
        finally:
            # reconcile the pool-wide signals whatever path exits: attempts
            # still counted (losers left running, drain-abandoned
            # stragglers) stop counting as busy, and this call's undecided
            # demand is withdrawn — a failed stage must read as idle, not
            # as a queue the autoscaler keeps growing for. The per-tenant
            # twins reconcile through the same two calls, so no exit path
            # (abort, speculation losers, mid-stage drain, admission
            # rejection) can leak phantom per-tenant load either.
            self._demand_delta(-demand_left, tenant)
            for ident, k in pool_acct.items():
                if k:
                    self._busy_delta(ident, -k, tenant)

    def _drain_merge(self, pending: Dict[Any, "_Attempt"],
                     results: List[Optional[Dict[str, Any]]],
                     retry_q: List[Tuple[float, int]]) -> List[str]:
        """Stage abort: cancel queued resubmits and drain in-flight tasks
        KEEPING whatever completed — unlike :meth:`_abort_stage`, nothing is
        freed, because the caller either resubmits around these results or
        frees them itself when recovery gives up. Speculation duplicates of
        tasks that already have a result are the exception: their outputs
        reach no caller, so they free here (the winner's refs are what the
        caller keeps). Returns lost object ids harvested from tasks that
        failed lost-blob during the drain."""
        retry_q.clear()
        lost: List[str] = []
        if not pending:
            return lost
        done, not_done = wait(list(pending.keys()), timeout=_DRAIN_TIMEOUT_S)
        if not_done:
            logger.warning(
                "abandoning %d in-flight tasks still running %.0fs after a "
                "stage abort; their outputs free on completion",
                len(not_done), _DRAIN_TIMEOUT_S)
            for fut in not_done:
                # whenever the straggler finally lands, free what it wrote —
                # its output is in neither results nor temps, so nothing
                # else would ever release it
                fut.add_done_callback(self._free_late_result)
        for fut in done:
            at = pending[fut]
            err = fut.exception()
            if err is None:
                if results[at.i] is None:
                    results[at.i] = fut.result()
                else:
                    self._free_loser_result(fut, results[at.i])
            elif isinstance(err, RemoteError) \
                    and err.exc_type == "ObjectLostError":
                lost.extend(_lost_ids_of(err))
        pending.clear()
        return lost

    def _free_late_result(self, fut) -> None:
        """Completion callback for a task abandoned past the drain timeout:
        free its store outputs, and drop a late-cached block from its
        executor — the block landed AFTER the aborting action's prefix sweep
        ran, and each persist() uses a fresh frame id, so no later sweep
        would ever target it (it would pin executor RAM forever)."""
        self._free_loser_result(fut, None)

    def _free_loser_result(self, fut, winner: Optional[Dict[str, Any]]
                           ) -> None:
        """Free the outputs of a task attempt whose result reaches no caller
        — a speculation loser, or a drain-abandoned straggler landing late.

        The work runs on a throwaway daemon thread: this may fire as a
        Future done-callback on the executor connection's RPC read loop, and
        ``drop_blocks`` is a synchronous call over that same connection —
        issued inline it would block the only thread able to deliver its own
        response, wedging the connection for every later task on that
        executor."""
        threading.Thread(target=self._free_loser_result_sync,
                         args=(fut, winner), daemon=True,
                         name="rdt-free-late-result").start()

    def _free_loser_result_sync(self, fut,
                                winner: Optional[Dict[str, Any]]) -> None:
        try:
            err = fut.exception()
            if err is not None:
                return  # a failed loser wrote nothing that survived
            res = fut.result()
            _free_result_refs([res])
            key = res.get("cache_key")
            if key is None:
                return
            if winner is not None and winner.get("cache_key") == key \
                    and winner.get("executor") == res.get("executor") \
                    and winner.get("cache_stamp") == res.get("cache_stamp"):
                # both copies ran on ONE executor and the duplicate
                # cache-put was idempotent (BlockCache.put_once returned
                # the first put's stamp): the loser's entry IS the block
                # the winner's CachedScan references — leave it alone
                return
            h = self.by_name.get(res.get("executor"))
            if h is not None:
                # stamp-conditioned: a lineage-recovery resubmit of
                # this same task may have re-cached the key on this
                # executor; only OUR stale generation must go
                h.drop_blocks([key], res.get("cache_stamp"))
        except Exception:
            pass  # store/executor may already be shut down; nothing to salvage

    def _abort_stage(self, pending: Dict[Any, "_Attempt"],
                     results: List[Optional[Dict[str, Any]]],
                     retry_q: List[Tuple[float, int]]) -> None:
        """The stage is failing: cancel queued resubmits, wait out tasks that
        are still executing on the pool (there is no remote cancel — draining
        is what keeps them from writing into the store after the driver has
        given up), and free every output the caller will never receive."""
        metrics.inc("stage_aborts_total")
        metrics.record_event("stage_abort",
                             inflight=len(pending),
                             completed=sum(1 for r in results
                                           if r is not None))
        self._drain_merge(pending, results, retry_q)
        _free_result_refs(results)


class Engine:
    """Thread-safe: shuffle intermediates are tracked in a per-action list
    threaded through compilation (two concurrent actions on one session must
    not cross-free each other's intermediates — the reference's Spark driver
    supports concurrent actions).

    ``tenant``/``tenant_weight`` tag every stage this engine dispatches for
    the pool's weighted fair sharing (doc/etl.md "Fair sharing and
    admission"). The tenant id is session-scoped by default (the owning
    master's name); a second Engine over the SAME ExecutorPool with a
    different tenant is how two user programs share one executor fleet.
    ``tenant_weight=None`` re-reads ``RDT_POOL_TENANT_WEIGHT`` per action."""

    def __init__(self, pool: ExecutorPool, shuffle_partitions: int = 8,
                 owner: Optional[str] = None, tenant: Optional[str] = None,
                 tenant_weight: Optional[float] = None):
        self.pool = pool
        self.shuffle_partitions = shuffle_partitions
        self.owner = owner
        self.tenant = tenant or owner or _DEFAULT_TENANT
        self.tenant_weight = tenant_weight
        self._report_lock = threading.Lock()
        # bounded per-engine shuffle-stage ledger (one entry per wide-op
        # stage); benchmarks and tests read it through shuffle_stage_report()
        # guarded-by: _report_lock
        self._stage_reports: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=256)
        self._retry_rng = random.Random()  # jitter for recovery resubmits
        # last measured-bytes figure pushed to the store's budget plane
        # (derive_store_budgets skips the RPC when unchanged)
        self._last_budget_measured: Optional[int] = None

    # ---- shuffle accounting -------------------------------------------------
    def _record_stage(self, label: str, results: Sequence[Dict[str, Any]],
                      num_buckets: int,
                      temps: Optional[List[ObjectRef]] = None,
                      sched_stats: Optional[Dict[str, Any]] = None,
                      pipelined: bool = False) -> None:
        """Aggregate map-task shuffle counters into one stage entry and emit
        a driver-side trace span carrying the totals as args."""
        rows = sum(int(r.get("num_rows", 0)) for r in results)
        nbytes = sum(int(r.get("shuffle_bytes", 0)) for r in results)
        rows_in = sum(int(r.get("shuffle_rows_in", r.get("num_rows", 0)))
                      for r in results)
        bytes_in = sum(int(r.get("shuffle_bytes_in", 0)) for r in results)
        entry = {"stage": label, "maps": len(results),
                 "buckets": num_buckets,
                 # which tenant's action ran this stage (weighted fair
                 # sharing across concurrent engines on one pool)
                 "tenant": self.tenant,
                 "rows_in": rows_in, "bytes_in": bytes_in,
                 "rows_shuffled": rows, "bytes_shuffled": nbytes,
                 # store control-plane traffic: metadata (seal/lookup) and
                 # payload-fetch RPCs issued by this stage's map tasks;
                 # reduce-side reads are attributed here later via
                 # Task.consumes_stage (_attribute_consumer_rpcs)
                 "meta_rpcs": sum(int(r.get("meta_rpcs", 0))
                                  for r in results),
                 "fetch_rpcs": sum(int(r.get("fetch_rpcs", 0))
                                   for r in results),
                 "consolidated": any(r.get("consolidated_ref") is not None
                                     for r in results),
                 # straggler-scheduler accounting: tasks that got a
                 # speculative backup / whose backup won (driver-side
                 # annotations on the winning results — reduce-task
                 # speculation folds in later via Task.consumes_stage), and
                 # the per-executor peak in-flight depth of the MAP stage
                 "speculated": sum(int(r.get("_speculated", 0))
                                   for r in results),
                 "speculation_won": sum(int(r.get("_speculation_won", 0))
                                        for r in results),
                 "per_executor_busy": dict(
                     (sched_stats or {}).get("per_executor_busy") or {}),
                 # adaptive-execution accounting: joins converted to
                 # broadcast, skewed buckets split, and buckets fused away
                 # by coalescing (all 0 when AQE is off or no rule fired)
                 "aqe_broadcast": 0, "aqe_split": 0, "aqe_coalesced": 0,
                 # pipelined-shuffle accounting: was this stage's reduce
                 # side dispatched concurrently with the maps; how long
                 # reducers spent fetching/decoding BEFORE the last map
                 # sealed (the measured overlap); and how soon after the
                 # map stage began the first reduce-side fetch started
                 # (reduce-side numbers fold in via Task.consumes_stage)
                 "pipelined": pipelined, "overlap_s": 0.0,
                 "first_reduce_fetch_s": None,
                 # lineage-recovery accounting: blobs regenerated for this
                 # stage's intermediates, and how many recovery events ran
                 "regenerated": 0, "recovered": 0}
        with self._report_lock:
            self._stage_reports.append(entry)
            if isinstance(temps, _ActionTemps):
                temps.stage_entries[label] = entry
                # bind the entry to the producers just ledgered for these
                # results, so recovery attributes to THIS stage even after
                # a later same-label stage overwrites stage_entries[label]
                for r in results:
                    for ref in _result_refs(r):
                        prod = temps.lineage.get(ref.id)
                        if prod is not None and prod.label == label \
                                and prod.entry is None:
                            prod.entry = entry
        with profiler.trace(f"shuffle:{label}", "etl", maps=len(results),
                            buckets=num_buckets, rows_in=rows_in,
                            bytes_in=bytes_in, rows_shuffled=rows,
                            bytes_shuffled=nbytes):
            pass
        return entry

    def shuffle_stage_report(self) -> List[Dict[str, Any]]:
        """Per-stage shuffle ledger: one dict per wide-op stage executed by
        this engine ({stage, tenant, maps, buckets, rows_in, bytes_in,
        rows_shuffled, bytes_shuffled, meta_rpcs, fetch_rpcs, consolidated,
        regenerated, recovered}); ``tenant`` is the fair-share tenant the
        stage was dispatched under (doc/etl.md "Fair sharing and
        admission"); in = entering the shuffle stage (before map-side partial
        aggregation), shuffled = what crossed the object store.
        ``meta_rpcs``/``fetch_rpcs`` count store control-plane calls (table
        ops / payload fetches) issued by the stage's map tasks plus its
        reduce tasks' reads — an upper bound when tasks overlap on one
        executor (they share process counters); the exact session totals are
        ``ObjectStoreServer.op_counts()``. ``consolidated`` marks the
        single-blob map output format. ``speculated``/``speculation_won``
        count tasks that got a speculative backup and tasks whose backup
        finished first (map tasks plus the stage's reduce-side consumers;
        0/0 on a straggler-free run); ``per_executor_busy`` maps executor
        name → the peak in-flight task depth the least-loaded dispatcher
        drove it to during the map stage. ``aqe_broadcast``/``aqe_split``/
        ``aqe_coalesced`` count adaptive re-planning events on the stage:
        joins converted to broadcast-hash (the ``join-broadcast`` entry is
        the pre-shuffle form; a post-map conversion marks the map stage it
        measured), skewed buckets split across extra reduce tasks, and
        reduce buckets fused away by tiny-partition coalescing (all 0 with
        ``RDT_ETL_AQE=0`` or when no rule fired). ``pipelined`` marks a
        stage whose reduce side was dispatched concurrently with its maps
        (push-based shuffle, ``RDT_SHUFFLE_PIPELINE``); ``overlap_s`` is the
        total time its reducers spent fetching/decoding BEFORE the last map
        sealed and ``first_reduce_fetch_s`` how soon after the map stage
        began the first reduce-side fetch started (False/0.0/None on a
        barrier-mode stage; first_reduce_fetch_s compares the driver's
        clock against the executor's ``time.time()``, so on a MULTI-host
        pool it is subject to cross-machine clock skew — overlap_s is
        executor-local and skew-free). ``regenerated`` counts intermediate blobs rebuilt
        through lineage recovery after a store loss, ``recovered`` the
        recovery events that rebuilt them (0/0 on a fault-free run)."""
        with self._report_lock:
            return [dict(e) for e in self._stage_reports]

    def _note_recovery(self, prod: _Producer, num_blobs: int,
                       temps: "_ActionTemps") -> None:
        """Attribute a lineage-recovery event to the entry of the stage that
        produced the lost blobs — the producer's own binding first (distinct
        for two same-label stages in one action), then the action's entry for
        that label; concurrent actions may interleave same-label entries in
        the engine deque, so "most recent with this label" would be the wrong
        stage exactly when two actions shuffle at once. A label the action
        never recorded (e.g. a ``materialize``) gets a bare entry with zero
        shuffle counters, registered so repeat recoveries accumulate."""
        with self._report_lock:
            entry = prod.entry
            if entry is None:
                entry = temps.stage_entries.get(prod.label)
            if entry is None:
                entry = {"stage": prod.label, "maps": 0, "buckets": 0,
                         "tenant": self.tenant,
                         "rows_in": 0, "bytes_in": 0, "rows_shuffled": 0,
                         "bytes_shuffled": 0, "meta_rpcs": 0,
                         "fetch_rpcs": 0, "consolidated": False,
                         "speculated": 0, "speculation_won": 0,
                         "per_executor_busy": {},
                         "aqe_broadcast": 0, "aqe_split": 0,
                         "aqe_coalesced": 0,
                         "pipelined": False, "overlap_s": 0.0,
                         "first_reduce_fetch_s": None,
                         "regenerated": 0, "recovered": 0}
                self._stage_reports.append(entry)
                temps.stage_entries[prod.label] = entry
            prod.entry = entry
            entry["regenerated"] += num_blobs
            entry["recovered"] += 1

    def reset_shuffle_stage_report(self) -> None:
        with self._report_lock:
            self._stage_reports.clear()

    # ---- AQE-fed store policy plane ------------------------------------------
    def measured_stage_bytes(self, window: int = 32) -> int:
        """Peak measured working set over the last ``window`` ledger
        entries: per stage, the bytes that entered it plus the bytes it
        moved through the store (bytes_in + bytes_shuffled). This is the
        AQE plane's measured-bytes signal — what store budget derivation
        and predictive autoscaling size from (0 until a stage has run)."""
        with self._report_lock:
            entries = list(self._stage_reports)[-max(1, int(window)):]
        return max((int(e.get("bytes_in") or 0)
                    + int(e.get("bytes_shuffled") or 0)
                    for e in entries), default=0)

    def derive_store_budgets(self) -> Optional[Dict[str, int]]:
        """Feed the stage ledger's measured bytes to the store's budget
        plane (``ObjectStoreServer.derive_budgets``): per-host budgets
        re-derive from what stages actually moved instead of only the
        static ``ENV_STORE_*`` numbers. Gated by ``RDT_STORE_AQE_BUDGET``;
        skips the RPC when the measured figure has not changed; never
        raises (a failed derivation leaves the static budgets standing)."""
        if not bool(knobs.get("RDT_STORE_AQE_BUDGET")):
            return None
        measured = self.measured_stage_bytes()
        if measured <= 0 or measured == self._last_budget_measured:
            return None
        try:
            out = get_client().derive_budgets(measured)
        except Exception:
            logger.warning("store budget derivation failed; static budgets "
                           "stand", exc_info=True)
            return None
        self._last_budget_measured = measured
        return out

    def _push_stage_hints(self, tasks: Sequence[T.Task]) -> List[ObjectRef]:
        """Pin this stage's input blobs in the store for its duration
        (stage-aware eviction, doc/etl.md "Store budgets"); returns the
        refs to unpin when the stage completes. Advisory and best-effort:
        a store that cannot take hints changes nothing. Deliberately NOT
        a metadata RPC (the data-plane counters stay comparable)."""
        if not bool(knobs.get("RDT_STORE_STAGE_HINTS")):
            return []
        seen: Dict[str, ObjectRef] = {}
        for t in tasks:
            for oid in T.task_input_ids(t):
                if oid not in seen:
                    seen[oid] = ObjectRef(id=oid)
        if not seen:
            return []
        refs = list(seen.values())
        try:
            get_client().eviction_hints(pin=refs)
        except Exception:
            return []
        return refs

    def _drop_stage_hints(self, refs: List[ObjectRef]) -> None:
        """The stage completed (or aborted): release its pins — at
        refcount zero the store demotes the blobs to evict-first (their
        consumer stage is done with them; LRU breaks ties only)."""
        if not refs:
            return
        try:
            get_client().eviction_hints(unpin=refs)
        except Exception:
            pass

    # ---- elastic pool: graceful drain ---------------------------------------
    def retire_executor(self, name: str, rehome=None, reap=None,
                        timeout: Optional[float] = None) -> Dict[str, Any]:
        """Gracefully drain one executor out of the pool (doc/etl.md
        "Elastic executor pool"; doc/fault_tolerance.md "Scale events").

        Protocol: (1) the scheduler stops routing new dispatches to it
        (:meth:`ExecutorPool.begin_drain`); (2) its in-flight tasks finish —
        or, if it dies mid-drain, fail and re-queue onto survivors through
        the ordinary retry/recovery machinery — bounded by
        ``RDT_DRAIN_TIMEOUT_S``; (3) its executor-RAM state is either
        re-homed (``RDT_DRAIN_REHOME=1``: the caller's ``rehome(name)`` hook
        rebuilds cached blocks on survivors from their lineage recipes) or
        deliberately abandoned to on-read lineage recovery; (4) it leaves
        every membership snapshot; (5) the caller's ``reap(handle)`` hook
        kills the process (through the node agent on remote nodes). Store
        blobs are machine-homed, not executor-homed, so the drain never
        moves store payloads — a mid-stream pipelined shuffle keeps its
        sealed generations, and a crash mid-drain re-seals via recovery.

        The ``pool.drain`` fault site fires here (key: executor name);
        action ``crash`` kills the RETIRING executor abruptly mid-drain —
        the chaos model for scale-down racing live work."""
        handle = self.pool.by_name.get(name)
        if handle is None:
            raise KeyError(f"unknown executor {name!r}")
        if timeout is None:
            timeout = float(knobs.get("RDT_DRAIN_TIMEOUT_S"))
        if not self.pool.begin_drain(name):
            raise ValueError(f"executor {name!r} is already draining")
        metrics.inc("pool_drains_total")
        metrics.record_event("executor_drain", executor=name)
        logger.info("draining executor %s out of the pool", name)
        try:
            rule = faults.check("pool.drain", key=name)
            if rule is not None:
                if rule.action == "crash":
                    # the RETIRING executor dies mid-drain (scale-down
                    # racing recovery/streams) — never this driver process.
                    # submit, not call: the process exits before replying
                    try:
                        handle.submit("crash")
                    except Exception:
                        pass
                else:
                    faults.apply(rule, "pool.drain")
            quiesced = self.pool.wait_idle(name, timeout)
            if not quiesced:
                logger.warning(
                    "executor %s still busy after the %.0fs drain window; "
                    "abandoning its in-flight tasks to retry/recovery",
                    name, timeout)
            rehomed = 0
            if rehome is not None and bool(knobs.get("RDT_DRAIN_REHOME")):
                try:
                    rehomed = int(rehome(name) or 0)
                except Exception:
                    # abandonment is always safe: a cached block that never
                    # re-homed rebuilds from its recipe on the next read
                    logger.warning("drain re-home for %s failed; its blocks "
                                   "recover through lineage on read", name,
                                   exc_info=True)
        except BaseException:
            # a failed retirement must not leave the executor unreachable
            # by the scheduler forever
            self.pool.cancel_drain(name)
            raise
        self.pool.remove_executor(name)
        if reap is not None:
            try:
                reap(handle)
            except Exception:
                logger.warning("reap of drained executor %s failed", name,
                               exc_info=True)
        return {"executor": name, "quiesced": quiesced, "rehomed": rehomed,
                "pool_size": len(self.pool.executors)}

    @staticmethod
    def _optimized(node: P.PlanNode) -> P.PlanNode:
        """Plan rewrite applied at every action entry point; the naive
        compile-verbatim path survives under RDT_ETL_OPTIMIZER=0."""
        return O.optimize(node)

    def _num_buckets(self) -> int:
        """Reduce-side bucket count for wide operators: capped by the
        configured shuffle parallelism, scaled to the executor pool."""
        return min(self.shuffle_partitions, max(1, len(self.pool.executors) * 2))

    @staticmethod
    def _gather_buckets(results: Sequence[Dict[str, Any]], num_buckets: int,
                        temps: List[ObjectRef]) -> List[List[Any]]:
        """Transpose map-task shuffle outputs (map × bucket → bucket × map),
        registering every intermediate ref in ``temps``. A consolidated map
        result contributes ``(ref, offset, size)`` byte-range triples into
        every bucket list (but only ONE temp ref — the blob); legacy results
        contribute whole-blob :class:`ObjectRef`\\ s, so a stage can mix
        formats and :meth:`_bucket_source` still builds a working reader."""
        buckets: List[List[Any]] = [[] for _ in range(num_buckets)]
        for r in results:
            cref = r.get("consolidated_ref")
            if cref is not None:
                temps.append(cref)
                for b, (off, size, _rows) in enumerate(r["bucket_index"]):
                    buckets[b].append((cref, int(off), int(size)))
            else:
                for b, ref in enumerate(r["bucket_refs"]):
                    buckets[b].append(ref)
                    temps.append(ref)
        return buckets

    @staticmethod
    def _bucket_source(bucket: Sequence[Any],
                       schema: Optional[bytes]) -> T.Step:
        """Reader step for one reduce bucket: whole-blob refs decode through
        :class:`tasks.ArrowRefSource` as always; byte-range triples (the
        consolidated format) through :class:`tasks.RangeRefSource` — with
        legacy refs normalized to full-blob ranges when a stage mixes both.
        A pipelined stage's bucket is a :class:`_StreamBucket` placeholder
        and reads through :class:`tasks.StreamingRangeSource` instead."""
        for x in bucket:
            if isinstance(x, _StreamBucket):
                return x.source(schema)
        if any(isinstance(x, tuple) for x in bucket):
            return T.RangeRefSource(Engine._as_parts(bucket), schema=schema)
        return T.ArrowRefSource(list(bucket), schema=schema)

    def _bucket_task(self, bucket: Sequence[Any], schema: Optional[bytes],
                     steps: Optional[List[T.Step]], label: str) -> T.Task:
        """A reduce task over one bucket, tagged with the stage it consumes
        so its store-RPC counters land on that stage's ledger entry — and,
        when that stage is pipelined, with its UNIQUE stream key (labels
        repeat within one action, stream keys never do)."""
        task = self._task(self._bucket_source(bucket, schema), steps)
        task.consumes_stage = label
        for x in bucket:
            if isinstance(x, _StreamBucket):
                task.consumes_stream = x.rec.stage_key
                break
        return task

    # ---- adaptive query execution (AQE) -------------------------------------
    # The three runtime re-planning rules (doc/etl.md "Adaptive execution"):
    # (a) broadcast-hash join — a join side whose MEASURED bytes fit under
    #     RDT_AQE_BROADCAST_MAX skips its shuffle and replicates instead
    #     (pre-shuffle when a static estimate flags it, post-map when the
    #     left map stage's byte counters reveal it);
    # (b) skew splitting — a reduce bucket exceeding RDT_AQE_SKEW_FACTOR ×
    #     the median bucket splits its byte-ranges across k reduce tasks
    #     (free at range granularity with the consolidated per-bucket index);
    # (c) tiny-partition coalescing — adjacent buckets fuse into one reduce
    #     task until their combined bytes reach RDT_AQE_COALESCE_MIN.
    # Rules (b)/(c) need the consolidated size index (RDT_SHUFFLE_CONSOLIDATE
    # =0 simply never fires them); every re-planned task flows through
    # _run_stage like any other, so lineage recovery, speculation, and the
    # abort/no-orphan contract compose unchanged.

    @staticmethod
    def _as_parts(bucket: Sequence[Any]) -> List[Tuple[ObjectRef, int, int]]:
        """Normalize a bucket's items to (ref, offset, size) byte-range
        triples (legacy whole-blob refs become full-blob ranges)."""
        return [x if isinstance(x, tuple) else (x, 0, int(x.size or 0))
                for x in bucket]

    @staticmethod
    def _bucket_bytes(buckets: Sequence[Sequence[Any]]) -> Optional[List[int]]:
        """Measured per-bucket byte totals from the consolidated index, or
        None when any bucket lacks it (legacy blobs — rules (b)/(c) then
        don't fire; a whole-blob ref's .size IS its bucket's bytes only on
        the consolidated-off path where the index is absent anyway)."""
        if not all(isinstance(x, tuple) for b in buckets for x in b):
            return None
        return [sum(int(size) for _, _, size in b) for b in buckets]

    def _note_aqe(self, temps, label: str, rule: str, n: int,
                  **trace_args) -> None:
        """Credit a fired AQE rule to the action's stage entry and emit the
        ``aqe:replan`` trace span."""
        if isinstance(temps, _ActionTemps):
            with self._report_lock:
                entry = temps.stage_entries.get(label)
                if entry is not None:
                    entry[rule] = entry.get(rule, 0) + n
        with profiler.trace("aqe:replan", "etl", stage=label, rule=rule,
                            n=n, **trace_args):
            pass

    def _aqe_coalesce(self, buckets: List[List[Any]], label: str, temps,
                      paired: Optional[List[List[Any]]] = None):
        """Rule (c): fuse runs of adjacent buckets until each fused group's
        measured bytes reach RDT_AQE_COALESCE_MIN — one multi-range read per
        group instead of one dispatch per kilobyte-sized bucket. Safe for
        every hash-bucketed op (a key's rows stay together under bucket
        union); ``paired`` fuses a join's right buckets in lockstep with the
        left so each reduce task still sees matching key ranges. Returns
        (buckets, paired)."""
        cmin = O.aqe_coalesce_min()
        if not O.aqe_enabled() or cmin <= 0 or len(buckets) < 2:
            return buckets, paired
        sizes = self._bucket_bytes(buckets)
        psizes = self._bucket_bytes(paired) if paired is not None else \
            [0] * len(buckets)
        if sizes is None or psizes is None:
            return buckets, paired  # no size index (legacy blobs)
        fused: List[List[Any]] = []
        pfused: List[List[Any]] = []
        cur_bytes = 0
        for b, bucket in enumerate(buckets):
            size = sizes[b] + psizes[b]
            if fused and cur_bytes + size <= cmin:
                fused[-1] = list(fused[-1]) + list(bucket)
                if paired is not None:
                    pfused[-1] = list(pfused[-1]) + list(paired[b])
                cur_bytes += size
            else:
                fused.append(list(bucket))
                if paired is not None:
                    pfused.append(list(paired[b]))
                cur_bytes = size
        away = len(buckets) - len(fused)
        if away > 0:
            self._note_aqe(temps, label, "aqe_coalesced", away,
                           buckets=len(buckets), fused=len(fused))
        return fused, (pfused if paired is not None else None)

    def _aqe_split_groups(self, buckets: List[List[Any]]
                          ) -> Optional[List[List[List[Any]]]]:
        """Rule (b) detector: per bucket, either ``[bucket]`` (no skew) or k
        byte-balanced contiguous range groups when the bucket's measured
        bytes exceed RDT_AQE_SKEW_FACTOR × the median bucket (and the
        2×RDT_AQE_COALESCE_MIN floor — a bucket below the coalesce target
        is never worth an extra stage). None when nothing splits."""
        factor = O.aqe_skew_factor()
        if not O.aqe_enabled() or factor <= 0 or len(buckets) < 2:
            return None
        sizes = self._bucket_bytes(buckets)
        if sizes is None:
            return None
        # LOWER median: with an even count (notably 2 buckets after heavy
        # coalescing), the upper median IS the hot bucket and skew could
        # never exceed factor × itself
        med = max(1, sorted(sizes)[(len(sizes) - 1) // 2])
        floor = 2 * O.aqe_coalesce_min()
        # split portions aim at median-bucket size (floored by the coalesce
        # target — splitting below what coalescing would fuse is pure churn)
        split_target = max(med, O.aqe_coalesce_min(), 1)
        out: List[List[List[Any]]] = []
        fired = False
        for bucket, size in zip(buckets, sizes):
            if size <= factor * med or size < floor or len(bucket) < 2:
                out.append([list(bucket)])
                continue
            k = min(len(bucket), max(2, math.ceil(size / split_target)))
            target = size / k
            groups: List[List[Any]] = [[]]
            acc = 0
            for part in bucket:
                psz = int(part[2]) if isinstance(part, tuple) else 0
                if groups[-1] and acc + psz > target \
                        and len(groups) < k:
                    groups.append([])
                    acc = 0
                groups[-1].append(part)
                acc += psz
            if len(groups) < 2:
                out.append([list(bucket)])
                continue
            fired = True
            out.append(groups)
        return out if fired else None

    @staticmethod
    def _free(temps: List[ObjectRef]) -> None:
        if isinstance(temps, _ActionTemps):
            # join pipelined map stages FIRST: their outputs register here
            # as they seal, and freeing under still-running writers would
            # orphan whatever lands after the sweep
            temps.close_streams()
        if temps:
            try:
                get_client().free(temps)
            except Exception:
                logger.warning("failed to free %d shuffle intermediates", len(temps))

    # ---- lineage recovery ---------------------------------------------------
    @staticmethod
    def _record_lineage(temps: List[ObjectRef], tasks: Sequence[T.Task],
                        results: Sequence[Dict[str, Any]], label: str,
                        task_bytes: Optional[Sequence[bytes]] = None) -> None:
        """Ledger every intermediate a stage just produced against its
        serialized producer task: shuffle buckets in bucket order, RETURN_REF
        blocks as singletons. The recipe (not the data) is what makes a lost
        blob recoverable on any executor — SURVEY.md's lineage-based fault
        tolerance, extended from ``cache()`` frames to every intermediate.
        ``task_bytes`` reuses the dispatch payloads so recording adds no
        second serialization pass."""
        if not isinstance(temps, _ActionTemps):
            return
        for i, (task, r) in enumerate(zip(tasks, results)):
            ids = [ref.id for ref in _result_refs(r)]
            if not ids:
                continue
            blob = task_bytes[i] if task_bytes is not None \
                else cloudpickle.dumps(task)
            prod = _Producer(blob, ids, label)
            for oid in ids:
                temps.lineage[oid] = prod

    def _run_stage(self, tasks: Sequence[T.Task],
                   preferred: Optional[Sequence[Optional[str]]] = None,
                   temps: Optional[List[ObjectRef]] = None,
                   lineage_label: Optional[str] = None,
                   sched_stats: Optional[Dict[str, Any]] = None,
                   on_task_result: Optional[Any] = None,
                   _depth: int = 0) -> List[Dict[str, Any]]:
        """``pool.run_tasks`` with lineage recovery: on a lost-blob failure,
        re-execute the producers of the lost intermediates (transitively,
        bounded depth), re-home the regenerated blobs, patch the stage's
        input refs, and resubmit — with exponential backoff + jitter between
        rounds. ``RDT_LINEAGE_RECOVERY=0`` disables recovery (the loss then
        surfaces as the ``StageError`` it always was).

        ``lineage_label`` ledgers the stage's own outputs AFTER it succeeds —
        recorded here, not by the caller, so the recipes carry any ref
        patches recovery applied (a recipe referencing an already-dead input
        id would force a pointless transitive round later).

        ``on_task_result(i, task, task_bytes, result)`` fires once per task
        index as its winning result lands (the pipelined shuffle's
        seal-notification hook; ``task_bytes`` is the dispatch payload so an
        incremental lineage ledger costs no extra serialization)."""
        with profiler.trace("stage:run", "etl", tasks=len(tasks),
                            label=lineage_label or "-", depth=_depth):
            return self._run_stage_traced(tasks, preferred, temps,
                                          lineage_label, sched_stats,
                                          on_task_result, _depth)

    def _run_stage_traced(self, tasks, preferred=None, temps=None,
                          lineage_label=None, sched_stats=None,
                          on_task_result=None, _depth=0):
        tasks = list(tasks)
        results: List[Optional[Dict[str, Any]]] = [None] * len(tasks)
        rounds = _recovery_rounds() \
            if _recovery_enabled() and isinstance(temps, _ActionTemps) else 0
        attempt = 0
        # one serialization per task, shared by dispatch AND the lineage
        # ledger; a recovery round invalidates only the entries it patched
        # (the blobs must match what actually ran / what a rerun would read)
        blobs: Optional[List[Optional[bytes]]] = \
            [None] * len(tasks) if lineage_label is not None else None
        notified = [False] * len(tasks)
        # stage-aware eviction: pin this stage's input blobs for its
        # duration; the finally demotes them to evict-first (their
        # consumer is done) whether the stage returns or aborts
        hinted = self._push_stage_hints(tasks)

        def _notify(i: int, r: Dict[str, Any]) -> None:
            if on_task_result is None or notified[i]:
                return
            notified[i] = True
            try:
                on_task_result(i, tasks[i],
                               blobs[i] if blobs is not None else None, r)
            except Exception:
                logger.warning("stage result hook failed for task %s",
                               tasks[i].task_id, exc_info=True)

        try:
            while True:
                todo = [i for i, r in enumerate(results) if r is None]
                sub_pref = [preferred[i] for i in todo] \
                    if preferred is not None else None
                if blobs is not None:
                    for i, t in enumerate(tasks):
                        if blobs[i] is None:
                            blobs[i] = cloudpickle.dumps(t)
                cb = None
                if on_task_result is not None:
                    def cb(j, r, _todo=todo):
                        _notify(_todo[j], r)
                try:
                    out = self.pool.run_tasks(
                        [tasks[i] for i in todo], sub_pref,
                        payloads=[blobs[i] for i in todo]
                        if blobs is not None else None,
                        sched_stats=sched_stats, on_result=cb,
                        tenant=self.tenant,
                        tenant_weight=self.tenant_weight)
                    for i, r in zip(todo, out):
                        results[i] = r
                    if lineage_label is not None:
                        self._record_lineage(temps, tasks, results,
                                             lineage_label, task_bytes=blobs)
                    self._attribute_consumer_rpcs(tasks, results, temps)
                    return results
                except ObjectsLostError as e:
                    if e.partial is not None:
                        # keep this round's completed work; only the
                        # unfinished tasks resubmit after recovery
                        for i, r in zip(todo, e.partial):
                            if r is not None:
                                results[i] = r
                                _notify(i, r)
                    if attempt >= rounds or not e.lost_ids:
                        raise
                    lost = self._expand_lost(e.lost_ids, tasks, results,
                                             temps)
                    mapping = self._regenerate(sorted(lost), temps, _depth)
                    if mapping is None:
                        raise
                    patched = [T.patch_task_refs(t, mapping) for t in tasks]
                    if blobs is not None:
                        for i, (old, new) in enumerate(zip(tasks, patched)):
                            if new is not old:
                                blobs[i] = None
                    tasks = patched
                    delay = _backoff_delay(attempt + 1, self._retry_rng,
                                           base=0.1)
                    logger.warning(
                        "resubmitting %d/%d stage tasks after lineage "
                        "recovery of %d blobs (round %d, backoff %.2fs)",
                        sum(1 for r in results if r is None), len(tasks),
                        len(lost), attempt + 1, delay)
                    time.sleep(delay)
                    attempt += 1
        except Exception:
            # outputs completed in earlier rounds never reach the caller on a
            # raise: free them (the pool already freed its own sub-round's)
            _free_result_refs(results)
            raise
        finally:
            self._drop_stage_hints(hinted)

    def _attribute_consumer_rpcs(self, tasks: Sequence[T.Task],
                                 results: Sequence[Optional[Dict[str, Any]]],
                                 temps) -> None:
        """Fold reduce-task store-RPC counters into the ledger entry of the
        shuffle stage each task consumed (``Task.consumes_stage``). Tasks
        that themselves end in a SHUFFLE write are skipped — their counters
        already landed on the stage they PRODUCE via ``_record_stage`` (one
        task, one entry; a join reduce reads both sides but is attributed to
        the left label it was tagged with — its pipelined overlap stats
        follow the same convention, so a pipelined join's right-stream
        overlap folds into the join-left entry: per-stage splits are coarse
        for joins, sums across entries exact)."""
        if not isinstance(temps, _ActionTemps):
            return
        # a pipelined stage's ledger entry is recorded by ITS background
        # thread when the map stage returns; reduce tasks can complete (and
        # land here) a beat earlier — wait for the entry before attributing.
        # Keyed on the UNIQUE stream key, never the label (labels repeat
        # within one action — a.join(b).join(c) runs "join-left" twice and
        # a label lookup would hand a cascaded stage its OWN rec, which this
        # thread can never see done: self-deadlock until the timeout)
        cur_thread = threading.current_thread()
        for key in {getattr(t, "consumes_stream", None) for t in tasks}:
            rec = temps.stream_by_key.get(key) if key else None
            if rec is not None and rec.thread is not cur_thread:
                rec.done.wait(timeout=300.0)
        with self._report_lock:
            for task, r in zip(tasks, results):
                label = getattr(task, "consumes_stage", None)
                if label is None or r is None:
                    continue
                # a pipelined stage's entry is bound to its rec — the label
                # map would misroute stats when two same-label stages are
                # live concurrently (a later _record_stage overwrites the
                # shared stage_entries[label] slot)
                rec = temps.stream_by_key.get(
                    getattr(task, "consumes_stream", None) or "")
                entry = rec.entry if rec is not None \
                    and rec.entry is not None \
                    else temps.stage_entries.get(label)
                if entry is None:
                    continue
                # pipelined-shuffle overlap folds in regardless of the
                # task's own output mode (a downstream SHUFFLE map reading
                # a pipelined stage still overlapped THAT stage's tail)
                ov = float(r.get("stream_overlap_s", 0) or 0)
                if ov:
                    entry["overlap_s"] = entry.get("overlap_s", 0.0) + ov
                ts = r.get("stream_first_fetch_ts")
                if ts is not None and rec is not None:
                    rel = max(0.0, float(ts) - rec.start_ts)
                    cur = entry.get("first_reduce_fetch_s")
                    entry["first_reduce_fetch_s"] = \
                        rel if cur is None else min(cur, rel)
                if task.output == T.SHUFFLE:
                    # RPC/speculation counters already landed on the stage
                    # this task PRODUCES via _record_stage
                    continue
                entry["meta_rpcs"] += int(r.get("meta_rpcs", 0))
                entry["fetch_rpcs"] += int(r.get("fetch_rpcs", 0))
                # reduce-side speculation lands on the stage the task
                # consumed, same attribution as its store RPCs
                entry["speculated"] += int(r.get("_speculated", 0))
                entry["speculation_won"] += \
                    int(r.get("_speculation_won", 0))

    @staticmethod
    def _expand_lost(lost_ids: Sequence[str], tasks: Sequence[T.Task],
                     results: Sequence[Optional[Dict[str, Any]]],
                     temps: "_ActionTemps") -> set:
        """Widen a consumer-reported loss to everything one locations() probe
        says is equally gone, sharing the read path's loss criterion. A
        consumer reports only the FIRST missing blob it read, so without
        this a host death taking several producers' outputs recovers one
        producer per round until the rounds budget burns. Two signals:
        ledgered inputs of unfinished tasks absent from the store table
        (freed or already purged), and — because a dead payload host's table
        entries outlive it until purge_host runs — every ledgered candidate
        homed on a host that still "lists" a blob whose read just failed.
        Head-local losses stay blob-specific (a missing spill file says
        nothing about its neighbors). Best-effort: on probe failure the
        per-round discovery still converges, just more slowly."""
        lost = set(lost_ids)
        try:
            cand = {cid: ObjectRef(id=cid)
                    for i, r in enumerate(results) if r is None
                    for cid in T.task_input_ids(tasks[i])
                    if cid in temps.lineage}
            if not cand:
                return lost
            probe = list(cand.values()) + [
                ObjectRef(id=lid) for lid in lost if lid not in cand]
            locs = get_client().locations(probe)
            lost.update(c for c in cand if c not in locs)
            dead_hosts = {locs[lid] for lid in lost_ids
                          if lid in locs} - {HEAD_HOST}
            if dead_hosts:
                lost.update(c for c in cand if locs.get(c) in dead_hosts)
        except Exception:
            pass
        return lost

    def _regenerate(self, lost_ids: Sequence[str], temps: "_ActionTemps",
                    depth: int) -> Optional[Dict[str, ObjectRef]]:
        """Re-execute the producer task of every lost intermediate; return
        old-id → fresh-ref patches for ALL the producers' outputs (reruns are
        deterministic, so sibling buckets are identical — patching them too
        costs nothing and spares bookkeeping). None = unrecoverable (no
        lineage for a source blob, or the transitive depth budget burned)."""
        if depth >= _recovery_depth():
            logger.warning("lineage recovery depth %d exhausted", depth)
            return None
        groups: Dict[int, Tuple[_Producer, List[str]]] = {}
        for oid in set(lost_ids):
            prod = temps.lineage.get(oid)
            if prod is None:
                logger.warning("no lineage recorded for lost object %s; "
                               "cannot recover", oid)
                return None
            groups.setdefault(id(prod), (prod, []))[1].append(oid)
        # one batched rerun per producer LABEL (one loss usually takes a
        # whole stage's worth of producers — _expand_lost harvests them all,
        # and serial single-task stages would leave the pool idle for
        # N × single-task latency instead of ceil(N / pool))
        by_label: Dict[str, List[Tuple[_Producer, List[str]]]] = {}
        for prod, ids in groups.values():
            by_label.setdefault(prod.label, []).append((prod, ids))
        mapping: Dict[str, ObjectRef] = {}
        for label, plist in by_label.items():
            rerun = [cloudpickle.loads(p.task_bytes) for p, _ in plist]
            metrics.inc("recovery_rounds_total")
            metrics.inc("recovery_blobs_regenerated_total",
                        sum(len(ids) for _, ids in plist))
            metrics.record_event(
                "recovery_round", stage=label, producers=len(plist),
                lost=sum(len(ids) for _, ids in plist), depth=depth)
            with profiler.trace("recover:lineage", "etl", stage=label,
                                lost=sum(len(ids) for _, ids in plist),
                                producers=len(plist)):
                # nested losses (the producers' own inputs) recover through
                # the same machinery, one depth level down; the rerun also
                # re-ledgers its outputs (with any nested ref patches)
                res_list = self._run_stage(rerun, None, temps,
                                           lineage_label=label,
                                           _depth=depth + 1)
            for (prod, ids), res in zip(plist, res_list):
                # same extraction the ledger used, so outputs zip 1:1
                new_refs = _result_refs(res)
                temps.extend(new_refs)
                if len(new_refs) != len(prod.outputs):
                    logger.warning(
                        "regenerated producer emitted %d outputs, expected "
                        "%d; aborting recovery", len(new_refs),
                        len(prod.outputs))
                    return None
                sub = dict(zip(prod.outputs, new_refs))
                mapping.update(sub)
                temps.apply_patches(sub)
                # pipelined stages: a regenerated producer RE-SEALS under
                # its map_id with the next generation, so in-flight and
                # resubmitted streaming reducers read the fresh blob (the
                # stale range's ObjectLostError is what got us here)
                for old_id, new_ref in sub.items():
                    pub = temps.stream_pubs.pop(old_id, None)
                    if pub is None:
                        continue
                    srec, map_id = pub
                    temps.stream_pubs[new_ref.id] = (srec, map_id)
                    try:
                        index = res.get("bucket_index")
                        if not index:
                            # an index-less rerun result can never serve
                            # ranged readers: abort with the real cause
                            # instead of publishing an empty index every
                            # poll would trip over (same shape as the
                            # missing-consolidated_ref abort)
                            get_client().stream_abort(
                                srec.stage_key,
                                f"regenerated map {map_id} returned no "
                                "bucket index")
                        else:
                            srec.publish(map_id, new_ref, index)
                    except Exception:
                        logger.warning("re-seal of regenerated map %d "
                                       "(stage %r) failed", map_id,
                                       srec.label, exc_info=True)
                self._note_recovery(prod, len(ids), temps)
                # the rerun re-ledgered fresh _Producer objects for its
                # outputs; inherit the stage binding so a SECOND loss of a
                # regenerated blob still attributes to the original entry
                for ref in new_refs:
                    nprod = temps.lineage.get(ref.id)
                    if nprod is not None and nprod.entry is None:
                        nprod.entry = prod.entry
                logger.warning(
                    "lineage recovery: regenerated %d lost blob(s) (of %d "
                    "outputs) for stage %r", len(ids), len(prod.outputs),
                    label)
        return mapping

    # ---- public entry points ------------------------------------------------
    @contextlib.contextmanager
    def _action(self, label: str):
        """Every driver-initiated action runs under one ``etl:action`` root
        span — minting the ``trace_id`` all its stage/task/recovery spans
        (local and remote) inherit — and a :class:`StageError` surfacing
        from it triggers the flight-recorder harvest: every process's event
        ring lands in a ``blackbox-<label>.json`` postmortem bundle
        (doc/observability.md), so a chaos-failed action leaves an artifact
        instead of log archaeology. Harvest failures never mask the error."""
        with profiler.trace("etl:action", "driver", action=label):
            try:
                yield
            except StageError as e:
                metrics.record_event("action_failed", action=label,
                                     exc_type=type(e).__name__,
                                     error=str(e)[:500])
                try:
                    path = metrics.write_blackbox(label, e)
                    if path:
                        logger.warning("action %r failed; flight-recorder "
                                       "bundle written to %s", label, path)
                except Exception:  # noqa: BLE001 - never mask the failure
                    logger.warning("blackbox harvest for failed action %r "
                                   "itself failed", label, exc_info=True)
                raise

    def materialize(self, node: P.PlanNode, owner: Optional[str] = None
                    ) -> Tuple[List[ObjectRef], Optional[bytes], List[int]]:
        """Execute the plan; return per-partition (refs, schema bytes, row counts)."""
        temps = _ActionTemps()
        try:
            with self._action("materialize"):
                # the returned refs are the action's FINAL outputs: nothing
                # later in this action can lose them, so ledgering their
                # recipes would be pure serialization overhead on the
                # data-feed hot path
                return self._materialize_inner(self._optimized(node), owner,
                                               temps, lineage_label=None)
        finally:
            self._free(temps)

    def _materialize_inner(self, node: P.PlanNode, owner: Optional[str],
                           temps: List[ObjectRef],
                           lineage_label: Optional[str] = "materialize"):
        """``lineage_label`` defaults on: the internal callers (sort child,
        window input, coalesce) feed these refs to LATER stages of the same
        action, which is exactly when a lost blob needs the recipe."""
        tasks, preferred = self._compile(node, temps)
        tasks = [t.with_output(output=T.RETURN_REF, owner=owner or self.owner)
                 for t in tasks]
        results = self._run_stage(tasks, preferred, temps,
                                  lineage_label=lineage_label)
        refs = [r["ref"] for r in results]
        schema = results[0]["schema"] if results else None
        num_rows = [r["num_rows"] for r in results]
        return refs, schema, num_rows

    def collect(self, node: P.PlanNode) -> pa.Table:
        temps = _ActionTemps()
        try:
            with self._action("collect"):
                tasks, preferred = self._compile(self._optimized(node), temps)
                tasks = [t.with_output(output=T.COLLECT) for t in tasks]
                results = self._run_stage(tasks, preferred, temps)
                tables = [pa.ipc.open_stream(pa.py_buffer(r["ipc"])).read_all()
                          for r in results]
                out = pa.concat_tables(tables, promote_options="permissive")
                limit = _root_limit(node)
                return out.slice(0, limit) if limit is not None else out
        finally:
            self._free(temps)

    def count(self, node: P.PlanNode) -> int:
        temps = _ActionTemps()
        try:
            with self._action("count"):
                tasks, preferred = self._compile(self._optimized(node), temps)
                tasks = [t.with_output(output=T.ROWCOUNT) for t in tasks]
                results = self._run_stage(tasks, preferred, temps)
                total = sum(r["num_rows"] for r in results)
                limit = _root_limit(node)
                return min(total, limit) if limit is not None else total
        finally:
            self._free(temps)

    def cache(self, node: P.PlanNode, frame_id: str) -> P.CachedScan:
        """Materialize into executor block caches with lineage recipes.

        Parity: ``prepareRecoverableRDD`` = persist + count + pin + locations map
        (ObjectStoreWriter.scala:164-204). The returned ``CachedScan`` carries,
        per partition: the cache key, the executor that holds it, and the pickled
        recipe that can rebuild it anywhere. Shuffle intermediates feeding the
        cached plan are pinned (not freed) because the lineage recipes reference
        them — they are released with the frame (the GC-pin of
        ObjectStoreWriter.scala:175-177).
        """
        with self._action("cache"):
            return self._cache_inner(node, frame_id)

    def _cache_inner(self, node: P.PlanNode, frame_id: str) -> P.CachedScan:
        temps = _ActionTemps()
        try:
            tasks, preferred = self._compile(self._optimized(node), temps)
            cache_tasks, keys = [], []
            for i, t in enumerate(tasks):
                key = f"block_{frame_id}_{i}"
                keys.append(key)
                cache_tasks.append(t.with_output(output=T.CACHE, cache_key=key))
            results = self._run_stage(cache_tasks, preferred, temps)
            # recover recipes are serialized AFTER the stage so they carry
            # any ref patches in-stage lineage recovery applied — a recipe
            # pointing at a pre-recovery (dead) blob id would fail every
            # future cache miss. Streaming sources resolve to concrete
            # ranged reads first: the seal-stream ledger closes with this
            # action, and the cache stage's completion guarantees every map
            # has sealed (their blobs stay pinned with the frame)
            recover_blobs = [
                cloudpickle.dumps(T.patch_task_refs(
                    temps.resolve_streams(
                        t.with_output(output=T.RETURN_REF)),
                    temps.ref_patches))
                for t in tasks
            ]
        except BaseException:
            self._free(temps)
            # partitions that completed before the failure already stored
            # their tables in executor block caches, beyond the reach of the
            # store-only free above — drop them by prefix everywhere, or
            # every retried persist of a failing plan pins more partition
            # tables in unbounded executor RAM. A straggler abandoned past
            # the drain timeout can still cache AFTER this sweep: the
            # pool's _free_late_result drops that block when it lands
            for h in self.pool.executors:
                try:
                    h.drop_block_prefix(f"block_{frame_id}_")
                except Exception:
                    pass
            raise
        # the success path keeps temps pinned (recipes reference them), so
        # the usual _free won't run — the seal-stream ledgers must still
        # close with the action (recipes were resolved to concrete ranges
        # above; an unclosed stage would leak in the head ledger and a
        # drain-abandoned straggler would never get its close-abort)
        temps.close_streams()
        executors = [r["executor"] for r in results]
        schema = results[0]["schema"] if results else None
        # temps stay pinned: the lineage recipes reference them (plain list —
        # the per-action ledger has no meaning past this action)
        return P.CachedScan(frame_id=frame_id, cache_keys=keys,
                            executors=executors, recover_tasks=recover_blobs,
                            schema=schema, pinned_refs=list(temps))

    def random_shuffle_refs(self, refs: Sequence[ObjectRef],
                            schema_bytes: Optional[bytes],
                            seed: Optional[int],
                            owner: Optional[str] = None,
                            ) -> Tuple[List[ObjectRef], List[int]]:
        """Executor-side uniform shuffle of materialized blocks.

        Two stages over the store data plane — map: seeded random bucketing
        of each block (:func:`tasks.random_buckets`); reduce: concat each
        bucket + in-partition permutation (:class:`tasks.LocalShuffleStep`).
        The driver handles ONLY refs: no row ever crosses the driver process
        (the reference's shuffle is likewise distributed — ray.data
        random_shuffle at torch/estimator.py:335-338). Returns (refs, rows)
        per output block; intermediates are freed before returning.
        """
        with self._action("random-shuffle"):
            return self._random_shuffle_inner(refs, schema_bytes, seed, owner)

    def _random_shuffle_inner(self, refs, schema_bytes, seed, owner=None):
        temps = _ActionTemps()
        try:
            nb = max(1, len(refs))
            base = 0 if seed is None else int(seed)
            consolidate = _consolidate_enabled()
            map_tasks = [
                self._task(T.ArrowRefSource([r], schema=schema_bytes))
                .with_output(output=T.SHUFFLE, num_buckets=nb,
                             shuffle_seed=(base * 1_000_003 + i) & 0x7FFFFFFF,
                             shuffle_consolidate=consolidate,
                             owner=self.owner)
                for i, r in enumerate(refs)
            ]
            # random-shuffle is never AQE-re-planned: pipelines under AQE
            buckets, _ = self._dispatch_shuffle_stage(
                map_tasks, self._locality([[r] for r in refs]), nb,
                "random-shuffle", temps, aqe_capable=False,
                consolidate=consolidate)
            reduce_tasks = [
                self._bucket_task(bucket, schema_bytes,
                                  [T.LocalShuffleStep(
                                      (base * 9_176 + 77 + b) & 0x7FFFFFFF)],
                                  "random-shuffle")
                .with_output(output=T.RETURN_REF, owner=owner or self.owner)
                for b, bucket in enumerate(buckets)
            ]
            out = self._run_stage(reduce_tasks, self._locality(buckets), temps)
            return [r["ref"] for r in out], [r["num_rows"] for r in out]
        finally:
            self._free(temps)

    def num_partitions(self, node: P.PlanNode) -> int:
        temps = _ActionTemps()
        try:
            tasks, _ = self._compile(self._optimized(node), temps)
            return len(tasks)
        finally:
            self._free(temps)

    # ---- compilation --------------------------------------------------------
    def _compile(self, node: P.PlanNode, temps: List[ObjectRef]
                 ) -> Tuple[List[T.Task], List[Optional[str]]]:
        """Return (tasks, preferred-executor-per-task); shuffle intermediates
        created along the way are appended to ``temps`` (per-action list)."""
        if isinstance(node, P.RangeScan):
            per = math.ceil((node.stop - node.start) / max(node.step, 1)
                            / node.num_partitions)
            tasks = []
            for i in range(node.num_partitions):
                lo = node.start + i * per * node.step
                hi = min(node.start + (i + 1) * per * node.step, node.stop)
                tasks.append(self._task(T.RangeSource(lo, hi, node.step, node.column)))
            return tasks, [None] * len(tasks)

        if isinstance(node, P.CsvScan):
            return self._compile_csv(node)

        if isinstance(node, P.ParquetScan):
            return self._compile_parquet(node)

        if isinstance(node, P.InMemory):
            tasks = [self._task(T.ArrowRefSource([ref], schema=node.schema))
                     for ref in node.refs]
            return tasks, self._locality([[ref] for ref in node.refs])

        if isinstance(node, P.CachedScan):
            tasks, preferred = [], []
            for key, executor, recover in zip(
                    node.cache_keys, node.executors, node.recover_tasks):
                rec_task: T.Task = cloudpickle.loads(recover)
                tasks.append(self._task(T.CachedSource(key, rec_task)))
                preferred.append(executor)
            return tasks, preferred

        # ---- narrow unary: fuse into child's task chains ----
        narrow = {
            P.Project: lambda n: T.ProjectStep(n.columns),
            P.Filter: lambda n: T.FilterStep(n.predicate),
            P.DropNa: lambda n: T.DropNaStep(n.subset),
            P.Limit: lambda n: T.LimitStep(n.n),
            P.Rename: lambda n: T.RenameStep(n.mapping),
        }
        for cls, make in narrow.items():
            if isinstance(node, cls):
                tasks, preferred = self._compile(node.child, temps)
                step = make(node)
                return [t.with_output(steps=t.steps + [step]) for t in tasks], preferred

        if isinstance(node, P.Sample):
            tasks, preferred = self._compile(node.child, temps)
            out = [t.with_output(steps=t.steps + [
                T.SampleStep(node.fraction, node.seed, i)])
                for i, t in enumerate(tasks)]
            return out, preferred

        if isinstance(node, P.SplitSelect):
            tasks, preferred = self._compile(node.child, temps)
            out = [t.with_output(steps=t.steps + [
                T.SplitSelectStep(node.lo, node.hi, node.seed, i)])
                for i, t in enumerate(tasks)]
            return out, preferred

        # ---- wide: execute child, shuffle through the object store ----
        if isinstance(node, P.Repartition):
            return self._compile_repartition(node, temps)

        if isinstance(node, P.GroupAgg):
            return self._compile_groupagg(node, temps)

        if isinstance(node, P.Join):
            return self._compile_join(node, temps)

        if isinstance(node, P.Sort):
            return self._compile_sort(node, temps)

        if isinstance(node, P.Distinct):
            return self._compile_distinct(node, temps)

        if isinstance(node, P.WindowOp):
            return self._compile_window(node, temps)

        if isinstance(node, P.Union):
            all_tasks, all_pref = [], []
            for child in node.inputs:
                tasks, preferred = self._compile(child, temps)
                all_tasks.extend(tasks)
                all_pref.extend(preferred)
            return all_tasks, all_pref

        raise TypeError(f"unknown plan node {type(node).__name__}")

    # ---- leaves -------------------------------------------------------------
    def _task(self, source: T.Step, steps: Optional[List[T.Step]] = None) -> T.Task:
        return T.Task(task_id=f"t-{uuid.uuid4().hex[:10]}", source=source,
                      steps=steps or [])

    def _locality(self, ref_lists: Sequence[Sequence[Optional[ObjectRef]]]
                  ) -> List[Optional[str]]:
        """Preferred executor per ref-reading task: one on the machine whose
        RESIDENT bytes dominate the task's inputs — data-gravity weighted
        (doc/etl.md "Data-gravity scheduling"): bytes whose local copy
        sits in shared memory count at full weight; bytes whose copy is
        SPILLED to disk at ``RDT_LOCALITY_SPILLED_WEIGHT`` (the fault-in
        is paid wherever the task lands, so disk-local placement is a
        smaller win than shm-local but still beats remote); bytes a host
        would PULL over the network count at
        ``RDT_LOCALITY_REMOTE_WEIGHT`` — that crediting is
        ranking-neutral among byte-holders (each host's score is
        ``(1-r)*local + r*total``, monotone in its local bytes) but
        gives every live host a real score, so when the gravity host is
        draining or backpressured :meth:`ExecutorPool.pick_weighted`
        falls back to a ranked live host instead of returning no
        preference; 0 restores holder-only scoring, 1 is distance-blind
        (all hosts tie and rotate). Absent bytes weigh nothing. One bulk
        ``residency`` RPC (``locations`` when the
        store predates tiers — weighting then degrades to tier-blind); a
        no-op on single-machine pools so round-robin balance is
        untouched. The heaviest host that still has a dispatchable member
        wins (:meth:`ExecutorPool.pick_weighted`; equal weights rotate).
        Parity: preferred locations from block owner addresses
        (RayDatasetRDD.scala:48-56, RayDPExecutor.scala:271-287).

        A task's entry may hold plain refs, ``(ref, offset, size)`` range
        triples, or nested lists of either (a coalesced multi-range read
        fusing several buckets): EVERY range contributes its own byte
        weight, so a multi-range source is routed by the total bytes it
        reads across all its (ref, off, size) triples — not just wherever
        its first ref happens to live. A streaming reducer's
        :class:`_StreamBucket` expands to the ranges of the seals seen SO
        FAR — early reducers re-weight from partial knowledge instead of
        dispatching preference-free (no seals yet → genuinely no
        preference)."""
        if not self.pool.multi_host():
            return [None] * len(ref_lists)

        def _flat(items):
            for item in items:
                if isinstance(item, list):
                    yield from _flat(item)
                elif isinstance(item, _StreamBucket):
                    yield from item.parts_so_far()
                else:
                    yield item

        def _norm(item) -> Tuple[Optional[ObjectRef], int]:
            # items are refs OR (ref, offset, size) range triples — weight a
            # range by ITS size, not the whole consolidated blob's
            if isinstance(item, tuple):
                return item[0], max(int(item[2]), 1)
            if item is not None:
                return item, max(int(item.size or 0), 1)
            return None, 0

        try:
            seen: Dict[str, ObjectRef] = {}
            for refs in ref_lists:
                for item in _flat(refs):
                    r, _ = _norm(item)
                    if r is not None:
                        seen[r.id] = r
            client = get_client()
            fetch = getattr(client, "residency", None)
            if fetch is not None:
                locs = fetch(list(seen.values()))
            else:  # tier-blind store: every present byte counts as shm
                locs = client.locations(list(seen.values()))
        except Exception:
            return [None] * len(ref_lists)
        spilled_w = max(0.0,
                        float(knobs.get("RDT_LOCALITY_SPILLED_WEIGHT")))
        remote_w = min(1.0, max(0.0, float(
            knobs.get("RDT_LOCALITY_REMOTE_WEIGHT"))))
        pool_hosts = (set(self.pool.hosts_by_name.values())
                      if remote_w > 0 else set())
        preferred: List[Optional[str]] = []
        for refs in ref_lists:
            weight: Dict[str, float] = {}
            total = 0.0
            for item in _flat(refs):
                r, w = _norm(item)
                loc = locs.get(r.id) if r is not None else None
                if loc is None:
                    continue
                if isinstance(loc, (tuple, list)):
                    host, tier = loc[0], loc[1]
                else:
                    host, tier = loc, "shm"
                scaled = w * (spilled_w if tier == "spilled" else 1.0)
                if scaled > 0:
                    weight[host] = weight.get(host, 0.0) + scaled
                    total += scaled
            if remote_w > 0 and total > 0:
                # local bytes at full (tier-scaled) weight, the rest of the
                # task's bytes at the remote-pull discount: (1-r)*local +
                # r*total — holder ranking is preserved, non-holders gain a
                # ranked fallback score
                weight = {h: (1.0 - remote_w) * weight.get(h, 0.0)
                          + remote_w * total
                          for h in pool_hosts | set(weight)}
            preferred.append(self.pool.pick_weighted(weight))
        return preferred

    def _compile_csv(self, node: P.CsvScan):
        tasks = []
        headerless = bool((node.options or {}).get("column_names"))
        for path in node.paths:
            size = os.path.getsize(path)
            if headerless:
                header = b""  # first line is data (column names via options)
            else:
                with open(path, "rb") as f:
                    header = f.readline()
            body = size - len(header)
            nparts = node.num_partitions or max(
                1, min(self.shuffle_partitions, body // (8 << 20) + 1))
            per = math.ceil(body / nparts) if body > 0 else 1
            for i in range(nparts):
                start = len(header) + i * per
                end = min(len(header) + (i + 1) * per, size)
                if start >= size:
                    break
                tasks.append(self._task(T.CsvSliceSource(
                    path, start if i > 0 else 0, end, header, node.options)))
        return tasks, [None] * len(tasks)

    def _compile_parquet(self, node: P.ParquetScan):
        import pyarrow.parquet as pq
        tasks = []
        for path in node.paths:
            f = pq.ParquetFile(path)
            for rg in range(f.num_row_groups):
                tasks.append(self._task(T.ParquetSource(path, [rg], node.columns)))
            if f.num_row_groups == 0:
                tasks.append(self._task(T.ParquetSource(path, None, node.columns)))
        return tasks, [None] * len(tasks)

    # ---- pipelined (push-based) shuffle -------------------------------------
    def _stream_ok(self, temps, aqe_capable: bool,
                   consolidate: bool) -> bool:
        """Whether a shuffle stage may pipeline its reduce side (doc/etl.md
        "Pipelined shuffle"). Requires the consolidated per-bucket index and
        an action ledger; and the AQE interaction rule is **AQE wins**: a
        stage AQE may re-plan (groupagg/join/distinct/repartition —
        post-map broadcast, skew split, and coalescing all need the full
        map-size picture) runs in barrier mode whenever ``RDT_ETL_AQE`` is
        on, while never-re-planned stages (window, sort-range,
        random-shuffle) pipeline regardless."""
        return (_pipeline_enabled() and consolidate
                and isinstance(temps, _ActionTemps)
                and not (aqe_capable and O.aqe_enabled()))

    def _stream_shuffle_stage(self, tasks: List[T.Task],
                              preferred: Optional[Sequence[Optional[str]]],
                              num_buckets: int, label: str,
                              temps: "_ActionTemps") -> List[List[Any]]:
        """Launch a shuffle map stage WITHOUT a barrier: the stage runs on a
        background thread and this returns immediately with per-bucket
        :class:`_StreamBucket` placeholders, so the caller's reduce tasks
        compile and dispatch while the maps are still running. As each map's
        winning result lands, the driver ledgers its lineage and publishes
        the seal ``(map_id, ref, per-bucket index)`` to the store server's
        stream ledger — already-running reducers fetch + decode that portion
        immediately. A failed map stage aborts the stream (reducers fail
        fast, typed) ; the thread is joined and the ledger closed by the
        action's ``_free`` via :meth:`_ActionTemps.close_streams`."""
        client = get_client()
        stage_key = f"ss-{uuid.uuid4().hex[:12]}"
        rec = _StreamStageRec(stage_key, label, len(tasks))
        client.stream_begin(stage_key, len(tasks))
        temps.streams.append(rec)
        temps.stream_by_key[stage_key] = rec

        def _on_map_result(i: int, task: T.Task, tbytes: Optional[bytes],
                           r: Dict[str, Any]) -> None:
            cref = r.get("consolidated_ref")
            if cref is None:
                # never expected (streaming requires shuffle_consolidate on
                # every task): abort rather than hang the reducers
                client.stream_abort(stage_key,
                                    f"map {task.task_id} returned a "
                                    "non-consolidated result")
                return
            temps.append(cref)
            # incremental lineage: a reducer can lose this blob while the
            # map stage is still running — the recipe must already be
            # ledgered (the stage-end _record_lineage re-ledgers, harmless)
            prod = _Producer(tbytes if tbytes is not None
                             else cloudpickle.dumps(task), [cref.id], label)
            temps.lineage[cref.id] = prod
            temps.stream_pubs[cref.id] = (rec, i)
            try:
                rec.publish(i, cref, r["bucket_index"])
            except BaseException as e:  # noqa: BLE001 - reducers must learn
                # a seal that never reaches the ledger would hang every
                # reducer in an unbounded poll loop: abort the stream so
                # the stage fails typed instead of the action never
                # returning
                logger.warning("seal publish for map %d (stage %r) "
                               "failed: %s", i, label, e)
                try:
                    client.stream_abort(
                        stage_key, f"seal publish failed for map "
                        f"{task.task_id}: {type(e).__name__}: {e}")
                except Exception:
                    pass

        sstats: Dict[str, Any] = {}
        # the map stage runs on a background thread but belongs to the
        # calling action's trace — hand the context across the Thread gap
        ctx = profiler.capture()

        def _runner():
            try:
                with profiler.activate(ctx):
                    results = self._run_stage(tasks, preferred, temps,
                                              lineage_label=label,
                                              sched_stats=sstats,
                                              on_task_result=_on_map_result)
                    rec.results = results
                    rec.entry = self._record_stage(label, results,
                                                   num_buckets, temps,
                                                   sched_stats=sstats,
                                                   pipelined=True)
            except BaseException as e:  # noqa: BLE001 - reducers must learn
                rec.error = e
                try:
                    client.stream_abort(stage_key,
                                        f"{type(e).__name__}: {e}")
                except Exception:
                    pass
            finally:
                rec.done.set()

        rec.thread = threading.Thread(target=_runner, daemon=True,
                                      name=f"rdt-stream-map-{label}")
        rec.thread.start()
        return [[_StreamBucket(rec, b)] for b in range(num_buckets)]

    def _dispatch_shuffle_stage(self, tasks: List[T.Task],
                                preferred: Optional[Sequence[Optional[str]]],
                                num_buckets: int, label: str, temps,
                                aqe_capable: bool, consolidate: bool,
                                stats: Optional[Dict[str, Any]] = None,
                                ) -> Tuple[List[List[Any]], Optional[bytes]]:
        """Run a built shuffle map stage, streamed or barrier — the ONE
        place the mt- map-task-id convention, the :meth:`_stream_ok` gate,
        and the barrier fallback live (every shuffle flavor routes through
        here, so their semantics cannot diverge). Returns (buckets, schema);
        a streamed stage returns :class:`_StreamBucket` placeholders and
        ``None`` schema (streamed reads decode it from the blobs' IPC
        streams), and ``stats`` stays unfilled (only AQE — which forces
        barrier — consumes it)."""
        # shuffle MAP task ids are prefixed so a fault/chaos schedule can
        # pin the map side (`executor.run_task` key match=|mt-)
        tasks = [t.with_output(task_id=f"mt-{t.task_id}") for t in tasks]
        if tasks and self._stream_ok(temps, aqe_capable, consolidate):
            return self._stream_shuffle_stage(tasks, preferred, num_buckets,
                                              label, temps), None
        sstats: Dict[str, Any] = {}
        results = self._run_stage(tasks, preferred, temps,
                                  lineage_label=label, sched_stats=sstats)
        self._record_stage(label, results, num_buckets, temps,
                           sched_stats=sstats)
        schema = results[0]["schema"] if results else None
        if stats is not None:
            stats["bytes_shuffled"] = sum(int(r.get("shuffle_bytes", 0))
                                          for r in results)
        return self._gather_buckets(results, num_buckets, temps), schema

    # ---- wide operators -----------------------------------------------------
    def _shuffle_children(self, node: P.PlanNode, num_buckets: int,
                          keys: Optional[List[str]], temps: List[ObjectRef],
                          range_key=None, pre_steps: Optional[List[T.Step]] = None,
                          label: str = "shuffle",
                          stats: Optional[Dict[str, Any]] = None,
                          aqe_capable: bool = True,
                          ) -> Tuple[List[List[Any]], Optional[bytes]]:
        """Execute ``node`` with SHUFFLE output; transpose map×bucket → bucket×map.

        ``pre_steps`` run on each map task AFTER the narrow chain and BEFORE
        bucketing (the hook map-side partial aggregation uses); ``label`` names
        the stage in the engine's shuffle ledger. ``stats``, when given, is
        filled with the stage's measured ``bytes_shuffled`` — the number the
        AQE post-map broadcast rule re-plans on (AQE-capable stages never
        stream, so the two never coexist). When the stage pipelines
        (:meth:`_stream_ok`) the returned buckets are
        :class:`_StreamBucket` placeholders, the map stage keeps running on
        a background thread, and the schema comes back ``None`` — streamed
        reads decode it from the map blobs' IPC streams."""
        tasks, preferred = self._compile(node, temps)
        extra = list(pre_steps or [])
        consolidate = _consolidate_enabled()
        tasks = [t.with_output(steps=t.steps + extra,
                               shuffle_pre_steps=len(extra),
                               output=T.SHUFFLE, num_buckets=num_buckets,
                               shuffle_keys=keys, range_key=range_key,
                               shuffle_consolidate=consolidate,
                               owner=self.owner)
                 for t in tasks]
        return self._dispatch_shuffle_stage(tasks, preferred, num_buckets,
                                            label, temps, aqe_capable,
                                            consolidate, stats=stats)

    def _aqe_split_partial_agg(self, buckets: List[List[Any]],
                               schema: Optional[bytes], keys: List[str],
                               partials, label: str,
                               temps: List[ObjectRef]) -> List[List[Any]]:
        """Rule (b) for a decomposable aggregation: run an INLINE stage of
        split tasks over each skewed bucket's range groups — each merges its
        portion's partials into partials (:class:`tasks.
        GroupAggPartialMergeStep`) — then hand the final reduce task the
        split outputs instead of the raw ranges, so the ordinary
        ``GroupAggMergeStep`` finishes the bucket unchanged. The split
        outputs are ledgered under the map stage's label: a lost split blob
        regenerates through the same recovery path as any intermediate (its
        producer itself reads ledgered map blobs, so nested losses recover
        transitively)."""
        groups = self._aqe_split_groups(buckets)
        if groups is None:
            return buckets
        split_tasks, split_pref_parts, placed = [], [], []
        for b, portions in enumerate(groups):
            if len(portions) < 2:
                continue
            for portion in portions:
                split_tasks.append(
                    self._bucket_task(portion, schema,
                                      [T.GroupAggPartialMergeStep(
                                          list(keys), list(partials))],
                                      label)
                    .with_output(owner=self.owner))
                split_pref_parts.append(list(portion))
            placed.append((b, len(portions)))
        results = self._run_stage(split_tasks,
                                  self._locality(split_pref_parts), temps,
                                  lineage_label=label)
        out = [list(b) for b in buckets]
        it = iter(results)
        for b, n in placed:
            refs = [next(it)["ref"] for _ in range(n)]
            temps.extend(refs)
            out[b] = [(r, 0, int(r.size or 0)) for r in refs]
        self._note_aqe(temps, label, "aqe_split", len(placed),
                       tasks=len(split_tasks))
        return out

    def _compile_repartition(self, node: P.Repartition, temps: List[ObjectRef]):
        n = node.num_partitions
        if not node.shuffle:
            # coalesce: group existing partitions without moving rows by key
            refs, schema, _ = self._materialize_inner(node.child, None, temps)
            temps.extend(refs)
            groups = [[refs[i] for i in g]
                      for g in np.array_split(np.arange(len(refs)), n)
                      if len(g) > 0]
            tasks = [self._task(T.ArrowRefSource(group, schema=schema))
                     for group in groups]
            return tasks, self._locality(groups)
        buckets, schema = self._shuffle_children(node.child, n, keys=None,
                                                 temps=temps, label="repartition")
        buckets, _ = self._aqe_coalesce(buckets, "repartition", temps)
        # skewed buckets split into SEPARATE output partitions (repartition
        # makes no key promise, so the "merge" of split outputs is just the
        # action-level concat — no combiner stage, no extra data movement)
        groups = self._aqe_split_groups(buckets)
        if groups is not None:
            self._note_aqe(temps, "repartition", "aqe_split",
                           sum(1 for g in groups if len(g) > 1))
            buckets = [portion for g in groups for portion in g]
        tasks = [self._bucket_task(bucket, schema, None, "repartition")
                 for bucket in buckets]
        return tasks, self._locality(buckets)

    def _compile_groupagg(self, node: P.GroupAgg, temps: List[ObjectRef]):
        nb = self._num_buckets()
        decomposable = all(f in O.DECOMPOSABLE_AGGS for _, f, _ in node.aggs)
        if O.enabled() and decomposable:
            # two-phase aggregation: partials computed map-side BEFORE the
            # shuffle, so one row per (map task, key) crosses the store; the
            # reduce side merges partials (mean = sum-of-sums / sum-of-counts)
            partials, merges = T.decompose_aggs(node.aggs)
            buckets, schema = self._shuffle_children(
                node.child, nb, keys=node.keys, temps=temps,
                pre_steps=[T.GroupAggPartialStep(node.keys, partials)],
                label="groupagg-partial")
            buckets, _ = self._aqe_coalesce(buckets, "groupagg-partial",
                                            temps)
            buckets = self._aqe_split_partial_agg(buckets, schema, node.keys,
                                                  partials,
                                                  "groupagg-partial", temps)
            tasks = [self._bucket_task(bucket, schema,
                                       [T.GroupAggMergeStep(node.keys, merges)],
                                       "groupagg-partial")
                     for bucket in buckets]
            return tasks, self._locality(buckets)
        # single-phase fallback (non-decomposable aggs / optimizer off): a
        # key's rows must all reach ONE task, so skew splitting cannot apply
        # — only coalescing does
        buckets, schema = self._shuffle_children(node.child, nb, keys=node.keys,
                                                 temps=temps, label="groupagg")
        buckets, _ = self._aqe_coalesce(buckets, "groupagg", temps)
        tasks = [self._bucket_task(bucket, schema,
                                   [T.GroupAggStep(node.keys, node.aggs)],
                                   "groupagg")
                 for bucket in buckets]
        return tasks, self._locality(buckets)

    def _aqe_broadcast_pre(self, node: P.Join, temps, bmax: int):
        """Rule (a), pre-shuffle form: when a static estimate says one
        (semantically broadcastable) side fits under ``bmax``, materialize it
        and CONFIRM with measured bytes — if confirmed, neither side buckets:
        the big side's partitions stream against executor-local replicas of
        the small side (one ranged fetch per executor). A lying estimate
        degrades gracefully: the materialized refs shuffle as an in-memory
        side through the ordinary bucketed join. Returns compiled (tasks,
        preferred) or None when the rule doesn't apply."""
        cands = []
        rest = O.estimate_plan_bytes(node.right)
        if rest is not None and rest <= bmax \
                and node.how in T.BROADCAST_RIGHT_JOIN_TYPES:
            cands.append(("right", rest))
        lest = O.estimate_plan_bytes(node.left)
        if lest is not None and lest <= bmax \
                and node.how in T.BROADCAST_LEFT_JOIN_TYPES:
            cands.append(("left", lest))
        if not cands:
            return None
        side = min(cands, key=lambda c: c[1])[0]
        small = node.right if side == "right" else node.left
        big = node.left if side == "right" else node.right
        stasks, spref = self._compile(small, temps)
        if not stasks:
            return None  # degenerate 0-task side: keep the bucketed path
        stasks = [t.with_output(output=T.RETURN_REF, owner=self.owner)
                  for t in stasks]
        sstats: Dict[str, Any] = {}
        results = self._run_stage(stasks, spref, temps,
                                  lineage_label="join-broadcast",
                                  sched_stats=sstats)
        refs = [r["ref"] for r in results]
        temps.extend(refs)
        schema = results[0]["schema"] if results else None
        size = sum(int(getattr(r, "size", 0) or 0) for r in refs)

        def _fallback():
            # bucketed join reusing the materialization as an in-memory
            # side (its blobs are ledgered, so nothing is wasted or lost)
            mem = P.InMemory(refs, schema=schema)
            fb = P.Join(mem, node.right, node.keys, node.right_keys,
                        node.how) if side == "left" else \
                P.Join(node.left, mem, node.keys, node.right_keys, node.how)
            return self._compile_join(fb, temps, allow_broadcast=False)

        if size > bmax or schema is None:
            return _fallback()  # measured bytes overrule the estimate
        # the big side compiles only now that the broadcast is confirmed —
        # its own wide subtrees execute exactly once either way
        big_tasks, big_pref = self._compile(big, temps)
        if not big_tasks:
            return _fallback()
        # the broadcast side's movement, in the ledger: what crossed the
        # store once (ref.size = serialized payload), under its own label
        for r in results:
            r["shuffle_bytes"] = int(r["ref"].size or 0)
            r.setdefault("shuffle_bytes_in", int(r.get("nbytes", 0)))
        self._record_stage("join-broadcast", results, 0, temps,
                           sched_stats=sstats)
        self._note_aqe(temps, "join-broadcast", "aqe_broadcast", 1,
                       side=side, bytes=size)
        step = T.BroadcastJoinStep([(r, 0, int(r.size or 0)) for r in refs],
                                   list(node.keys), list(node.right_keys),
                                   node.how, broadcast_side=side,
                                   schema=schema)
        tasks = [t.with_output(steps=t.steps + [step],
                               consumes_stage="join-broadcast")
                 for t in big_tasks]
        return tasks, big_pref

    def _compile_join(self, node: P.Join, temps: List[ObjectRef],
                      allow_broadcast: bool = True):
        nb = self._num_buckets()
        bmax = O.aqe_broadcast_max() if O.aqe_enabled() else 0
        if bmax > 0 and allow_broadcast:
            out = self._aqe_broadcast_pre(node, temps, bmax)
            if out is not None:
                return out
        lstats: Dict[str, Any] = {}
        left_buckets, lschema = self._shuffle_children(node.left, nb, node.keys,
                                                       temps, label="join-left",
                                                       stats=lstats)
        # rule (a), post-map form: the left map stage's measured bytes reveal
        # a small side no estimate could see (aggregated/joined subtrees).
        # Converting HERE — before the right side buckets — is what saves the
        # big side's shuffle: right partitions stream against replicas built
        # from the left's already-written map blobs (every bucket's range).
        if allow_broadcast and bmax > 0 and lschema is not None \
                and lstats.get("bytes_shuffled", 0) <= bmax \
                and node.how in T.BROADCAST_LEFT_JOIN_TYPES:
            right_tasks, right_pref = self._compile(node.right, temps)
            if right_tasks:
                parts = [p for lb in left_buckets
                         for p in self._as_parts(lb)]
                self._note_aqe(temps, "join-left", "aqe_broadcast", 1,
                               side="left",
                               bytes=lstats.get("bytes_shuffled", 0))
                step = T.BroadcastJoinStep(
                    parts, list(node.keys), list(node.right_keys), node.how,
                    broadcast_side="left", schema=lschema)
                tasks = [t.with_output(steps=t.steps + [step],
                                       consumes_stage="join-left")
                         for t in right_tasks]
                return tasks, right_pref
        right_buckets, rschema = self._shuffle_children(node.right, nb,
                                                        node.right_keys, temps,
                                                        label="join-right")
        left_buckets, right_buckets = self._aqe_coalesce(
            left_buckets, "join-left", temps, paired=right_buckets)
        # rule (b) on the probe side: a skewed left bucket's ranges split
        # across k join tasks, each probing the SAME right bucket — an inner/
        # semi/outer-left row lands in exactly one split, so the concat of
        # split outputs (the action-level gather) is the bucket's join. The
        # gate is the same partition-safety condition as broadcasting the
        # right side: any join type that emits RIGHT-side rows on their own
        # (right/full outer, right semi/anti) would emit them once per
        # split, because every split probes the whole right bucket
        split_groups = self._aqe_split_groups(left_buckets) \
            if node.how in T.BROADCAST_RIGHT_JOIN_TYPES else None
        tasks, pref_parts = [], []
        for b, (lb, rb) in enumerate(zip(left_buckets, right_buckets)):
            stream_rb = next((x for x in rb if isinstance(x, _StreamBucket)),
                             None)
            if stream_rb is not None:
                # pipelined right side: the build table accumulates from
                # seal notifications while BOTH map stages still run
                join_step = T.HashJoinStep([], node.keys, node.right_keys,
                                           node.how, right_schema=rschema,
                                           right_stream=stream_rb.source(
                                               rschema))
            elif any(isinstance(x, tuple) for x in rb):
                join_step = T.HashJoinStep([], node.keys, node.right_keys,
                                           node.how, right_schema=rschema,
                                           right_parts=self._as_parts(rb))
            else:
                join_step = T.HashJoinStep(list(rb), node.keys,
                                           node.right_keys, node.how,
                                           right_schema=rschema)
            portions = split_groups[b] if split_groups is not None else [lb]
            for portion in portions:
                tasks.append(self._bucket_task(portion, lschema, [join_step],
                                               "join-left"))
                # a join task reads BOTH sides: weight locality over them
                pref_parts.append(list(portion) + list(rb))
        if split_groups is not None:
            self._note_aqe(temps, "join-left", "aqe_split",
                           sum(1 for g in split_groups if len(g) > 1))
        return tasks, self._locality(pref_parts)

    def _compile_sort(self, node: P.Sort, temps: List[ObjectRef]):
        """Range-partitioned sort on the COMPOSITE key: materialize the child
        ONCE, sample boundary key-tuples from EVERY block on the executors
        (any orderable type — no numeric cast), range-shuffle those refs by
        lexicographic comparison, locally sort each range. Composite
        boundaries keep the partitioning balanced even when the first key has
        few distinct values (per-key boundaries would collapse there)."""
        keys = node.keys
        key_names = [k for k, _ in keys]
        refs, schema, num_rows = self._materialize_inner(node.child, None, temps)
        temps.extend(refs)

        # boundary sample: a bounded uniform sample over ALL blocks, taken by
        # the executors — sampling only the first blocks skews the range
        # boundaries on sorted or clustered input. Only the key columns
        # travel back to the driver.
        nb = self._num_buckets()
        total = sum(num_rows)
        target = max(1000, 100 * nb)
        frac = min(1.0, target / total) if total else 0.0
        sample_tasks = [
            self._task(T.ArrowRefSource([ref], schema=schema),
                       [T.SampleStep(frac, seed=0, partition_index=i),
                        T.ProjectStep([(k, _col(k)) for k in key_names])]
                       ).with_output(output=T.COLLECT)
            for i, (ref, n) in enumerate(zip(refs, num_rows)) if n > 0
        ]
        sampled = []
        if sample_tasks:
            for r in self._run_stage(sample_tasks, None, temps):
                tbl = pa.ipc.open_stream(pa.py_buffer(r["ipc"])).read_all()
                if tbl.num_rows:
                    sampled.append(tbl)
        boundaries: List[Tuple] = []
        if sampled:
            sample = pa.concat_tables(sampled, promote_options="permissive")
            # rows with a null or NaN key need no boundary: both always sort
            # at the extreme (and either as a boundary value would poison
            # every comparison — NaN > x and NaN == x are both false)
            for k in key_names:
                column = sample.column(k)
                sample = sample.filter(pc.is_valid(column))
                column = sample.column(k)
                if pa.types.is_floating(column.type) and sample.num_rows:
                    sample = sample.filter(pc.invert(pc.is_nan(column)))
            if sample.num_rows:
                sample = sample.sort_by(keys)
                qpos = [int(q * (sample.num_rows - 1))
                        for q in np.linspace(0, 1, nb + 1)[1:-1]]
                cols = {k: sample.column(k) for k in key_names}
                for p in qpos:
                    tup = tuple(cols[k][p].as_py() for k in key_names)
                    if not boundaries or tup != boundaries[-1]:
                        boundaries.append(tup)

        consolidate = _consolidate_enabled()
        shuffle_tasks = [
            self._task(T.ArrowRefSource([ref], schema=schema)).with_output(
                output=T.SHUFFLE, num_buckets=len(boundaries) + 1,
                range_key=(list(keys), boundaries),
                shuffle_consolidate=consolidate,
                owner=self.owner)
            for ref in refs
        ]
        # sort-range is never AQE-re-planned: it pipelines under AQE too
        buckets, _ = self._dispatch_shuffle_stage(
            shuffle_tasks, None, len(boundaries) + 1, "sort-range", temps,
            aqe_capable=False, consolidate=consolidate)
        # buckets come out in global sort order for any direction mix (the
        # composite comparison honors per-key direction; nulls sort last)
        tasks = [self._bucket_task(bucket, schema,
                                   [T.LocalSortStep(node.keys)], "sort-range")
                 for bucket in buckets]
        return tasks, self._locality(buckets)

    def _compile_distinct(self, node: P.Distinct, temps: List[ObjectRef]):
        """distinct / dropDuplicates: hash-shuffle on the key columns (the
        ``["*"]`` sentinel = full row, resolved executor-side), then local
        first-per-key dedupe — equal keys share a bucket, so local dedupe is
        globally exact."""
        nb = self._num_buckets()
        keys = list(node.subset) if node.subset else ["*"]
        buckets, schema = self._shuffle_children(node.child, nb, keys=keys,
                                                 temps=temps, label="distinct")
        # equal keys share a bucket, and that stays true under bucket UNION:
        # tiny-partition coalescing keeps local dedupe globally exact
        buckets, _ = self._aqe_coalesce(buckets, "distinct", temps)
        tasks = [self._bucket_task(bucket, schema,
                                   [T.DistinctStep(node.subset)], "distinct")
                 for bucket in buckets]
        return tasks, self._locality(buckets)

    def _compile_window(self, node: P.WindowOp, temps: List[ObjectRef]):
        """Window function: equal partition keys share a bucket (hash
        shuffle), so per-bucket sorted evaluation is globally exact. Without
        partition keys everything collapses to one task (Spark's "No
        Partition Defined" single-partition path).

        Adjacent WindowOps over the SAME partition keys collapse into one
        shuffle feeding a chain of WindowSteps (innermost first) — Spark
        likewise evaluates same-spec window functions in a single exchange;
        the doc example chains three columns over one spec and must not pay
        three shuffles of the whole dataset."""
        def _step(w: P.WindowOp) -> T.WindowStep:
            return T.WindowStep(list(w.partition_keys), list(w.order_keys),
                                w.out_name, w.fn, w.arg_col,
                                w.offset, w.default)

        steps = [_step(node)]
        child = node.child
        while (isinstance(child, P.WindowOp)
               and list(child.partition_keys) == list(node.partition_keys)):
            steps.append(_step(child))
            child = child.child
        steps.reverse()  # innermost (first-defined) column computes first

        if node.partition_keys:
            nb = self._num_buckets()
            # window is never AQE-re-planned: it pipelines under AQE too
            buckets, schema = self._shuffle_children(
                child, nb, keys=list(node.partition_keys), temps=temps,
                label="window", aqe_capable=False)
            tasks = [self._bucket_task(bucket, schema, list(steps), "window")
                     for bucket in buckets]
            return tasks, self._locality(buckets)
        refs, schema, _ = self._materialize_inner(child, None, temps)
        temps.extend(refs)
        tasks = [self._task(T.ArrowRefSource(list(refs), schema=schema),
                            list(steps))]
        return tasks, self._locality([list(refs)])

    # ---- driver-merged summaries -------------------------------------------
    def describe(self, node: P.PlanNode, cols: List[str]) -> Dict[str, Dict]:
        """count/mean/stddev/min/max per column: executors reduce each
        partition to one row of moment partials (DescribeStep); the driver
        merges K tiny rows, never the data. Sample stddev (ddof=1), matching
        Spark's ``describe``."""
        temps = _ActionTemps()
        try:
            # describe reads only `cols`: expose that to the optimizer by
            # narrowing the plan root, so scans and shuffles below prune too
            narrowed = (P.Project(node, [(c, _col(c)) for c in cols])
                        if O.enabled() else node)
            tasks, preferred = self._compile(self._optimized(narrowed), temps)
            tasks = [t.with_output(steps=t.steps + [T.DescribeStep(cols)],
                                   output=T.COLLECT)
                     for t in tasks]
            results = self._run_stage(tasks, preferred, temps)
        finally:
            self._free(temps)
        agg = {c: {"count": 0, "sum": 0.0, "sumsq": 0.0,
                   "min": None, "max": None} for c in cols}
        for r in results:
            tbl = pa.ipc.open_stream(pa.py_buffer(r["ipc"])).read_all()
            row = {name: tbl.column(name)[0].as_py()
                   for name in tbl.column_names}
            for c in cols:
                a = agg[c]
                a["count"] += int(row[f"{c}:count"])
                a["sum"] += float(row[f"{c}:sum"])
                a["sumsq"] += float(row[f"{c}:sumsq"])
                for fn, key in ((min, "min"), (max, "max")):
                    v = row[f"{c}:{key}"]
                    if v is not None:
                        a[key] = v if a[key] is None else fn(a[key], v)
        out: Dict[str, Dict] = {}
        for c, a in agg.items():
            n = a["count"]
            mean = a["sum"] / n if n else None
            if n > 1:
                var = max(0.0, (a["sumsq"] - a["sum"] ** 2 / n) / (n - 1))
                std = math.sqrt(var)
            else:
                std = None
            out[c] = {"count": n, "mean": mean, "stddev": std,
                      "min": a["min"], "max": a["max"]}
        return out
