"""The ETL executor actor: computes partitions, serves cached blocks.

Parity: ``RayDPExecutor`` — a worker hosted as a runtime actor that computes
partitions and doubles as the data-plane server for cached Arrow blocks
(RayDPExecutor.scala:103-249 serves Spark tasks; 271-355 serves
``getBlockLocations``/``getRDDPartition`` with recache-on-miss). Restart behavior:
a revived executor re-registers with the master under a fresh executor id and the
master keeps the old→new mapping (RayDPExecutor.scala:82-101,
RayAppMaster.scala:192-209); our executor does the same through
``current_actor_context().was_restarted``.
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Any, Dict, List, Optional

import cloudpickle
import pyarrow as pa

from raydp_tpu import faults, knobs
from raydp_tpu.etl import tasks as T
from raydp_tpu.log import get_logger
from raydp_tpu.runtime.actor import current_actor_context
from raydp_tpu.runtime.object_store import get_client

logger = get_logger("etl.executor")


class BlockCache:
    """In-memory named Arrow block cache (the BlockManager analogue)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._blocks: Dict[str, pa.Table] = {}  # guarded-by: _lock
        #: per-put generation stamp — a drop conditioned on a stamp only
        #: removes the exact entry its caller saw, so a drain-abandoned
        #: straggler's deferred cleanup can't delete the live block a
        #: recovery resubmit of the same task cached under the same key
        self._stamps: Dict[str, Optional[str]] = {}  # guarded-by: _lock

    def get(self, key: str) -> Optional[pa.Table]:
        with self._lock:
            return self._blocks.get(key)

    def put(self, key: str, table: pa.Table,
            stamp: Optional[str] = None) -> None:
        with self._lock:
            self._blocks[key] = table
            self._stamps[key] = stamp

    def put_once(self, key: str, table: pa.Table,
                 stamp: Optional[str] = None) -> Optional[str]:
        """Idempotent cache-put for duplicate task attempts (speculative
        backups, recovery resubmits racing a drain-abandoned straggler): if
        the key is already cached, keep the existing entry and return ITS
        stamp — tasks are deterministic recipes, so two attempts' tables are
        byte-identical, and sharing one entry + stamp lets the driver's
        loser drain recognize "the loser's block IS the winner's block" and
        skip the drop. Worst case (the first writer's deferred drop fires
        later) the block vanishes and the next read rebuilds it from its
        lineage recipe — never wrong data, never a pinned stale table."""
        with self._lock:
            if key in self._blocks:
                return self._stamps.get(key)
            self._blocks[key] = table
            self._stamps[key] = stamp
            return stamp

    def drop(self, keys: List[str], if_stamp: Optional[str] = None) -> int:
        with self._lock:
            n = 0
            for k in keys:
                if if_stamp is not None and self._stamps.get(k) != if_stamp:
                    continue
                if self._blocks.pop(k, None) is not None:
                    self._stamps.pop(k, None)
                    n += 1
            return n

    def drop_prefix(self, prefix: str) -> int:
        with self._lock:
            victims = [k for k in self._blocks if k.startswith(prefix)]
            for k in victims:
                del self._blocks[k]
                self._stamps.pop(k, None)
            return len(victims)

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._blocks)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(t.nbytes for t in self._blocks.values())


_block_cache: Optional[BlockCache] = None


def current_block_cache() -> BlockCache:
    """The block cache of the executor actor this code is running in."""
    if _block_cache is None:
        raise RuntimeError("no block cache: not inside an ETL executor actor")
    return _block_cache


class BroadcastCache:
    """Bounded process-local cache of broadcast-join build tables.

    The AQE broadcast rule replicates a small join side to every executor;
    this cache is the executor half of that replication — the FIRST
    ``BroadcastJoinStep`` on an executor pays the batched ranged fetch, and
    every sibling partition probes the already-built table. Keys embed the
    exact (blob id, offset, size) ranges, so a lineage-regenerated broadcast
    side (fresh blob ids) misses and refetches instead of probing stale
    bytes. LRU-bounded: a long session running many different joins holds at
    most ``max_entries`` small-side tables in executor RAM."""

    def __init__(self, max_entries: int = 4):
        self._lock = threading.Lock()
        self._max = max_entries
        # guarded-by: _lock; insertion-ordered (LRU via re-insert)
        self._tables: "dict" = {}

    def get_or_load(self, key, loader):
        with self._lock:
            hit = self._tables.pop(key, None)
            if hit is not None:
                self._tables[key] = hit  # re-insert: most recently used
                return hit
        # load OUTSIDE the lock: a slow fetch must not serialize sibling
        # tasks probing other (cached) broadcasts; a duplicate concurrent
        # load of the same key is benign (deterministic bytes, last wins)
        table = loader()
        with self._lock:
            self._tables[key] = table
            while len(self._tables) > self._max:
                self._tables.pop(next(iter(self._tables)))
        return table

    def clear(self) -> None:
        with self._lock:
            self._tables.clear()


_broadcast_cache = BroadcastCache()


def broadcast_cache() -> BroadcastCache:
    """The process-local broadcast-side table cache (executors; also used
    in-process by unit tests running steps directly)."""
    return _broadcast_cache


class EtlExecutor:
    """Actor class. One instance per executor process."""

    def __init__(self, master_name: Optional[str] = None):
        global _block_cache
        self.cache = BlockCache()
        _block_cache = self.cache
        self.executor_id: Optional[str] = None
        ctx = current_actor_context()
        self._actor_name = ctx.name if ctx else f"local-{uuid.uuid4().hex[:6]}"
        # register with the master; a restarted actor asks for a fresh executor id
        # (parity: RequestAddPendingRestartedExecutor, RayAppMaster.scala:192-209)
        if master_name and ctx is not None:
            from raydp_tpu.runtime.head import ENV_HEAD  # noqa: F401 (doc pointer)
            from raydp_tpu.runtime.rpc import RpcClient
            master_id = ctx.head.call("get_named_actor", master_name)
            if master_id is not None:
                address = ctx.head.call("get_actor_address", master_id)
                if address is not None:
                    master = RpcClient(tuple(address))
                    self.executor_id = master.call(
                        "register_executor", self._actor_name, ctx.was_restarted)
                    master.close()

    # -- control ---------------------------------------------------------------
    def ping(self) -> str:
        return "pong"

    def crash(self) -> None:
        """Fault injection: die abruptly (tests' node-kill analogue). The
        declarative twin is an ``executor.run_task:crash`` rule in
        ``RDT_FAULTS`` (see raydp_tpu/faults.py)."""
        faults.crash_process()

    def get_executor_id(self) -> Optional[str]:
        return self.executor_id

    def spawn_info(self) -> Dict[str, Any]:
        """Spawn provenance: ``warm_forked`` is True when this process was
        forked from the pre-imported warm-start prototype (the warm plane
        injects RDT_WARM_FORKED into the child env) rather than cold-spawned
        — the gravity bench's readiness audit reads this to prove the warm
        path actually served the scale-up."""
        return {"executor": self._actor_name, "pid": os.getpid(),
                "warm_forked": bool(knobs.get("RDT_WARM_FORKED"))}

    # -- compute ---------------------------------------------------------------
    def run_task(self, task_bytes: bytes):
        """Execute one task; the return shape depends on the task's output
        mode. Tasks with a STREAMING source (pipelined-shuffle reducers, and
        downstream map tasks reading a pipelined stage) run on a dedicated
        daemon thread behind a :class:`~raydp_tpu.runtime.rpc.DeferredReply`:
        they spend most of their life waiting on seal notifications and
        eagerly fetching/decoding arriving portions, and parking one of the
        bounded RPC dispatcher threads on that wait could starve — or, with
        every dispatcher parked, deadlock — the very map tasks being waited
        on. One thread per streaming task (no pool, so no queue to deadlock
        in); the count is bounded by the driver's per-executor in-flight
        caps."""
        from concurrent.futures import Future

        from raydp_tpu import profiler
        from raydp_tpu.runtime.rpc import DeferredReply

        task: T.Task = cloudpickle.loads(task_bytes)
        if T.stream_sources_of(task):
            fut: Future = Future()
            # the dispatcher thread holds the caller's trace context (the
            # RPC layer installed it); a plain Thread would lose it — hand
            # it across explicitly so the task's spans keep their driver
            # stage as parent
            ctx = profiler.capture()

            def _run():
                try:
                    with profiler.activate(ctx):
                        fut.set_result(self._run_task_obj(task))
                except BaseException as e:  # noqa: BLE001 - serialize any
                    fut.set_exception(e)

            threading.Thread(target=_run, daemon=True,
                             name=f"rdt-stream-{task.task_id}").start()
            return DeferredReply(fut)
        return self._run_task_obj(task)

    def _run_task_obj(self, task: T.Task) -> Dict[str, Any]:
        from raydp_tpu import profiler

        # the fault key carries the executor name so a chaos schedule can
        # target ONE executor (`match=<executor name>|` = a seeded straggler
        # or crashy node) as well as one task (`match=<task id>`; shuffle map
        # tasks carry an `mt-` id prefix, so `match=|mt-` pins the map side)
        rule = faults.check("executor.run_task",
                            key=f"{self._actor_name}|{task.task_id}")
        if rule is not None:
            faults.apply(rule, "executor.run_task")
        client = get_client()
        # per-task store control-plane deltas for the engine's shuffle ledger.
        # Concurrent tasks share the process counters, so an op can land in
        # every overlapping task's window: the per-stage sums are an upper
        # bound under concurrency, good for relative comparisons — the exact
        # session-wide numbers live in ObjectStoreServer.op_counts()
        rpc0 = client.rpc_counters()

        def _with_rpcs(result: Dict[str, Any]) -> Dict[str, Any]:
            rpc1 = client.rpc_counters()
            result["meta_rpcs"] = rpc1["meta"] - rpc0["meta"]
            result["fetch_rpcs"] = rpc1["fetch"] - rpc0["fetch"]
            # streamed reads leave overlap/first-fetch stats on their
            # sources; the driver folds them into the CONSUMED stage's entry
            result.update(T.collect_stream_stats(task))
            return result

        pre = (int(getattr(task, "shuffle_pre_steps", 0) or 0)
               if task.output == T.SHUFFLE else 0)
        rows_in = bytes_in = None
        with profiler.trace(f"task:{type(task.source).__name__}", "etl",
                            task_id=task.task_id):
            if pre:
                # run the narrow chain, measure what ENTERS the shuffle
                # stage, then apply the shuffle-side steps (partial agg)
                trimmed = task.with_output(steps=task.steps[:-pre])
                table = T.run_task_body(trimmed)
                rows_in, bytes_in = table.num_rows, table.nbytes
                with profiler.trace("shuffle:map-partial", "etl",
                                    task_id=task.task_id, rows_in=rows_in,
                                    bytes_in=bytes_in):
                    for step in task.steps[-pre:]:
                        table = step.run(table)
            else:
                table = T.run_task_body(task)
        owner = task.owner

        if task.output == T.ROWCOUNT:
            return _with_rpcs({"num_rows": table.num_rows})

        if task.output == T.COLLECT:
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, table.schema) as w:
                w.write_table(table)
            return _with_rpcs({"ipc": sink.getvalue().to_pybytes(),
                               "num_rows": table.num_rows})

        if task.output == T.CACHE:
            assert task.cache_key is not None
            # put_once: a speculative duplicate of this task may have cached
            # the key already — both attempts then report the SAME stamp, so
            # the driver's loser drain knows the entries coincide
            stamp = self.cache.put_once(task.cache_key, table,
                                        uuid.uuid4().hex)
            return _with_rpcs({
                "num_rows": table.num_rows,
                "nbytes": table.nbytes,
                "cache_key": task.cache_key,
                "cache_stamp": stamp,
                "executor": self._actor_name,
                "schema": table.schema.serialize().to_pybytes(),
            })

        if task.output == T.SHUFFLE:
            with profiler.trace("shuffle:bucket", "etl",
                                task_id=task.task_id,
                                rows_in=table.num_rows):
                if task.range_key is not None:
                    key, boundaries, *rest = task.range_key
                    if isinstance(key, str):  # legacy single-key format
                        buckets = T.range_buckets(
                            table, key, boundaries,
                            nulls_high=bool(rest and rest[0]))
                    else:  # composite: key = [(name, order), ...]
                        buckets = T.range_buckets_multi(table, key, boundaries)
                elif task.shuffle_keys:
                    buckets = T.hash_buckets(table, task.shuffle_keys,
                                             task.num_buckets)
                elif task.shuffle_seed is not None:
                    buckets = T.random_buckets(table, task.num_buckets,
                                               task.shuffle_seed)
                else:
                    start = T.hash_bytes(task.task_id) % max(task.num_buckets, 1)
                    buckets = T.round_robin_buckets(table, task.num_buckets,
                                                    start)
            consolidated_index = None
            if getattr(task, "shuffle_consolidate", False):
                # consolidated map output: every bucket serialized
                # back-to-back as independent Arrow IPC streams into ONE blob
                # (a single arena allocation), sealed with a single RPC; the
                # (offset, size, rows) index lets each reduce task read only
                # its bucket's byte range (tasks.RangeRefSource)
                sink = pa.BufferOutputStream()
                consolidated_index = []
                for b in buckets:
                    start = sink.tell()
                    with pa.ipc.new_stream(sink, b.schema) as w:
                        w.write_table(b)
                    consolidated_index.append(
                        (int(start), int(sink.tell() - start), b.num_rows))
                ref = client.put_raw(memoryview(sink.getvalue()),
                                     owner=owner)
                refs = [ref]
            else:
                refs = [client.put_arrow(b, owner=owner) for b in buckets]
            rule = faults.check("shuffle.write", key=task.task_id)
            if rule is not None:
                if rule.action == "drop" and refs:
                    # the blob is written, its ref handed to the driver — and
                    # the payload silently dies before the reduce stage reads
                    # it (the store-host-died model the lineage ledger
                    # exists for). On the consolidated path there is exactly
                    # ONE blob per map task — bucket= wraps onto it, so the
                    # drop takes every bucket at once and recovery must
                    # rebuild the whole consolidated output
                    victim = refs[rule.bucket % len(refs)]
                    try:
                        client.free([victim])
                    except Exception:
                        pass
                    logger.warning("fault plane dropped shuffle bucket %s "
                                   "of %s", victim.id, task.task_id)
                else:
                    # a fired rule must never be swallowed (its once-sentinel
                    # is already claimed): generic actions apply here too. An
                    # injected raise fails the task AFTER its buckets hit the
                    # store — free them first, or the retry's fresh copies
                    # leave these orphaned until session shutdown (crash is
                    # deliberately not cleaned up: an abruptly dead process
                    # leaves its writes behind, which is the point)
                    if rule.action == "raise" and refs:
                        try:
                            client.free(refs)
                        except Exception:
                            pass
                    faults.apply(rule, "shuffle.write")
            # ref.size is the serialized payload written to the store — the
            # honest bytes-moved number (bucket tables are zero-copy slices,
            # whose nbytes would overcount shared buffers)
            shuffle_bytes = sum(int(getattr(r, "size", 0) or 0) for r in refs)
            with profiler.trace("shuffle:write", "etl", task_id=task.task_id,
                                rows_out=table.num_rows,
                                bytes_out=shuffle_bytes,
                                consolidated=consolidated_index is not None):
                pass
            result = {
                "num_rows": table.num_rows,
                "shuffle_bytes": shuffle_bytes,
                # pre-shuffle-stage size (differs from num_rows/bytes out
                # when map-side partial aggregation ran; bytes_in is the
                # in-memory table estimate, bytes out are serialized sizes)
                "shuffle_rows_in": rows_in if rows_in is not None
                else table.num_rows,
                "shuffle_bytes_in": bytes_in if bytes_in is not None
                else table.nbytes,
                "schema": table.schema.serialize().to_pybytes(),
            }
            if consolidated_index is not None:
                result["consolidated_ref"] = refs[0]
                result["bucket_index"] = consolidated_index
            else:
                result["bucket_refs"] = refs
            return _with_rpcs(result)

        # default: RETURN_REF
        ref = client.put_arrow(table, owner=owner)
        return _with_rpcs({
            "ref": ref,
            "num_rows": table.num_rows,
            "nbytes": table.nbytes,
            "schema": table.schema.serialize().to_pybytes(),
        })

    # -- serving replicas (raydp_tpu/serve/replica.py) -------------------------
    def serve_load(self, replica_id: str, export_dir: str) -> Dict[str, Any]:
        """(Re)load a serving replica in this process from an exported
        bundle; idempotent per (id, dir). A restarted executor comes back
        with an empty registry — the driver calls this again on the
        ``ReplicaNotLoaded`` signal."""
        from raydp_tpu.serve import replica as serve_replica
        return serve_replica.load(replica_id, export_dir, self._actor_name)

    def serve_predict(self, replica_id: str, payload: bytes):
        """One encoded micro-batch → prediction array. Enqueues onto the
        replica's worker (decode/stage/H2D overlap the jitted apply there)
        and returns a DeferredReply — a slow model never parks this bounded
        dispatcher pool."""
        from raydp_tpu.serve import replica as serve_replica
        return serve_replica.predict(replica_id, payload)

    def serve_unload(self, replica_id: str) -> bool:
        from raydp_tpu.serve import replica as serve_replica
        return serve_replica.unload(replica_id)

    def serve_stats(self) -> Dict[str, Any]:
        from raydp_tpu.serve import replica as serve_replica
        return serve_replica.stats()

    # -- data-plane server (parity: getRDDPartition) ---------------------------
    def get_block(self, cache_key: str, recover_bytes: Optional[bytes] = None,
                  owner: Optional[str] = None) -> Dict[str, Any]:
        """Serve a cached block as an object-store ref; recompute on miss.

        Parity: RayDPExecutor.scala:312-355 — BlockManager read, recache via the
        driver agent on miss, then an Arrow IPC stream handed back through the
        object store.
        """
        table = self.cache.get(cache_key)
        if table is None:
            if recover_bytes is None:
                raise KeyError(f"block {cache_key} not cached and no lineage")
            task: T.Task = cloudpickle.loads(recover_bytes)
            table = T.run_task_body(task)
            self.cache.put(cache_key, table)
            logger.warning("recovered lost block %s via lineage", cache_key)
        ref = get_client().put_arrow(table, owner=owner)
        return {"ref": ref, "num_rows": table.num_rows}

    def warm_block(self, cache_key: str,
                   recover_bytes: Optional[bytes] = None) -> bool:
        """Pre-populate this executor's block cache — the graceful-drain
        re-homing path: a retiring executor's cached partition is rebuilt
        HERE from its lineage recipe (which reads the frame's pinned store
        blobs through the ranged-fetch plane) before the retiree is reaped,
        so later cache-local reads never pay the on-miss rebuild. Unlike
        :meth:`get_block`, nothing is written to the object store. True
        when the block is cached afterwards."""
        if self.cache.get(cache_key) is not None:
            return True
        if recover_bytes is None:
            return False
        task: T.Task = cloudpickle.loads(recover_bytes)
        table = T.run_task_body(task)
        self.cache.put(cache_key, table)
        return True

    def drain_info(self) -> Dict[str, Any]:
        """What this executor uniquely holds in process RAM — the drain
        protocol's inventory (cached blocks to re-home, serving replicas to
        re-route) and the scale bench's audit surface."""
        from raydp_tpu.serve import replica as serve_replica
        return {
            "executor": self._actor_name,
            "blocks": self.cache.keys(),
            "block_bytes": self.cache.total_bytes(),
            "replicas": sorted(r.get("replica", "")
                               for r in serve_replica.stats()["replicas"]),
        }

    def has_block(self, cache_key: str) -> bool:
        return self.cache.get(cache_key) is not None

    def list_blocks(self) -> List[str]:
        return self.cache.keys()

    def drop_blocks(self, keys: List[str],
                    if_stamp: Optional[str] = None) -> int:
        return self.cache.drop(keys, if_stamp)

    def drop_block_prefix(self, prefix: str) -> int:
        return self.cache.drop_prefix(prefix)

    def cache_stats(self) -> Dict[str, Any]:
        return {"keys": self.cache.keys(), "total_bytes": self.cache.total_bytes()}
