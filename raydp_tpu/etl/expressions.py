"""Column expressions compiled to ``pyarrow.compute`` kernels.

The surface mirrors the PySpark ``Column`` algebra the reference's examples lean on
(examples/data_process.py builds features with ``col`` arithmetic, comparisons,
casts and date functions). Expressions are small picklable trees; executors
evaluate them against an Arrow table partition with vectorized kernels — on the
CPU side of the pipeline there is no MXU to feed, so the win is staying columnar
and zero-copy end to end.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc


class Expr:
    """Base expression node. Subclasses must implement ``evaluate`` and ``_name``."""

    def evaluate(self, table: pa.Table):
        raise NotImplementedError

    def _name(self) -> str:
        raise NotImplementedError

    # -- naming ---------------------------------------------------------------
    def alias(self, name: str) -> "Expr":
        return Alias(self, name)

    # -- arithmetic -----------------------------------------------------------
    def __add__(self, other):
        return BinaryOp("add", self, _wrap(other))

    def __radd__(self, other):
        return BinaryOp("add", _wrap(other), self)

    def __sub__(self, other):
        return BinaryOp("subtract", self, _wrap(other))

    def __rsub__(self, other):
        return BinaryOp("subtract", _wrap(other), self)

    def __mul__(self, other):
        return BinaryOp("multiply", self, _wrap(other))

    def __rmul__(self, other):
        return BinaryOp("multiply", _wrap(other), self)

    def __truediv__(self, other):
        return BinaryOp("divide", self, _wrap(other))

    def __rtruediv__(self, other):
        return BinaryOp("divide", _wrap(other), self)

    def __mod__(self, other):
        return BinaryOp("mod", self, _wrap(other))

    def __neg__(self):
        return UnaryOp("negate", self)

    # -- comparisons ----------------------------------------------------------
    def __eq__(self, other):  # noqa: A003 - expression semantics over identity
        return BinaryOp("equal", self, _wrap(other))

    def __ne__(self, other):
        return BinaryOp("not_equal", self, _wrap(other))

    def __lt__(self, other):
        return BinaryOp("less", self, _wrap(other))

    def __le__(self, other):
        return BinaryOp("less_equal", self, _wrap(other))

    def __gt__(self, other):
        return BinaryOp("greater", self, _wrap(other))

    def __ge__(self, other):
        return BinaryOp("greater_equal", self, _wrap(other))

    # -- boolean --------------------------------------------------------------
    def __and__(self, other):
        return BinaryOp("and_kleene", self, _wrap(other))

    def __rand__(self, other):
        return BinaryOp("and_kleene", _wrap(other), self)

    def __or__(self, other):
        return BinaryOp("or_kleene", self, _wrap(other))

    def __ror__(self, other):
        return BinaryOp("or_kleene", _wrap(other), self)

    def __invert__(self):
        return UnaryOp("invert", self)

    def __hash__(self):
        return id(self)

    # -- analysis -------------------------------------------------------------
    def references(self) -> "set[str]":
        """Column names this expression reads — the optimizer's required-set
        primitive. The generic walk covers every node whose operands live in
        instance attributes (including tuples like ``When.branches``);
        :class:`Column` overrides it as the base case."""
        out: set = set()

        def visit(v):
            if isinstance(v, Expr):
                out.update(v.references())
            elif isinstance(v, (list, tuple)):
                for item in v:
                    visit(item)

        for v in self.__dict__.values():
            visit(v)
        return out

    # -- misc helpers ---------------------------------------------------------
    def is_null(self) -> "Expr":
        return UnaryOp("is_null", self)

    def is_not_null(self) -> "Expr":
        return UnaryOp("is_valid", self)

    def isin(self, values: Sequence) -> "Expr":
        return IsIn(self, list(values))

    def cast(self, dtype) -> "Expr":
        return Cast(self, dtype)

    def astype(self, dtype) -> "Expr":
        return Cast(self, dtype)

    def between(self, low, high) -> "Expr":
        return (self >= low) & (self <= high)

    def fill_null(self, value) -> "Expr":
        return FillNull(self, value)

    @property
    def dt(self) -> "_DtAccessor":
        return _DtAccessor(self)

    @property
    def str(self) -> "_StrAccessor":
        return _StrAccessor(self)


def _wrap(v) -> Expr:
    return v if isinstance(v, Expr) else Literal(v)


def _is_integer_like(v) -> bool:
    t = v.type if isinstance(v, (pa.Array, pa.ChunkedArray, pa.Scalar)) else None
    return t is not None and pa.types.is_integer(t)


def _modulo(left, right):
    """Python-semantics modulo (Arrow ships no kernel). Integers stay in int64
    (a float64 round-trip would corrupt values beyond 2^53); division by zero
    yields null, matching SQL/Spark."""
    import numpy as np

    if _is_integer_like(left) and _is_integer_like(right):
        l_arr, l_null = _to_np_int(left)
        r_arr, r_null = _to_np_int(right)
        l_arr, r_arr = np.broadcast_arrays(l_arr, r_arr)
        invalid = (r_arr == 0)
        for nm in (l_null, r_null):
            if nm is not None:
                invalid = invalid | np.broadcast_to(nm, invalid.shape)
        if invalid.ndim == 0:  # scalar % scalar
            if invalid:
                return pa.scalar(None, type=pa.int64())
            return pa.scalar(int(np.remainder(l_arr, r_arr)), type=pa.int64())
        safe_r = np.where(invalid, 1, r_arr)
        out = np.remainder(l_arr, safe_r)
        return pa.array(np.where(invalid, 0, out), type=pa.int64(),
                        mask=invalid if invalid.any() else None)
    quot = pc.floor(pc.divide(pc.cast(left, pa.float64(), safe=False),
                              pc.cast(right, pa.float64(), safe=False)))
    return pc.subtract(pc.cast(left, pa.float64(), safe=False),
                       pc.multiply(quot, pc.cast(right, pa.float64(), safe=False)))


def _to_np_int(v):
    """(int64 ndarray or 0-d, null-mask ndarray or None) for an Arrow value."""
    import numpy as np

    if isinstance(v, pa.Scalar):
        if v.as_py() is None:
            return np.int64(0), np.bool_(True)
        return np.int64(v.as_py()), None
    if isinstance(v, pa.ChunkedArray):
        v = v.combine_chunks()
    null_mask = None
    if v.null_count:
        null_mask = np.asarray(pc.is_null(v))
        v = pc.fill_null(v, 0)
    return np.asarray(pc.cast(v, pa.int64())), null_mask


class Column(Expr):
    def __init__(self, name: str):
        self.name = name

    def evaluate(self, table: pa.Table):
        return table.column(self.name)

    def _name(self) -> str:
        return self.name

    def references(self) -> "set[str]":
        return {self.name}

    def __repr__(self):
        return f"col({self.name!r})"


class Literal(Expr):
    def __init__(self, value: Any):
        self.value = value

    def evaluate(self, table: pa.Table):
        return pa.scalar(self.value)

    def _name(self) -> str:
        return str(self.value)


class Alias(Expr):
    def __init__(self, child: Expr, name: str):
        self.child = child
        self.name = name

    def evaluate(self, table: pa.Table):
        return self.child.evaluate(table)

    def _name(self) -> str:
        return self.name


class BinaryOp(Expr):
    def __init__(self, op: str, left: Expr, right: Expr):
        self.op = op
        self.left = left
        self.right = right

    def evaluate(self, table: pa.Table):
        left = self.left.evaluate(table)
        right = self.right.evaluate(table)
        if self.op == "mod":
            return _modulo(left, right)
        return getattr(pc, self.op)(left, right)

    def _name(self) -> str:
        return f"({self.left._name()} {self.op} {self.right._name()})"


class UnaryOp(Expr):
    def __init__(self, op: str, child: Expr):
        self.op = op
        self.child = child

    def evaluate(self, table: pa.Table):
        return getattr(pc, self.op)(self.child.evaluate(table))

    def _name(self) -> str:
        return f"{self.op}({self.child._name()})"


class IsIn(Expr):
    def __init__(self, child: Expr, values: List):
        self.child = child
        self.values = values

    def evaluate(self, table: pa.Table):
        return pc.is_in(self.child.evaluate(table), value_set=pa.array(self.values))

    def _name(self) -> str:
        return f"{self.child._name()} IN {self.values}"


class Cast(Expr):
    def __init__(self, child: Expr, dtype):
        self.child = child
        self.dtype = dtype

    def evaluate(self, table: pa.Table):
        return pc.cast(self.child.evaluate(table), _to_arrow_type(self.dtype),
                       safe=False)

    def _name(self) -> str:
        return self.child._name()


class FillNull(Expr):
    def __init__(self, child: Expr, value):
        self.child = child
        self.value = value

    def evaluate(self, table: pa.Table):
        return pc.fill_null(self.child.evaluate(table), self.value)

    def _name(self) -> str:
        return self.child._name()


class When(Expr):
    """``when(cond, value).when(...).otherwise(default)`` conditional."""

    def __init__(self, branches: List, default=None):
        self.branches = branches
        self.default = default

    def when(self, cond: Expr, value) -> "When":
        return When(self.branches + [(cond, _wrap(value))], self.default)

    def otherwise(self, value) -> "When":
        return When(self.branches, _wrap(value))

    def evaluate(self, table: pa.Table):
        conds = pa.table(
            {f"c{i}": _to_bool_array(c.evaluate(table), table.num_rows)
             for i, (c, _) in enumerate(self.branches)})
        cases = [v.evaluate(table) for _, v in self.branches]
        default = (self.default.evaluate(table) if self.default is not None
                   else pa.scalar(None))
        return pc.case_when(pc.make_struct(*conds.columns), *cases, default)

    def _name(self) -> str:
        return "CASE WHEN"


def _to_bool_array(v, length: int):
    if isinstance(v, pa.Scalar):
        return pa.array([v.as_py()] * length, type=pa.bool_())
    if isinstance(v, pa.ChunkedArray):
        return v.combine_chunks()
    return v


class Func(Expr):
    """A named pyarrow.compute function over expressions, e.g. log1p, abs."""

    def __init__(self, fn: str, children: List[Expr], options=None,
                 name: Optional[str] = None):
        self.fn = fn
        self.children = children
        self.options = options
        self.name = name

    def evaluate(self, table: pa.Table):
        args = [c.evaluate(table) for c in self.children]
        kwargs = {"options": self.options} if self.options is not None else {}
        return getattr(pc, self.fn)(*args, **kwargs)

    def _name(self) -> str:
        return self.name or f"{self.fn}({', '.join(c._name() for c in self.children)})"


class UdfExpr(Expr):
    """A user-defined function over column expressions.

    Parity: PySpark ``@udf`` as the reference's feature engineering uses it
    (examples/data_process.py ``night``/``late_night``/``manhattan`` UDFs). The
    function is applied per-row over the evaluated child arrays; the result is
    cast to ``return_type``. Vectorized ``pyarrow.compute`` expressions are always
    preferred — UDFs are the escape hatch.
    """

    def __init__(self, fn: Callable, children: List[Expr], return_type,
                 name: Optional[str] = None):
        self.fn = fn
        self.children = children
        self.return_type = return_type
        self.name = name or getattr(fn, "__name__", "udf")

    def evaluate(self, table: pa.Table):
        cols = []
        for c in self.children:
            v = evaluate_to_array(c, table)
            cols.append(v.to_pylist())
        if not cols:
            out = [self.fn() for _ in range(table.num_rows)]
        else:
            out = [self.fn(*vals) for vals in zip(*cols)]
        return pa.array(out, type=_to_arrow_type(self.return_type))

    def _name(self) -> str:
        return self.name


def udf(return_type="string"):
    """``@udf("int")`` decorator; the wrapped fn accepts column names or exprs."""

    def deco(fn):
        def make(*cols):
            children = [c if isinstance(c, Expr) else Column(c) for c in cols]
            return UdfExpr(fn, children, return_type)
        make.__name__ = getattr(fn, "__name__", "udf")
        return make

    if callable(return_type):  # used bare: @udf
        fn, return_type = return_type, "string"
        return deco(fn)
    return deco


class AggExpr:
    """An aggregation spec for ``groupBy().agg(...)``: (fn, column, out name)."""

    def __init__(self, fn: str, column: str, name: Optional[str] = None):
        self.fn = fn
        self.column = column
        self.name = name or f"{self.fn}({column})"

    def alias(self, name: str) -> "AggExpr":
        return AggExpr(self.fn, self.column, name)

    def over(self, spec):
        """Evaluate this aggregate as a window function over ``spec``
        (Spark: ``F.sum("x").over(Window.partitionBy("k"))`` broadcasts the
        per-partition aggregate to every row)."""
        from raydp_tpu.etl.window import WindowExpr

        supported = {"mean", "sum", "min", "max", "count"}
        if self.fn not in supported:
            raise ValueError(
                f"aggregate {self.fn!r} is not supported over a window; "
                f"have {sorted(supported)}")
        return WindowExpr(self.fn, spec, arg_col=self.column)


class _DtAccessor:
    """Datetime component extraction (examples/data_process.py uses dayofweek,
    hour, month etc. on pickup datetimes)."""

    def __init__(self, child: Expr):
        self._child = child

    def __getattr__(self, item: str):
        mapping = {
            "year": "year", "month": "month", "day": "day",
            "hour": "hour", "minute": "minute", "second": "second",
            "dayofweek": "day_of_week", "day_of_week": "day_of_week",
            "dayofyear": "day_of_year", "week": "iso_week",
        }
        if item not in mapping:
            raise AttributeError(item)
        return lambda: Func(mapping[item], [self._child], name=item)


class _StrAccessor:
    def __init__(self, child: Expr):
        self._child = child

    def lower(self):
        return Func("utf8_lower", [self._child])

    def upper(self):
        return Func("utf8_upper", [self._child])

    def strip(self):
        return Func("utf8_trim_whitespace", [self._child])

    def contains(self, pat: str):
        import pyarrow.compute as _pc
        return Func("match_substring", [self._child],
                    options=_pc.MatchSubstringOptions(pat))

    def startswith(self, pat: str):
        import pyarrow.compute as _pc
        return Func("starts_with", [self._child],
                    options=_pc.MatchSubstringOptions(pat))


_TYPE_ALIASES: Dict[str, Callable[[], pa.DataType]] = {
    "int": pa.int64, "long": pa.int64, "int64": pa.int64, "int32": pa.int32,
    "short": pa.int16, "byte": pa.int8, "float": pa.float32, "float32": pa.float32,
    "double": pa.float64, "float64": pa.float64, "bool": pa.bool_,
    "boolean": pa.bool_, "string": pa.string, "str": pa.string,
    "timestamp": lambda: pa.timestamp("us"), "date": pa.date32,
    "binary": pa.binary,
}


def _to_arrow_type(dtype) -> pa.DataType:
    if isinstance(dtype, pa.DataType):
        return dtype
    if isinstance(dtype, str):
        key = dtype.lower()
        if key in _TYPE_ALIASES:
            return _TYPE_ALIASES[key]()
    if isinstance(dtype, type) and issubclass(dtype, (int, float, bool, str)):
        return {int: pa.int64(), float: pa.float64(), bool: pa.bool_(),
                str: pa.string()}[dtype]
    if isinstance(dtype, np.dtype) or (isinstance(dtype, type)
                                       and issubclass(dtype, np.generic)):
        return pa.from_numpy_dtype(np.dtype(dtype))
    raise ValueError(f"unsupported dtype: {dtype!r}")


def evaluate_to_array(expr: Expr, table: pa.Table):
    """Evaluate and materialize to a ChunkedArray of the table's length."""
    out = expr.evaluate(table)
    if isinstance(out, pa.Scalar):
        out = pa.chunked_array([pa.array([out.as_py()] * table.num_rows,
                                         type=out.type if out.type != pa.null() else None)])
    if isinstance(out, pa.Array):
        out = pa.chunked_array([out])
    return out


def _substitute_value(v, mapping: Dict[str, str]):
    if isinstance(v, Expr):
        return substitute_columns(v, mapping)
    if isinstance(v, tuple):
        return tuple(_substitute_value(x, mapping) for x in v)
    if isinstance(v, list):
        return [_substitute_value(x, mapping) for x in v]
    return v


def substitute_columns(expr: Expr, mapping: Dict[str, str]) -> Expr:
    """A structural copy of ``expr`` with every :class:`Column` renamed through
    ``mapping`` (names absent from the mapping are kept). Used by the plan
    optimizer to sink predicates below ``Rename`` nodes."""
    import copy

    if isinstance(expr, Column):
        return Column(mapping.get(expr.name, expr.name))
    clone = copy.copy(expr)
    for k, v in list(clone.__dict__.items()):
        clone.__dict__[k] = _substitute_value(v, mapping)
    return clone


# -- public constructors ------------------------------------------------------------
def col(name: str) -> Column:
    return Column(name)


def lit(value: Any) -> Literal:
    return Literal(value)


def when(cond: Expr, value) -> When:
    return When([(cond, _wrap(value))])
