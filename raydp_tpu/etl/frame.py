"""The lazy distributed DataFrame (the SparkDataFrame analogue).

Surface parity targets what the reference's examples exercise on Spark DataFrames
(examples/data_process.py, examples/pytorch_nyctaxi.py:58-67): ``select``,
``filter``/``where``, ``withColumn``, ``drop``, ``dropna``/``fillna``,
``groupBy().agg``, ``join``, ``randomSplit``, ``repartition``, ``count``,
``collect``/``toPandas``, ``schema``, ``write.parquet``. Plans are immutable;
every transformation returns a new frame sharing the session's engine.
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional, Sequence, Tuple, Union

import pyarrow as pa

from raydp_tpu.etl import plan as P
from raydp_tpu.etl.expressions import AggExpr, Column, Expr, _wrap, col
from raydp_tpu.log import get_logger

logger = get_logger("etl.frame")


class DataFrame:
    def __init__(self, session, plan: P.PlanNode,
                 schema: Optional[pa.Schema] = None):
        self._session = session
        self._plan = plan
        self._schema: Optional[pa.Schema] = schema

    # ---- schema -------------------------------------------------------------
    @property
    def schema(self) -> pa.Schema:
        if self._schema is None:
            sample = self.limit(1)._collect_table()
            self._schema = sample.schema
        return self._schema

    @property
    def columns(self) -> List[str]:
        return list(self.schema.names)

    # ---- projections --------------------------------------------------------
    def _all_columns(self) -> List[Tuple[str, Expr]]:
        return [(name, col(name)) for name in self.columns]

    def select(self, *cols_) -> "DataFrame":
        columns: List[Tuple[str, Expr]] = []
        for c in cols_:
            if isinstance(c, str):
                columns.append((c, col(c)))
            elif isinstance(c, Expr):
                columns.append((c._name(), c))
            else:
                raise TypeError(f"cannot select {c!r}")
        return self._with(P.Project(self._plan, columns))

    def withColumn(self, name: str, expr) -> "DataFrame":
        from raydp_tpu.etl.window import WindowExpr

        if isinstance(expr, WindowExpr):
            # window columns are a wide op (shuffle by partition keys), not a
            # per-partition projection. Replacing an existing column drops it
            # first (WindowStep appends) — unless the window itself reads it.
            base = self._plan
            if name in self.columns:
                used = set(expr.spec.partition_keys)
                used.update(k for k, _ in expr.spec.order_keys)
                if expr.arg_col:
                    used.add(expr.arg_col)
                if name in used:
                    raise ValueError(
                        f"withColumn({name!r}) would replace a column the "
                        "window function reads; use a different output name")
                base = self.drop(name)._plan
            # derive the output schema statically: without it, chaining a
            # second window column would run the first one's whole shuffle
            # just to list column names (the schema property's limit-1 probe)
            schema = None
            if self._schema is not None:
                from raydp_tpu.etl.tasks import window_output_type
                arg_t = None
                if expr.arg_col and expr.arg_col != "*":
                    i = self._schema.get_field_index(expr.arg_col)
                    arg_t = self._schema.field(i).type if i >= 0 else None
                base_schema = self._schema
                if name in base_schema.names:
                    base_schema = base_schema.remove(
                        base_schema.get_field_index(name))
                schema = base_schema.append(
                    pa.field(name, window_output_type(expr.fn, arg_t)))
            return self._with(P.WindowOp(
                base, list(expr.spec.partition_keys),
                list(expr.spec.order_keys), name, expr.fn,
                expr.arg_col, expr.offset, expr.default), schema=schema)
        columns = [(n, e) for n, e in self._all_columns() if n != name]
        columns.append((name, _wrap(expr)))
        return self._with(P.Project(self._plan, columns))

    with_column = withColumn

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        return self._with(P.Rename(self._plan, {old: new}))

    def drop(self, *names: str) -> "DataFrame":
        keep = [(n, e) for n, e in self._all_columns() if n not in names]
        return self._with(P.Project(self._plan, keep))

    def filter(self, predicate: Expr) -> "DataFrame":
        return self._with(P.Filter(self._plan, predicate))

    where = filter

    def dropna(self, subset: Optional[List[str]] = None) -> "DataFrame":
        return self._with(P.DropNa(self._plan, subset))

    def fillna(self, value, subset: Optional[List[str]] = None) -> "DataFrame":
        cols = subset or self.columns
        out = self
        for c in cols:
            out = out.withColumn(c, col(c).fill_null(value))
        return out

    def limit(self, n: int) -> "DataFrame":
        # local limit per partition; exact global limit applied at collect
        return self._with(P.Limit(self._plan, n), schema=self._schema)

    def sample(self, fraction: float, seed: Optional[int] = None) -> "DataFrame":
        return self._with(P.Sample(self._plan, fraction, seed),
                          schema=self._schema)

    def repartition(self, num_partitions: int) -> "DataFrame":
        return self._with(P.Repartition(self._plan, num_partitions, shuffle=True),
                          schema=self._schema)

    def coalesce(self, num_partitions: int) -> "DataFrame":
        return self._with(P.Repartition(self._plan, num_partitions, shuffle=False),
                          schema=self._schema)

    def union(self, other: "DataFrame") -> "DataFrame":
        return self._with(P.Union([self._plan, other._plan]),
                          schema=self._schema)

    def sort(self, *cols_, ascending: bool = True) -> "DataFrame":
        """Global sort. Columns are names, Column expressions, or
        ``(name, "ascending"|"descending")`` tuples for per-key direction."""
        keys = []
        for c in cols_:
            if isinstance(c, tuple):
                name, order = c
                keys.append((name if isinstance(name, str) else name._name(),
                             order))
            else:
                keys.append((c if isinstance(c, str) else c._name(),
                             "ascending" if ascending else "descending"))
        return self._with(P.Sort(self._plan, keys), schema=self._schema)

    orderBy = sort

    def distinct(self) -> "DataFrame":
        """Deduplicate whole rows (parity: Spark ``distinct``; reference
        usage examples/data_process.py). Executor-side hash-shuffle dedupe."""
        return self._with(P.Distinct(self._plan, None), schema=self._schema)

    def dropDuplicates(self, subset: Optional[Sequence[str]] = None
                       ) -> "DataFrame":
        """Keep one row per distinct value of ``subset`` (None → all
        columns); which row survives is unspecified, as in Spark."""
        return self._with(
            P.Distinct(self._plan, list(subset) if subset else None),
            schema=self._schema)

    drop_duplicates = dropDuplicates

    def describe(self, *cols: str) -> "DataFrame":
        """count/mean/stddev/min/max summary of numeric columns (parity:
        Spark ``describe``, reference usage examples/data_process.py). The
        executors reduce partitions to moment partials; the driver merges
        those tiny rows and returns a small local frame with a ``summary``
        column, so ``describe().show()`` works as in Spark."""
        names = list(cols)
        if not names:
            names = [f.name for f in self.schema
                     if pa.types.is_integer(f.type)
                     or pa.types.is_floating(f.type)]
        if not names:
            raise ValueError("describe: no numeric columns")
        stats = self._session.engine.describe(self._plan, names)
        rows = ["count", "mean", "stddev", "min", "max"]
        data = {"summary": rows}
        for c in names:
            data[c] = [float(stats[c][r]) if stats[c][r] is not None
                       else None for r in rows]
        import pandas as pd
        return self._session.createDataFrame(pd.DataFrame(data),
                                             num_partitions=1)

    def join(self, other: "DataFrame", on: Union[str, List[str]],
             how: str = "inner") -> "DataFrame":
        keys = [on] if isinstance(on, str) else list(on)
        return self._with(P.Join(self._plan, other._plan, keys, keys, how))

    def groupBy(self, *keys: str) -> "GroupedData":
        return GroupedData(self, list(keys))

    groupby = groupBy

    def randomSplit(self, weights: Sequence[float],
                    seed: Optional[int] = None) -> List["DataFrame"]:
        """Disjoint random splits via per-row uniform draws in weight bands
        (reference: utils.py random_split → df.randomSplit)."""
        total = float(sum(weights))
        seed = seed if seed is not None else 17
        out, lo = [], 0.0
        for w in weights:
            hi = lo + w / total
            out.append(self._with(P.SplitSelect(self._plan, lo, hi, seed),
                                  schema=self._schema))
            lo = hi
        return out

    random_split = randomSplit

    # ---- actions ------------------------------------------------------------
    def count(self) -> int:
        return self._session.engine.count(self._plan)

    def _collect_table(self) -> pa.Table:
        return self._session.engine.collect(self._plan)

    def collect(self) -> List[dict]:
        return self._collect_table().to_pylist()

    def to_arrow(self) -> pa.Table:
        return self._collect_table()

    def toPandas(self):
        return self._collect_table().to_pandas()

    to_pandas = toPandas

    def take(self, n: int) -> List[dict]:
        return self.limit(n)._collect_table().slice(0, n).to_pylist()

    def first(self) -> Optional[dict]:
        rows = self.take(1)
        return rows[0] if rows else None

    def show(self, n: int = 20) -> None:
        print(self.limit(n)._collect_table().slice(0, n).to_pandas())

    def num_partitions(self) -> int:
        return self._session.engine.num_partitions(self._plan)

    # ---- persistence --------------------------------------------------------
    def persist(self) -> "DataFrame":
        """Materialize into executor block caches with lineage (recoverable).

        Parity: ``df.toArrowBatchRdd.persist(); rdd.count()`` + GC pin inside
        ``prepareRecoverableRDD`` (ObjectStoreWriter.scala:164-204). The session
        tracks the cached frame so ``release`` can drop it later.
        """
        frame_id = f"f{uuid.uuid4().hex[:10]}"
        cached = self._session.engine.cache(self._plan, frame_id)
        self._session.register_cached(frame_id, cached)
        return self._with(cached, schema=self._schema)

    cache = persist

    def unpersist(self) -> None:
        if isinstance(self._plan, P.CachedScan):
            self._session.release_cached(self._plan.frame_id)

    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)

    # ---- internals ----------------------------------------------------------
    def _with(self, plan: P.PlanNode,
              schema: Optional[pa.Schema] = None) -> "DataFrame":
        return DataFrame(self._session, plan, schema)

    def __repr__(self):
        try:
            return f"DataFrame[{', '.join(self.columns)}]"
        except Exception:
            return "DataFrame[<unresolved>]"


class GroupedData:
    def __init__(self, df: DataFrame, keys: List[str]):
        self._df = df
        self._keys = keys

    def agg(self, *aggs: AggExpr) -> DataFrame:
        specs: List[Tuple[str, str, str]] = []
        for a in aggs:
            column = a.column
            if column == "*":
                column = self._keys[0]
            specs.append((column, a.fn, a.name))
        return self._df._with(P.GroupAgg(self._df._plan, self._keys, specs))

    def count(self) -> DataFrame:
        key = self._keys[0]
        return self._df._with(P.GroupAgg(
            self._df._plan, self._keys, [(key, "count", "count")]))

    def _simple(self, fn: str, cols: Sequence[str]) -> DataFrame:
        cols = cols or [c for c in self._df.columns if c not in self._keys]
        specs = [(c, fn, f"{fn}({c})") for c in cols]
        return self._df._with(P.GroupAgg(self._df._plan, self._keys, specs))

    def mean(self, *cols: str) -> DataFrame:
        return self._simple("mean", cols)

    avg = mean

    def sum(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple("sum", cols)

    def max(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple("max", cols)

    def min(self, *cols: str) -> DataFrame:  # noqa: A003
        return self._simple("min", cols)


class DataFrameWriter:
    def __init__(self, df: DataFrame):
        self._df = df

    def parquet(self, path: str, mode: str = "overwrite") -> None:
        """Write one parquet file per partition under ``path`` (the spill path
        used by ``fit_on_spark(fs_directory=...)``, torch/estimator.py:365-376)."""
        import os

        import pyarrow.parquet as pq
        os.makedirs(path, exist_ok=True)
        refs, _, _ = self._df._session.engine.materialize(self._df._plan)
        from raydp_tpu.runtime.object_store import get_client
        client = get_client()
        for i, ref in enumerate(refs):
            table = client.get(ref)
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))
        client.free(refs)

    def csv(self, path: str, mode: str = "overwrite") -> None:
        import os

        import pyarrow.csv as pacsv
        os.makedirs(path, exist_ok=True)
        refs, _, _ = self._df._session.engine.materialize(self._df._plan)
        from raydp_tpu.runtime.object_store import get_client
        client = get_client()
        for i, ref in enumerate(refs):
            pacsv.write_csv(client.get(ref),
                            os.path.join(path, f"part-{i:05d}.csv"))
        client.free(refs)
