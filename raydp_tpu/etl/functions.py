"""Column functions with PySpark-compatible semantics.

The reference's feature pipelines import these from ``pyspark.sql.functions``
(examples/data_process.py:4): datetime components, ``abs``, ``lit``, ``udf``.
Semantics intentionally match Spark where Spark differs from Arrow — e.g.
``dayofweek`` is 1=Sunday..7=Saturday in Spark while Arrow counts 0=Monday — so
ported pipelines produce identical features.
"""

from __future__ import annotations

from typing import Optional, Union

import pyarrow.compute as pc

from raydp_tpu.etl.expressions import (
    AggExpr, Column, Expr, Func, Literal, UdfExpr, _wrap, col, lit, udf, when,
)

__all__ = [
    "col", "lit", "when", "udf",
    "hour", "minute", "second", "year", "month", "quarter",
    "dayofmonth", "dayofweek", "dayofyear", "weekofyear",
    "abs", "sqrt", "exp", "log", "log1p", "pow", "floor", "ceil", "round",
    "upper", "lower", "trim", "length", "concat",
    "mean", "avg", "sum", "count", "max", "min", "stddev", "variance",
    "first", "last", "count_distinct",
    "row_number", "rank", "dense_rank", "lag", "lead",
]


def _c(x: Union[str, Expr]) -> Expr:
    return Column(x) if isinstance(x, str) else x


# -- datetime (Spark semantics) ------------------------------------------------------
def hour(c):
    return Func("hour", [_c(c)], name="hour")


def minute(c):
    return Func("minute", [_c(c)], name="minute")


def second(c):
    return Func("second", [_c(c)], name="second")


def year(c):
    return Func("year", [_c(c)], name="year")


def month(c):
    return Func("month", [_c(c)], name="month")


def quarter(c):
    return Func("quarter", [_c(c)], name="quarter")


def dayofmonth(c):
    return Func("day", [_c(c)], name="dayofmonth")


def dayofweek(c):
    # Arrow: Monday=0..Sunday=6 ; Spark: Sunday=1..Saturday=7
    arrow_dow = Func("day_of_week", [_c(c)], name="dayofweek")
    return ((arrow_dow + 1) % 7) + 1


def dayofyear(c):
    return Func("day_of_year", [_c(c)], name="dayofyear")


def weekofyear(c):
    return Func("iso_week", [_c(c)], name="weekofyear")


# -- math ---------------------------------------------------------------------------
def abs(c):  # noqa: A001 - Spark-compatible name
    return Func("abs", [_c(c)], name="abs")


def sqrt(c):
    return Func("sqrt", [_c(c)], name="sqrt")


def exp(c):
    return Func("exp", [_c(c)], name="exp")


def log(c):
    return Func("ln", [_c(c)], name="log")


def log1p(c):
    return Func("log1p", [_c(c)], name="log1p")


def pow(base, exponent):  # noqa: A001
    return Func("power", [_wrap(base), _wrap(exponent)], name="pow")


def floor(c):
    return Func("floor", [_c(c)], name="floor")


def ceil(c):
    return Func("ceil", [_c(c)], name="ceil")


def round(c, ndigits: int = 0):  # noqa: A001
    return Func("round", [_c(c)], options=pc.RoundOptions(ndigits=ndigits),
                name="round")


# -- strings ------------------------------------------------------------------------
def upper(c):
    return Func("utf8_upper", [_c(c)], name="upper")


def lower(c):
    return Func("utf8_lower", [_c(c)], name="lower")


def trim(c):
    return Func("utf8_trim_whitespace", [_c(c)], name="trim")


def length(c):
    return Func("utf8_length", [_c(c)], name="length")


def concat(*cols):
    return Func("binary_join_element_wise",
                [_c(c) for c in cols] + [Literal("")], name="concat")


# -- aggregations -------------------------------------------------------------------
def mean(c: str) -> AggExpr:
    return AggExpr("mean", c)


avg = mean


def sum(c: str) -> AggExpr:  # noqa: A001
    return AggExpr("sum", c)


def count(c: str = "*") -> AggExpr:
    return AggExpr("count", c)


def max(c: str) -> AggExpr:  # noqa: A001
    return AggExpr("max", c)


def min(c: str) -> AggExpr:  # noqa: A001
    return AggExpr("min", c)


def stddev(c: str) -> AggExpr:
    return AggExpr("stddev", c)


def variance(c: str) -> AggExpr:
    return AggExpr("variance", c)


def first(c: str) -> AggExpr:
    return AggExpr("first", c)


def last(c: str) -> AggExpr:
    return AggExpr("last", c)


def count_distinct(c: str) -> AggExpr:
    return AggExpr("count_distinct", c)


# -- window functions (Spark: F.row_number().over(Window...)) ------------------------
def row_number():
    from raydp_tpu.etl.window import WindowFunction
    return WindowFunction("row_number")


def rank():
    from raydp_tpu.etl.window import WindowFunction
    return WindowFunction("rank")


def dense_rank():
    from raydp_tpu.etl.window import WindowFunction
    return WindowFunction("dense_rank")


def lag(c: str, offset: int = 1, default=None):
    from raydp_tpu.etl.window import WindowFunction
    return WindowFunction("lag", arg_col=c, offset=offset, default=default)


def lead(c: str, offset: int = 1, default=None):
    from raydp_tpu.etl.window import WindowFunction
    return WindowFunction("lead", arg_col=c, offset=offset, default=default)
