"""The ETL master actor.

Parity: ``RayDPSparkMaster`` + ``RayAppMaster`` collapsed into one native actor —
executor registration and executor-id assignment (RayAppMaster.scala:133-167), the
restarted-executor old↔new id map consulted by conversions
(RayAppMaster.scala:48,192-209; ObjectStoreWriter.scala:183-191), and the
object-holder role for the reverse data path: the master owns objects handed to
``to_frame`` so they outlive the frames/executors that produced them
(ray_cluster_master.py:222-226 ``add_objects``/``get_object``; dataset.py:137-158
ownership transfer).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from raydp_tpu.log import get_logger
from raydp_tpu.runtime.object_store import ObjectRef

logger = get_logger("etl.master")


class EtlMaster:
    def __init__(self, app_name: str):
        self.app_name = app_name
        self._lock = threading.Lock()
        self._next_executor_id = 0
        # executor_id -> actor name
        self._executors: Dict[int, str] = {}
        # restarted actor bookkeeping: actor name -> list of its executor ids
        self._ids_by_actor: Dict[str, List[int]] = {}
        # new executor id -> old executor id (RayAppMaster.scala:48)
        self._restarted: Dict[int, int] = {}
        # object holder: df_id -> refs (ray_cluster_master.py:222-226)
        self._held_objects: Dict[str, List[ObjectRef]] = {}

    # -- registration ---------------------------------------------------------
    def register_executor(self, actor_name: str, was_restarted: bool) -> int:
        with self._lock:
            executor_id = self._next_executor_id
            self._next_executor_id += 1
            self._executors[executor_id] = actor_name
            history = self._ids_by_actor.setdefault(actor_name, [])
            if was_restarted and history:
                old_id = history[-1]
                self._restarted[executor_id] = old_id
                self._executors.pop(old_id, None)
                logger.info("executor %s re-registered: id %d -> %d",
                            actor_name, old_id, executor_id)
            history.append(executor_id)
            return executor_id

    def resolve_executor(self, executor_id: int) -> Optional[str]:
        """Actor name for an executor id, following restart remapping
        (parity: ObjectStoreWriter.scala:183-191)."""
        with self._lock:
            if executor_id in self._executors:
                return self._executors[executor_id]
            # an old id may have been superseded by a restart
            for new_id, old_id in self._restarted.items():
                if old_id == executor_id:
                    return self._executors.get(new_id)
            return None

    def executors(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._executors)

    def remove_executor(self, actor_name: str) -> None:
        """Reap an executor record (parity: onDisconnected,
        RayAppMaster.scala:212-214)."""
        with self._lock:
            victims = [i for i, n in self._executors.items() if n == actor_name]
            for i in victims:
                del self._executors[i]

    # -- object holder --------------------------------------------------------
    def add_objects(self, holder_id: str, refs: List[ObjectRef]) -> None:
        with self._lock:
            self._held_objects[holder_id] = list(refs)

    def get_object(self, holder_id: str, index: int) -> ObjectRef:
        with self._lock:
            return self._held_objects[holder_id][index]

    def get_objects(self, holder_id: str) -> List[ObjectRef]:
        with self._lock:
            return list(self._held_objects.get(holder_id, []))

    def drop_objects(self, holder_id: str) -> List[ObjectRef]:
        with self._lock:
            return self._held_objects.pop(holder_id, [])

    def holders(self) -> List[str]:
        with self._lock:
            return list(self._held_objects)

    def ping(self) -> str:
        return "pong"
