"""Rule-based logical-plan optimizer: move fewer bytes through the shuffle.

The reference leans on Spark's Catalyst doing real query optimization before any
row reaches RayDP's conversion layer; the seed engine compiled the user's plan
verbatim, so every wide operator (groupby/join/window/distinct) shuffled
full-width, full-row tables through the object store. This module rewrites the
plan tree before compilation:

1. **Predicate pushdown** — ``Filter`` sinks below ``Project`` (when the
   referenced columns are plain pass-throughs), ``Rename`` (predicate column
   names rewritten through the mapping), ``DropNa`` and ``Union``, so rows die
   before they are bucketed or projected. It does NOT commute with
   ``Sample``/``SplitSelect``: their draws are positional, so filtering first
   would select a different random row set.
2. **Projection pruning** — required-column sets walk the tree top-down
   (via :meth:`Expr.references`); wide operators narrow their shuffle input to
   key + referenced columns, ``ParquetScan`` prunes at the reader
   (``columns=``), and CSV / in-memory scans get a post-read prune ``Project``.

Map-side partial aggregation (the third shuffle-byte rule) lives in
``Engine._compile_groupagg`` because it is a physical rewrite of the shuffle
stage, not a plan-tree rewrite; it consults :func:`enabled` from here.

Opt-out: ``RDT_ETL_OPTIMIZER=0`` (read per action, so tests can flip it at
runtime) preserves the naive compile-verbatim path.
"""

from __future__ import annotations

import os
from dataclasses import replace
from typing import List, Optional

import pyarrow as pa

from raydp_tpu import knobs
from raydp_tpu.etl import plan as P
from raydp_tpu.etl.expressions import Column, Expr, col, substitute_columns

#: aggregate functions the engine can decompose into map-side partials +
#: a reduce-side merge (mean via sum+count); anything else falls back to the
#: single-phase shuffle-then-aggregate path
DECOMPOSABLE_AGGS = {"count", "sum", "min", "max", "mean"}


def enabled() -> bool:
    return bool(knobs.get("RDT_ETL_OPTIMIZER"))


# ==== adaptive query execution (AQE) knobs =========================================
# The static rules above plan blind; the engine's AQE layer re-plans at stage
# boundaries from MEASURED statistics (materialized bytes, the consolidated
# shuffle's per-bucket size index). The knobs live here beside the optimizer
# opt-out because they follow the same contract: read per action, so a test
# or bench can flip them at runtime. A threshold of 0 disables its rule.

def aqe_enabled() -> bool:
    """Adaptive-execution master switch (default ON, ``RDT_ETL_AQE=0`` off).
    Read per action like ``RDT_ETL_OPTIMIZER``."""
    return bool(knobs.get("RDT_ETL_AQE"))


def aqe_broadcast_max() -> int:
    """Broadcast-hash-join threshold: a join side whose MEASURED materialized
    bytes fit under this skips its shuffle entirely and replicates to every
    executor instead (default ~8MB, Spark's autoBroadcastJoinThreshold
    ballpark). 0 disables rule (a)."""
    return int(knobs.get("RDT_AQE_BROADCAST_MAX"))


def aqe_skew_factor() -> float:
    """Skew-mitigation trigger: a reduce bucket whose measured bytes exceed
    this multiple of the median bucket splits its byte-ranges across several
    reduce tasks. 0 disables rule (b)."""
    return float(knobs.get("RDT_AQE_SKEW_FACTOR"))


def aqe_coalesce_min() -> int:
    """Tiny-partition coalescing target: adjacent reduce buckets fuse into
    one reduce task until their combined measured bytes reach this (default
    1MB), so many-bucket configs stop paying a dispatch per kilobyte-sized
    bucket. Doubles as the floor under which a bucket is never worth skew-
    splitting. 0 disables rule (c) (and the split floor)."""
    return int(knobs.get("RDT_AQE_COALESCE_MIN"))


def estimate_plan_bytes(node: P.PlanNode) -> Optional[int]:
    """Static upper-bound estimate of a plan's materialized bytes, or None
    when nothing cheap is known. Used by the AQE pre-shuffle broadcast rule
    to decide whether materializing a join side is worth trying at all — the
    MEASURED size after materialization is what actually gates the
    broadcast, so an over-estimate only costs a missed opportunity and an
    under-estimate is corrected (the materialized refs shuffle as an
    in-memory side instead)."""
    if isinstance(node, P.InMemory):
        return sum(int(getattr(r, "size", 0) or 0) for r in node.refs)
    if isinstance(node, P.RangeScan):
        n = max(0, node.stop - node.start)
        return (n // max(node.step, 1) + 1) * 8
    if isinstance(node, (P.CsvScan, P.ParquetScan)):
        try:
            return sum(os.path.getsize(p) for p in node.paths)
        except OSError:
            return None
    if isinstance(node, P.Union):
        total = 0
        for child in node.inputs:
            est = estimate_plan_bytes(child)
            if est is None:
                return None
            total += est
        return total
    # row-preserving / row-shrinking unary ops: the child's bytes bound the
    # output (WindowOp adds one column — close enough for an upper bound)
    if isinstance(node, (P.Project, P.Rename, P.DropNa, P.Filter, P.Limit,
                         P.Sample, P.SplitSelect, P.Repartition, P.Sort,
                         P.Distinct, P.WindowOp)):
        return estimate_plan_bytes(node.child)
    # GroupAgg / Join / CachedScan outputs are not statically bounded; the
    # post-map fallback (measured map bytes) covers those sides instead
    return None


def optimize(node: P.PlanNode) -> P.PlanNode:
    """Apply all plan rewrites (no-op when the knob disables the optimizer)."""
    if not enabled():
        return node
    node = push_filters(node)
    node = prune_columns(node, None)
    return node


# ==== predicate pushdown ===========================================================
def _is_passthrough(expr: Expr) -> bool:
    return type(expr) is Column


def push_filters(node: P.PlanNode) -> P.PlanNode:
    """Sink every ``Filter`` as deep as the rewrite rules allow."""
    if isinstance(node, P.Filter):
        child = push_filters(node.child)
        return _sink_filter(node.predicate, child)
    return _rebuild(node, [push_filters(c) for c in node.children()])


def _sink_filter(pred: Expr, child: P.PlanNode) -> P.PlanNode:
    """``Filter(pred, child)`` with the filter pushed below ``child`` when a
    rule applies; otherwise the filter stays put."""
    if isinstance(child, P.Project):
        # push only when every referenced column is a plain pass-through of
        # the same name — a computed column must be evaluated before the
        # predicate can run (no expression inlining: UDFs are not pure-cheap)
        defs = dict(child.columns)
        refs = pred.references()
        ok = all(name in defs and _is_passthrough(defs[name])
                 and defs[name].name == name for name in refs)
        if ok:
            return P.Project(_sink_filter(pred, child.child), child.columns)
    elif isinstance(child, P.Rename):
        inverse = {new: old for old, new in child.mapping.items()}
        # un-invertible mapping (two olds renamed to one new) cannot rewrite
        if len(inverse) == len(child.mapping):
            renamed = substitute_columns(pred, inverse)
            return P.Rename(_sink_filter(renamed, child.child), child.mapping)
    elif isinstance(child, P.Union):
        # only sink when every input provably produces the predicate's
        # columns — permissive concat null-fills asymmetric schemas, and the
        # pushed filter would otherwise die on a missing column
        refs = pred.references()
        cols = [output_columns(c) for c in child.inputs]
        if all(c is not None and refs <= set(c) for c in cols):
            return P.Union([_sink_filter(pred, c) for c in child.inputs])
    elif isinstance(child, P.DropNa):
        # row-wise deterministic: commuting keeps the same surviving rows.
        # (Sample/SplitSelect do NOT commute — their draws are positional,
        # so filtering first would select a different random row set.)
        inner = _sink_filter(pred, child.child)
        return _rebuild(child, [inner])
    # NOTE: a filter must NOT leapfrog another filter. The inner predicate may
    # be a guard for the outer one (filter(b != 0).filter(a/b > 2)): Arrow
    # kernels raise eagerly (divide by zero) instead of yielding null, so
    # reordering a conjunction is observably unsafe here. Stacked filters
    # still sink as a unit: the inner one sinks first (push_filters recurses
    # bottom-up), and the outer sinks through whatever node the inner left on
    # top, landing directly ABOVE it — order preserved.
    return P.Filter(child, pred)


# ==== projection pruning ===========================================================
def output_columns(node: P.PlanNode) -> Optional[List[str]]:
    """Statically-known output column names of a plan node, or None when the
    schema cannot be derived without running anything."""
    if isinstance(node, P.RangeScan):
        return [node.column]
    if isinstance(node, P.ParquetScan):
        return list(node.columns) if node.columns is not None else None
    if isinstance(node, P.CsvScan):
        names = (node.options or {}).get("column_names")
        return list(names) if names else None
    if isinstance(node, (P.InMemory, P.CachedScan)):
        if node.schema is not None:
            return list(pa.ipc.read_schema(pa.py_buffer(node.schema)).names)
        return None
    if isinstance(node, P.Project):
        return [name for name, _ in node.columns]
    if isinstance(node, P.Rename):
        inner = output_columns(node.child)
        if inner is None:
            return None
        return [node.mapping.get(c, c) for c in inner]
    if isinstance(node, P.GroupAgg):
        # pyarrow's group_by().aggregate() emits the key columns first
        return list(node.keys) + [out for _, _, out in node.aggs]
    if isinstance(node, P.WindowOp):
        inner = output_columns(node.child)
        if inner is None:
            return None
        return [c for c in inner if c != node.out_name] + [node.out_name]
    if isinstance(node, P.Join):
        left = output_columns(node.left)
        right = output_columns(node.right)
        if left is None or right is None:
            return None
        # Arrow's join keeps left columns then the right's non-key columns
        return list(left) + [c for c in right if c not in node.right_keys]
    if isinstance(node, P.Union):
        cols = [output_columns(c) for c in node.inputs]
        if any(c is None for c in cols):
            return None
        out: List[str] = []
        for cs in cols:  # permissive concat unions schemas by name, in order
            for c in cs:
                if c not in out:
                    out.append(c)
        return out
    children = node.children()
    if len(children) == 1:  # row-only ops pass the schema through
        return output_columns(children[0])
    return None


def _ordered_union(*lists) -> List[str]:
    out: List[str] = []
    for lst in lists:
        for c in lst:
            if c not in out:
                out.append(c)
    return out


def _narrow(child: P.PlanNode, required: List[str]) -> P.PlanNode:
    """Prune ``child`` to ``required`` columns: recurse with the requirement,
    then — if the child may still be wider — insert a pass-through prune
    ``Project`` so shuffles above it carry only what is needed."""
    if not required:
        return prune_columns(child, None)
    pruned = prune_columns(child, list(required))
    cols = output_columns(pruned)
    if cols is not None and list(cols) == list(required):
        return pruned  # already exactly the required set
    if cols is not None:
        # known schema: keep the child's own column order, require only what
        # exists there (callers pass supersets when a side's schema is mixed)
        keep = [c for c in cols if c in required]
        if len(keep) == len(cols):
            return pruned
        return P.Project(pruned, [(c, col(c)) for c in keep])
    return P.Project(pruned, [(c, col(c)) for c in required])


def prune_columns(node: P.PlanNode,
                  required: Optional[List[str]]) -> P.PlanNode:
    """Top-down required-column walk. ``required=None`` means "everything the
    node produces is needed" (the root, and any consumer we cannot analyze)."""
    # ---- leaves ----
    if isinstance(node, P.ParquetScan):
        if required is not None and node.columns is None:
            return P.ParquetScan(node.paths, columns=list(required))
        return node
    if isinstance(node, (P.CsvScan, P.InMemory, P.CachedScan, P.RangeScan)):
        # CSV cannot prune at the reader (byte-sliced parse); in-memory blocks
        # are already materialized. A post-read prune Project (inserted by
        # _narrow) handles both; nothing to do at the leaf itself.
        return node

    if isinstance(node, P.Project):
        columns = node.columns
        if required is not None:
            keep = [(n, e) for n, e in columns if n in required]
            # a projection must keep producing at least one column
            columns = keep if keep else columns[:1]
        child_req = _ordered_union(*[sorted(e.references())
                                     for _, e in columns])
        if not child_req:
            # all-literal projection: the child still supplies the ROW COUNT,
            # so it must not be pruned to zero columns
            return P.Project(prune_columns(node.child, None), columns)
        return P.Project(prune_columns(node.child, child_req), columns)

    if isinstance(node, P.Filter):
        if required is None:
            return P.Filter(prune_columns(node.child, None), node.predicate)
        child_req = _ordered_union(required, sorted(node.predicate.references()))
        return P.Filter(prune_columns(node.child, child_req), node.predicate)

    if isinstance(node, P.Rename):
        if required is None:
            return P.Rename(prune_columns(node.child, None), node.mapping)
        inverse = {new: old for old, new in node.mapping.items()}
        child_req = [inverse.get(c, c) for c in required]
        return P.Rename(prune_columns(node.child, child_req), node.mapping)

    if isinstance(node, P.DropNa):
        if required is None or node.subset is None:
            return P.DropNa(prune_columns(node.child, None), node.subset)
        child_req = _ordered_union(required, node.subset)
        return P.DropNa(prune_columns(node.child, child_req), node.subset)

    if isinstance(node, (P.Sample, P.SplitSelect, P.Limit, P.Repartition)):
        child = (prune_columns(node.child, list(required))
                 if required is not None else prune_columns(node.child, None))
        if isinstance(node, P.Repartition) and node.shuffle \
                and required is not None:
            # narrow BELOW the shuffle so the repartition moves fewer bytes
            child = _narrow_if_known_node(child, list(required))
        return _rebuild(node, [child])

    if isinstance(node, P.Sort):
        key_names = [k for k, _ in node.keys]
        if required is None:
            return P.Sort(prune_columns(node.child, None), node.keys)
        child_req = _ordered_union(required, key_names)
        return P.Sort(prune_columns(node.child, child_req), node.keys)

    if isinstance(node, P.Distinct):
        # output is the full surviving row: every child column is needed, plus
        # the dedupe keys must survive any pruning below
        return P.Distinct(prune_columns(node.child, None), node.subset)

    if isinstance(node, P.GroupAgg):
        # the aggregate's input set is exact regardless of what is required
        # above: keys + aggregated columns. This is the big shuffle narrowing.
        child_req = _ordered_union(node.keys, [c for c, _, _ in node.aggs])
        return P.GroupAgg(_narrow(node.child, child_req), node.keys, node.aggs)

    if isinstance(node, P.WindowOp):
        if required is None:
            return _rebuild(node, [prune_columns(node.child, None)])
        child_req = _ordered_union(
            [c for c in required if c != node.out_name],
            node.partition_keys, [k for k, _ in node.order_keys],
            [node.arg_col] if node.arg_col and node.arg_col != "*" else [])
        if isinstance(node.child, P.WindowOp) and \
                list(node.child.partition_keys) == list(node.partition_keys):
            # keep same-spec window chains ADJACENT: the engine collapses
            # them into one shuffle, and a prune Project in between would
            # split that back into N shuffles
            return _rebuild(node, [prune_columns(node.child, child_req)])
        return _rebuild(node, [_narrow(node.child, child_req)])

    if isinstance(node, P.Join):
        lcols = output_columns(node.left)
        rcols = output_columns(node.right)
        left, right = node.left, node.right
        if required is not None and lcols is not None:
            lreq = _ordered_union([c for c in lcols
                                   if c in required], node.keys)
            left = _narrow(left, lreq)
        else:
            left = prune_columns(left, None)
        if required is not None and rcols is not None:
            rreq = _ordered_union(node.right_keys,
                                  [c for c in rcols if c in required
                                   and c not in node.right_keys])
            # keep the right side's own order, keys included where they sit
            rreq = [c for c in rcols if c in rreq]
            right = _narrow(right, rreq)
        else:
            right = prune_columns(right, None)
        return P.Join(left, right, node.keys, node.right_keys, node.how)

    if isinstance(node, P.Union):
        if required is not None:
            cols = [output_columns(c) for c in node.inputs]
            # only prune when every input provably produces the required set —
            # permissive concat null-fills asymmetric schemas, and a prune
            # Project would turn that into a missing-column error
            if all(c is not None and set(required) <= set(c) for c in cols):
                return P.Union([_narrow(c, list(required))
                                for c in node.inputs])
        return P.Union([prune_columns(c, None) for c in node.inputs])

    return _rebuild(node, [prune_columns(c, None) for c in node.children()])


def _narrow_if_known_node(child: P.PlanNode,
                          required: List[str]) -> P.PlanNode:
    cols = output_columns(child)
    if cols is not None and not set(cols) <= set(required):
        keep = [c for c in cols if c in required]
        if keep:
            return P.Project(child, [(c, col(c)) for c in keep])
    return child


# ==== helpers ======================================================================
def _rebuild(node: P.PlanNode, children: List[P.PlanNode]) -> P.PlanNode:
    """A copy of ``node`` with its children replaced (dataclass-generic)."""
    if not children:
        return node
    if isinstance(node, P.Join):
        return replace(node, left=children[0], right=children[1])
    if isinstance(node, P.Union):
        return replace(node, inputs=list(children))
    return replace(node, child=children[0])
