"""Logical plan nodes for the lazy DataFrame.

A plan is a small immutable tree; the engine (:mod:`raydp_tpu.etl.engine`) compiles
it into partition tasks, fusing narrow operators into one task chain and breaking
stages at wide (shuffle) operators — the same stage/shuffle split Spark performs on
the reference's DataFrames before they ever reach RayDP's conversion layer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from raydp_tpu.etl.expressions import Expr
from raydp_tpu.runtime.object_store import ObjectRef


class PlanNode:
    def children(self) -> List["PlanNode"]:
        return []


# ==== leaves =======================================================================
@dataclass
class RangeScan(PlanNode):
    start: int
    stop: int
    step: int = 1
    num_partitions: int = 1
    column: str = "id"


@dataclass
class CsvScan(PlanNode):
    paths: List[str]
    num_partitions: Optional[int] = None
    options: Optional[dict] = None


@dataclass
class ParquetScan(PlanNode):
    paths: List[str]
    columns: Optional[List[str]] = None


@dataclass
class InMemory(PlanNode):
    """Partitions already in the object store."""

    refs: List[ObjectRef]
    schema: Optional[bytes] = None


@dataclass
class CachedScan(PlanNode):
    """A persisted frame: blocks cached on executors with lineage recipes.

    Parity: the persisted+pinned Arrow-batch RDD of ``prepareRecoverableRDD``
    (ObjectStoreWriter.scala:164-204).
    """

    frame_id: str
    cache_keys: List[str]
    executors: List[str]           # preferred executor actor-name per partition
    recover_tasks: List[bytes]     # cloudpickled lineage Task per partition
    schema: Optional[bytes] = None
    # shuffle intermediates the lineage recipes depend on, pinned until release
    # (parity: the recoverableRDDs GC pin, ObjectStoreWriter.scala:175-177)
    pinned_refs: List[ObjectRef] = field(default_factory=list)


# ==== unary ========================================================================
@dataclass
class Project(PlanNode):
    child: PlanNode
    columns: List[Tuple[str, Expr]]

    def children(self):
        return [self.child]


@dataclass
class Filter(PlanNode):
    child: PlanNode
    predicate: Expr

    def children(self):
        return [self.child]


@dataclass
class DropNa(PlanNode):
    child: PlanNode
    subset: Optional[List[str]] = None

    def children(self):
        return [self.child]


@dataclass
class Sample(PlanNode):
    child: PlanNode
    fraction: float
    seed: Optional[int] = None

    def children(self):
        return [self.child]


@dataclass
class SplitSelect(PlanNode):
    child: PlanNode
    lo: float
    hi: float
    seed: int

    def children(self):
        return [self.child]


@dataclass
class Limit(PlanNode):
    child: PlanNode
    n: int

    def children(self):
        return [self.child]


@dataclass
class Rename(PlanNode):
    child: PlanNode
    mapping: Dict[str, str]

    def children(self):
        return [self.child]


@dataclass
class Repartition(PlanNode):
    child: PlanNode
    num_partitions: int
    shuffle: bool = True

    def children(self):
        return [self.child]


@dataclass
class GroupAgg(PlanNode):
    child: PlanNode
    keys: List[str]
    aggs: List[Tuple[str, str, str]]  # (col, fn, out_name)

    def children(self):
        return [self.child]


@dataclass
class Sort(PlanNode):
    child: PlanNode
    keys: List[Tuple[str, str]]

    def children(self):
        return [self.child]


@dataclass
class WindowOp(PlanNode):
    """One window-function column: hash-shuffle by ``partition_keys``, sort
    each bucket by (partition, order) keys, compute ``fn`` executor-side.
    No partition keys → single-partition evaluation (Spark's "No Partition
    Defined" path)."""

    child: PlanNode
    partition_keys: List[str]
    order_keys: List[Tuple[str, str]]
    out_name: str
    fn: str
    arg_col: Optional[str] = None
    offset: int = 1
    default: object = None

    def children(self):
        return [self.child]


@dataclass
class Distinct(PlanNode):
    """Row dedupe over ``subset`` (None → all columns): hash-shuffle on the
    key columns, then local first-row-per-key dedupe in each bucket."""

    child: PlanNode
    subset: Optional[List[str]] = None

    def children(self):
        return [self.child]


# ==== n-ary ========================================================================
@dataclass
class Join(PlanNode):
    left: PlanNode
    right: PlanNode
    keys: List[str]
    right_keys: List[str]
    how: str = "inner"

    def children(self):
        return [self.left, self.right]


@dataclass
class Union(PlanNode):
    inputs: List[PlanNode] = field(default_factory=list)

    def children(self):
        return list(self.inputs)
