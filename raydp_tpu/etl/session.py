"""The ETL Session: the SparkSession analogue returned by ``raydp_tpu.init``.

Bring-up parity (call stack §3.1 of SURVEY.md): create the master actor, then the
executor gang — each an actor with ``{CPU, memory}`` resources, scheduled into the
session's placement-group bundles round-robin (RayAppMaster.scala:290-303), with
``max_restarts=-1`` (RayExecutorUtils.java:58). Teardown order parity:
``stop(cleanup_data=False)`` keeps the master actor (and the objects it owns)
alive so converted datasets survive the ETL engine, exactly like
``RayDPSparkMaster.stop(cleanup_data)`` (ray_cluster_master.py:236-247).
"""

from __future__ import annotations

import threading
import uuid
from typing import Dict, List, Optional, Union

import pandas as pd
import pyarrow as pa

from raydp_tpu import config as cfg
from raydp_tpu.config import Config
from raydp_tpu.etl import plan as P
from raydp_tpu.etl.engine import Engine, ExecutorPool
from raydp_tpu.etl.frame import DataFrame
from raydp_tpu.log import get_logger
from raydp_tpu.runtime import get_runtime
from raydp_tpu.runtime.actor import ActorHandle

logger = get_logger("etl.session")


class Session:
    def __init__(self, app_name: str, num_executors: int, executor_cores: int,
                 executor_memory: int, config: Optional[Config] = None,
                 placement_group=None):
        self.app_name = app_name
        self.num_executors = num_executors
        self.executor_cores = executor_cores
        self.executor_memory = executor_memory
        self.config = config or Config()
        self.placement_group = placement_group
        self.master_name = f"{app_name}_MASTER"
        self.master: Optional[ActorHandle] = None
        self.cluster = None  # EtlCluster after start()
        self.engine: Optional[Engine] = None
        self._cached_frames: Dict[str, P.CachedScan] = {}
        self._stopped = False
        self._autoscaler = None  # PoolAutoscaler once autoscale() is asked for
        #: serializes EVERY scale operation — manual request_total_executors,
        #: retire_executor, and the autoscaler's grow/shrink — so two racing
        #: ops can never read cluster.workers[-1] for each other's spawn or
        #: pick the same drain victim. Reentrant: request_total_executors
        #: holds it around the per-executor ops that also take it.
        self._scale_lock = threading.RLock()

    @property
    def executors(self) -> List[ActorHandle]:
        return self.cluster.workers if self.cluster is not None else []

    # ---- lifecycle ----------------------------------------------------------
    def start(self) -> "Session":
        """Bring-up through the generic :class:`~raydp_tpu.cluster.Cluster`
        surface (reference services.py:22-90): the built-in engine is an
        :class:`EtlCluster`; an external engine subclasses ``Cluster`` and
        rides the same lifecycle."""
        from raydp_tpu.cluster import EtlCluster

        master_resources = self.config.resource_map(
            cfg.MASTER_ACTOR_RESOURCE_PREFIX)
        self.cluster = EtlCluster(self.app_name, master_resources)
        self.master = self.cluster.master.handle

        for _ in range(self.num_executors):
            self._launch_executor(block=False)
        for h in self.executors:
            h.wait_ready()

        pool = ExecutorPool(self.executors,
                            hosts_by_name=self._executor_hosts())
        self.engine = Engine(
            pool,
            shuffle_partitions=self.config.get_int(cfg.SHUFFLE_PARTITIONS_KEY, 8),
            owner=self.master_name,
        )
        logger.info("session %s started: master + %d executors",
                    self.app_name, len(self.executors))
        return self

    def _launch_executor(self, block: bool = True) -> ActorHandle:
        executor_resources = {"CPU": float(self.executor_cores),
                              "memory": float(self.executor_memory)}
        executor_resources.update(
            self.config.resource_map(cfg.EXECUTOR_ACTOR_RESOURCE_PREFIX))
        max_restarts = self.config.get_int(cfg.EXECUTOR_RESTARTS_KEY, -1)
        pg_id, bundle = None, None
        if self.placement_group is not None:
            pg_id = self.placement_group.group_id
            bundle = (self.cluster._worker_index
                      % len(self.placement_group.bundles))
        self.cluster.add_worker(
            executor_resources,
            max_restarts=max_restarts,
            max_concurrency=max(2, self.executor_cores),
            placement_group=pg_id,
            bundle_index=bundle,
            block=block,
        )
        return self.cluster.workers[-1]

    def _executor_hosts(self) -> Dict[str, str]:
        """Executor name → data-plane host id, for locality-aware scheduling
        of ref-reading tasks (a no-op when everything shares one machine)."""
        hosts: Dict[str, str] = {}
        try:
            rt = get_runtime()
            for h in self.executors:
                rec = rt.records.get(h.actor_id)
                if rec is not None and h.name:
                    hosts[h.name] = rt.store_host_of_node(rec.node_id)
        except Exception:
            pass
        return hosts

    # ---- dynamic allocation / elastic pool ----------------------------------
    def request_total_executors(self, total: int) -> int:
        """Scale the executor gang to ``total`` live executors.

        Parity: Spark dynamic allocation routed to actor create/kill —
        ``doRequestTotalExecutors`` / ``doKillExecutors``
        (RayCoarseGrainedSchedulerBackend.scala:278-301, RayAppMaster.scala:
        173-190, 275-288). Shrinking DRAINS the newest executors gracefully
        (:meth:`retire_executor`: out of rotation, in-flight work finishes,
        cached blocks re-home or abandon to lineage, then the process is
        reaped); growing spawns through the ordinary launch path and admits
        each executor into the live pool once ready."""
        if total < 1:
            raise ValueError("need at least one executor")
        from raydp_tpu import knobs
        with self._scale_lock:
            while len(self.executors) > total:
                victim = self._shrink_candidate()
                if victim is None:
                    break
                self.retire_executor(victim)
            # grow in PARALLEL: launch every missing executor non-blocking
            # first, then absorb their warm-ups concurrently through the
            # readiness probes (serial spawn+wait would pay the jax import
            # storm once per executor)
            need = total - len(self.executors)
            launched = [self._launch_executor(block=False)
                        for _ in range(need)]
            wait_s = float(knobs.get("RDT_EXECUTOR_WAIT_S"))
            ready, failures = [], []
            for h in launched:
                try:
                    h.wait_ready(timeout=wait_s)
                    ready.append(h)
                except Exception as e:  # noqa: BLE001 - reaped + re-raised
                    # a half-started worker is reaped, never admitted — and
                    # never left as an invisible member a later scale call
                    # would count but the scheduler never dispatches to
                    failures.append((h, e))
                    self.cluster.remove_worker(h)
            hosts = self._executor_hosts()  # once, not per admission
            if self.engine is not None:
                for h in ready:
                    self.engine.pool.add_executor(h,
                                                  host_id=hosts.get(h.name))
            if failures:
                raise RuntimeError(
                    f"{len(failures)}/{len(launched)} executors never "
                    f"became ready during scale-up (first: "
                    f"{failures[0][0].name})") from failures[0][1]
        logger.info("session %s scaled to %d executors", self.app_name,
                    len(self.executors))
        return len(self.executors)

    def retire_executor(self, name: str) -> int:
        """Gracefully drain executor ``name`` out of the session: scheduler
        rotation stops, in-flight tasks finish (or re-queue through
        retry/recovery), cached frame partitions re-home onto survivors
        (``RDT_DRAIN_REHOME``) or abandon to their lineage recipes, and only
        then is the process reaped (through its node agent on remote
        nodes). Returns the new pool size."""
        if self.engine is None:
            raise RuntimeError("session is not started")
        with self._scale_lock:
            out = self.engine.retire_executor(
                name, rehome=self._rehome_blocks,
                reap=lambda h: self.cluster.remove_worker(h))
        logger.info("session %s retired executor %s (pool %d, quiesced=%s, "
                    "rehomed=%d)", self.app_name, name, out["pool_size"],
                    out["quiesced"], out["rehomed"])
        return out["pool_size"]

    def autoscale(self, min_size: Optional[int] = None,
                  max_size: Optional[int] = None):
        """Start (or return) the pool's autoscale controller
        (:class:`~raydp_tpu.etl.autoscale.PoolAutoscaler`): grows under
        sustained queued demand up to ``max_size`` (default
        ``RDT_POOL_MAX``), drains idle executors down to ``min_size``
        (default ``RDT_POOL_MIN``), with hysteresis. Stopped by
        :meth:`stop`."""
        if self.engine is None:
            raise RuntimeError("session is not started")
        if self._autoscaler is None:
            from raydp_tpu.etl.autoscale import PoolAutoscaler
            self._autoscaler = PoolAutoscaler(
                self, min_size=min_size, max_size=max_size).start()
        elif min_size is not None or max_size is not None:
            # a second call adjusts the LIVE controller's bounds (they are
            # re-read every tick) instead of silently keeping the old caps
            self._autoscaler.set_bounds(min_size=min_size, max_size=max_size)
        return self._autoscaler

    def _grow_executor(self):
        """Spawn one executor and admit it to the live pool once the
        ``RDT_EXECUTOR_WAIT_S`` readiness probe absorbs its warm-up; None
        when the spawn or the probe fails (the half-started worker is
        reaped, never admitted)."""
        from raydp_tpu import knobs
        with self._scale_lock:
            try:
                h = self._launch_executor(block=False)
            except Exception:
                logger.warning("executor spawn failed", exc_info=True)
                return None
            try:
                h.wait_ready(timeout=float(knobs.get("RDT_EXECUTOR_WAIT_S")))
            except Exception:
                logger.warning("executor %s never became ready; reaping it",
                               h.name, exc_info=True)
                self.cluster.remove_worker(h)
                return None
            if self.engine is not None:
                host = self._executor_hosts().get(h.name)
                self.engine.pool.add_executor(h, host_id=host)
            return h

    def _shrink_candidate(self) -> Optional[str]:
        """The newest non-draining executor — the reverse of spawn order,
        like Spark's kill-newest dynamic allocation; None when only one
        would remain."""
        if self.engine is None:
            return None
        draining = set(self.engine.pool.draining_names())
        names = [h.name for h in self.executors
                 if h.name and h.name not in draining]
        return names[-1] if len(names) > 1 else None

    def _rehome_blocks(self, name: str) -> int:
        """Drain re-homing: every cached frame partition homed on the
        retiring executor is rebuilt on a survivor from its lineage recipe
        (``warm_block`` reads the frame's pinned store blobs through the
        ranged-fetch plane) and the frame's preferred-executor map is
        repointed. Best-effort per block: a block that fails to re-home is
        simply abandoned — the next read rebuilds it via ``CachedSource``
        recovery. Returns the number of blocks re-homed."""
        survivors = [h for h in self.executors if h.name and h.name != name]
        if not survivors:
            return 0
        moved = 0
        rr = 0
        for cached in self._cached_frames.values():
            for i, owner in enumerate(cached.executors):
                if owner != name:
                    continue
                target = survivors[rr % len(survivors)]
                rr += 1
                try:
                    target.call("warm_block", cached.cache_keys[i],
                                cached.recover_tasks[i], timeout=120.0)
                    cached.executors[i] = target.name
                    moved += 1
                except Exception:
                    logger.warning(
                        "re-home of block %s onto %s failed; it will "
                        "rebuild on read", cached.cache_keys[i], target.name,
                        exc_info=True)
        return moved

    def stop(self, cleanup_data: bool = True) -> None:
        """Idempotent; a later ``stop(cleanup_data=True)`` after a keep-data stop
        still reaps the master (parity: ray_cluster_master.py:236-247)."""
        if not self._stopped:
            self._stopped = True
            if self._autoscaler is not None:
                self._autoscaler.stop()
                self._autoscaler = None
            if self.cluster is not None:
                self.cluster.stop(cleanup_master=False)
        if cleanup_data and self.master is not None:
            if self.cluster is not None:
                self.cluster.stop(cleanup_master=True)
            else:
                try:
                    self.master.kill(no_restart=True)
                except Exception:
                    pass
            self.master = None
        logger.info("session %s stopped (cleanup_data=%s)",
                    self.app_name, cleanup_data)

    # ---- frame constructors -------------------------------------------------
    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    def range(self, start: int, stop: Optional[int] = None, step: int = 1,
              num_partitions: Optional[int] = None) -> DataFrame:
        if stop is None:
            start, stop = 0, start
        n = num_partitions or max(1, min(len(self.executors),
                                         (stop - start) // 1000 + 1))
        return DataFrame(self, P.RangeScan(start, stop, step, n))

    def createDataFrame(
        self,
        data: Union[pd.DataFrame, pa.Table, List[dict]],
        num_partitions: Optional[int] = None,
    ) -> DataFrame:
        if isinstance(data, list):
            table = pa.Table.from_pylist(data)
        elif isinstance(data, pd.DataFrame):
            table = pa.Table.from_pandas(data, preserve_index=False)
        elif isinstance(data, pa.Table):
            table = data
        else:
            raise TypeError(f"cannot create DataFrame from {type(data)}")
        n = num_partitions or max(1, min(len(self.executors),
                                         table.num_rows or 1))
        from raydp_tpu.runtime.object_store import get_client
        client = get_client()
        rows = table.num_rows
        per = max(1, -(-rows // n))
        chunks = [table.slice(i, per) for i in range(0, max(rows, 1), per)]
        # one batched seal for all N chunks instead of one RPC each
        refs = client.put_arrow_many(chunks, owner=self.master_name)
        schema = table.schema.serialize().to_pybytes()
        return DataFrame(self, P.InMemory(refs, schema), schema=table.schema)

    create_frame = createDataFrame

    # ---- cached-frame registry (recoverable conversions) --------------------
    def register_cached(self, frame_id: str, cached: P.CachedScan) -> None:
        self._cached_frames[frame_id] = cached

    def release_cached(self, frame_id: str) -> None:
        """Drop a persisted frame's blocks (parity: ``releaseRecoverableRDD``,
        ObjectStoreWriter.scala:211-216)."""
        cached = self._cached_frames.pop(frame_id, None)
        if cached is None:
            return
        for h in self.executors:
            try:
                h.drop_block_prefix(f"block_{frame_id}_")
            except Exception:
                pass
        if cached.pinned_refs:
            from raydp_tpu.runtime.object_store import get_client
            try:
                get_client().free(cached.pinned_refs)
            except Exception:
                pass

    def cached_frames(self) -> List[str]:
        return list(self._cached_frames)


class DataFrameReader:
    def __init__(self, session: Session):
        self._session = session
        self._options: Dict[str, str] = {}

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key] = value
        return self

    def format(self, fmt: str) -> "DataFrameReader":
        self._format = fmt
        return self

    def load(self, path: str) -> DataFrame:
        fmt = getattr(self, "_format", "parquet")
        return getattr(self, fmt)(path)

    def csv(self, path: Union[str, List[str]],
            num_partitions: Optional[int] = None,
            options: Optional[dict] = None) -> DataFrame:
        """``options``: ``delimiter`` (default ','), ``column_names`` (for
        headerless files, e.g. Criteo TSV), ``convert`` (pyarrow
        ConvertOptions kwargs)."""
        paths = _expand_paths(path, (".csv", ".tsv", ".txt"))
        return DataFrame(self._session,
                         P.CsvScan(paths, num_partitions=num_partitions,
                                   options=options))

    def parquet(self, path: Union[str, List[str]],
                columns: Optional[List[str]] = None) -> DataFrame:
        """Read parquet; silently skips non-parquet files in a directory
        (parity: reference ``read_spark_parquet`` filtering, tests/test_read_parquet.py)."""
        paths = _expand_paths(path, (".parquet", ".pq"))
        return DataFrame(self._session, P.ParquetScan(paths, columns=columns))


def _expand_paths(path: Union[str, List[str]], suffixes) -> List[str]:
    import glob
    import os
    if isinstance(path, list):
        candidates = path
    elif os.path.isdir(path):
        candidates = sorted(glob.glob(os.path.join(path, "*")))
        candidates = [p for p in candidates
                      if p.endswith(suffixes) or "part-" in os.path.basename(p)]
    else:
        candidates = sorted(glob.glob(path)) or [path]
    if not candidates:
        raise FileNotFoundError(f"no input files match {path!r}")
    for p in candidates:
        if p.startswith("file://"):
            raise ValueError("strip the file:// prefix; local paths only")
    return candidates
