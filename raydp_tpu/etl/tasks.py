"""Task model: the physical unit of ETL work.

A :class:`Task` is a self-contained recipe for one partition — a source step plus a
chain of transform steps — finished by an output mode (return a store ref, cache as
a named block, hash-shuffle into buckets, collect, or count). Tasks being
self-contained *is* the lineage mechanism: any executor can recompute any lost
partition from the recipe, the property the reference gets from Spark RDD lineage +
its recache RPC (ObjectStoreWriter.scala:164-204 persists and pins the Arrow RDD;
RayDPExecutor.scala:289-310 re-caches lost blocks through the driver agent).

Everything here must stay picklable and runnable inside an executor actor process.
"""

from __future__ import annotations

import io
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.csv as pacsv
import pyarrow.parquet as pq

from raydp_tpu import faults
from raydp_tpu.etl.expressions import Expr, evaluate_to_array
from raydp_tpu.runtime.object_store import KIND_RAW, ObjectLostError, \
    ObjectRef, ShuffleStreamAborted, get_client

# -- output modes -------------------------------------------------------------------
RETURN_REF = "return_ref"
CACHE = "cache"
SHUFFLE = "shuffle"
COLLECT = "collect"
ROWCOUNT = "rowcount"


class Step:
    def run(self, table: pa.Table) -> pa.Table:
        raise NotImplementedError


# ==== sources ======================================================================
@dataclass
class RangeSource(Step):
    start: int
    stop: int
    step: int = 1
    column: str = "id"

    def load(self) -> pa.Table:
        return pa.table({self.column: np.arange(self.start, self.stop, self.step)})


@dataclass
class CsvSliceSource(Step):
    """Byte-range slice of a CSV file.

    ``start``/``end`` are *approximate* offsets: the reader skips to the first full
    line at/after ``start`` and reads through the line spanning ``end``. The header
    is re-attached so every slice parses independently — this is how one big CSV
    becomes N parallel partitions without a pre-pass.
    """

    path: str
    start: int
    end: int
    header: bytes
    parse_options: Optional[dict] = None

    def load(self) -> pa.Table:
        with open(self.path, "rb") as f:
            if self.start > 0:
                f.seek(self.start - 1)
                f.readline()  # consume partial line (or the newline ending it)
            pos = f.tell()
            if pos >= self.end and self.start > 0:
                data = b""
            else:
                data = f.read(self.end - pos)
                # extend through the end of the line spanning `end`
                if not data.endswith(b"\n"):
                    data += f.readline()
        payload = self.header + data if self.start > 0 else data
        opts = self.parse_options or {}
        names = opts.get("column_names")  # headerless files (e.g. Criteo TSV)
        parse = pacsv.ParseOptions(delimiter=opts.get("delimiter", ","))
        read = pacsv.ReadOptions(column_names=names) if names \
            else pacsv.ReadOptions()
        convert = pacsv.ConvertOptions(**opts.get("convert", {}))
        if not payload.strip():
            if names:
                # null-typed empties promote to any sibling slice's inferred
                # type under permissive concat (string would not)
                return pa.table({n: pa.array([], pa.null()) for n in names})
            return pacsv.read_csv(io.BytesIO(self.header),
                                  parse_options=parse)[:0]
        return pacsv.read_csv(io.BytesIO(payload), read_options=read,
                              parse_options=parse, convert_options=convert)


@dataclass
class ParquetSource(Step):
    path: str
    row_groups: Optional[List[int]] = None
    columns: Optional[List[str]] = None

    def load(self) -> pa.Table:
        f = pq.ParquetFile(self.path)
        if self.row_groups is None:
            return f.read(columns=self.columns)
        return f.read_row_groups(self.row_groups, columns=self.columns)


def _ranged_fetch_fault(client, parts: List[Tuple["ObjectRef", int, int]],
                        total: int) -> None:
    """The ``shuffle.fetch`` fault site, shared by every ranged reader
    (barrier :class:`RangeRefSource` and streamed
    :class:`StreamingRangeSource` — the chaos matrix compares the two
    directly, so the drop/delay semantics must never diverge): ``drop``
    frees part ``bucket=N``'s backing blob and surfaces the typed loss (the
    store-host-died model); generic actions honor ``ms_per_mb=`` against
    the bytes this read moves."""
    rule = faults.check("shuffle.fetch",
                        key=parts[0][0].id if parts else "")
    if rule is None:
        return
    if rule.action == "drop" and parts:
        victim = parts[rule.bucket % len(parts)][0]
        try:
            client.free([victim])
        except Exception:
            pass
        raise ObjectLostError(victim.id, "fault-injected fetch drop")
    faults.apply(rule, "shuffle.fetch", nbytes=total)


def concat_or_empty(tables: List[pa.Table],
                    schema: Optional[bytes]) -> pa.Table:
    """Concat bucket/block tables; an empty input list falls back to the
    serialized schema (shared by :class:`ArrowRefSource` and
    :class:`RangeRefSource` so both sources agree on the 0-ref case)."""
    if not tables:
        if schema is not None:
            return pa.ipc.read_schema(pa.py_buffer(schema)).empty_table()
        raise ValueError("ref source with no refs and no schema")
    return pa.concat_tables(tables, promote_options="permissive")


@dataclass
class ArrowRefSource(Step):  # carries-refs: refs
    """Concatenate Arrow tables from object-store refs (zero-copy reads)."""

    refs: List[ObjectRef]
    schema: Optional[bytes] = None  # serialized schema for the 0-ref case

    def load(self) -> pa.Table:
        client = get_client()
        return concat_or_empty([client.get(r) for r in self.refs],
                               self.schema)


@dataclass
class RangeRefSource(Step):  # carries-refs: parts
    """Byte-range reads of store blobs: ``(ref, offset, size)`` triples, each
    range an independent Arrow IPC stream — the reduce-side reader of the
    consolidated shuffle path (a map task's B buckets live back-to-back in
    ONE blob; each reduce task decodes only its bucket's slice). Sibling of
    :class:`SlicedRefSource`, but byte-range rather than row-range. A
    full-blob part ``(ref, 0, ref.size)`` reads a legacy single-bucket blob
    identically, so mixed stages decode fine.

    The fetch is batched: one ``lookup_batch`` for all refs (memo hits are
    free), local slices zero-copy out of the attached segment, and one
    ``store_fetch_ranges`` RPC per remote payload host (threaded across
    hosts) — O(hosts) round-trips per reduce task instead of O(maps)."""

    parts: List[Tuple[ObjectRef, int, int]]
    schema: Optional[bytes] = None  # serialized schema for the 0-part case

    def load(self) -> pa.Table:
        from raydp_tpu import profiler

        client = get_client()
        total = sum(size for _, _, size in self.parts)
        # the ranged-read fault site (shared with the streamed reader):
        # ``drop`` removes one part's backing blob and surfaces the typed
        # loss — the store-host-died model for consolidated reduce reads,
        # skew-split portions, and broadcast replicas, all of which must
        # route into lineage recovery
        _ranged_fetch_fault(client, self.parts, total)
        with profiler.trace("shuffle:fetch", "etl", parts=len(self.parts),
                            bytes=total):
            bufs = client.get_range_buffers(self.parts)
        tables = [pa.ipc.open_stream(pa.py_buffer(b)).read_all()
                  for b in bufs]
        return concat_or_empty(tables, self.schema)


@dataclass
class StreamingRangeSource(Step):
    """The pipelined-shuffle reduce reader: consumes seal notifications from
    the store server's per-stage stream ledger and accumulates partial
    fetches — each map task's portion of this bucket is fetched + decoded as
    soon as that map SEALS, overlapping reduce-side work with the map tail
    instead of waiting for the stage barrier (doc/etl.md "Pipelined
    shuffle"). Decoded portions concatenate in ``map_id`` order, so the
    bucket's row order is identical to the barrier-mode
    :class:`RangeRefSource` read of the same stage.

    Generations: a lineage-regenerated producer re-seals under the same
    ``map_id`` with ``gen+1`` and a fresh ``(ref, off, size)``. A portion
    already decoded from the older generation is kept — reruns are
    byte-identical — but a fetch failing :class:`ObjectLostError` on a stale
    range first re-checks the ledger for a newer generation (another reducer
    may have triggered recovery already) and refetches in place; with no
    newer generation the loss rides the existing lineage-recovery path (the
    task fails typed, the engine regenerates + re-seals, and the resubmitted
    task reads the fresh generation).

    An aborted/closed stream raises :class:`ShuffleStreamAborted` (no-retry:
    replaying the consumer replays the abort), carrying the map stage's
    error when there was one.

    After ``load`` the instance carries ``stream_stats``:
    ``overlap_s`` (seconds spent fetching/decoding before the final seal
    notification arrived — the measured map/reduce overlap),
    ``first_fetch_ts`` (wall-clock of the first fetch), and ``rounds``."""

    stage_key: str
    bucket: int
    num_maps: int
    schema: Optional[bytes] = None
    poll_timeout_s: float = 10.0

    def load(self) -> pa.Table:
        from raydp_tpu import profiler

        client = get_client()
        tables: Dict[int, pa.Table] = {}
        gens: Dict[int, int] = {}
        stats = {"overlap_s": 0.0, "first_fetch_ts": None, "rounds": 0}
        self.stream_stats = stats
        while len(tables) < self.num_maps:
            resp = client.stream_poll(self.stage_key, self.bucket, gens,
                                      self.poll_timeout_s)
            if resp.get("aborted"):
                raise ShuffleStreamAborted(
                    f"shuffle stream {self.stage_key} aborted: "
                    f"{resp['aborted']}")
            parts, metas = [], []
            for map_id, gen, ref_id, blob_size, off, size in \
                    resp.get("events") or []:
                if gens.get(map_id, 0) >= gen:
                    continue
                if map_id in tables:
                    # a re-sealed generation of a portion we already hold:
                    # reruns are byte-identical, so keep ours — just adopt
                    # the generation (or the superseded event would come
                    # back on every poll)
                    gens[map_id] = int(gen)
                    continue
                parts.append((ObjectRef(id=ref_id, size=blob_size,
                                        kind=KIND_RAW), int(off), int(size)))
                metas.append((int(map_id), int(gen)))
            if not parts:
                continue
            total = sum(size for _, _, size in parts)
            # does this batch complete the stage? If not, the map tail is
            # still running and the fetch+decode below is measured OVERLAP
            tail_live = len(set(tables) | {m for m, _ in metas}) \
                < self.num_maps
            t0 = time.perf_counter()
            if stats["first_fetch_ts"] is None:
                stats["first_fetch_ts"] = time.time()
            # the fault site sits INSIDE the timed window: an injected
            # per-MiB delay models fetch cost, so it must count as overlap
            _ranged_fetch_fault(client, parts, total)
            try:
                with profiler.trace("shuffle:fetch", "etl",
                                    parts=len(parts), bytes=total,
                                    streamed=True):
                    bufs = client.get_range_buffers(parts)
            except ObjectLostError as e:
                # stale range: a regenerated producer may ALREADY have
                # re-sealed a newer generation — discard this batch (gens
                # uncommitted, so every portion reappears in the next poll)
                # and refetch; no newer generation means the loss is fresh,
                # so surface it into lineage recovery
                probe = client.stream_poll(self.stage_key, self.bucket,
                                           gens, timeout_s=0)
                if probe.get("aborted"):
                    # the map stage died and its sealed blobs were freed —
                    # THAT is why the range is gone. Fail fast with the
                    # abort's real cause instead of sending the typed loss
                    # into a pointless lineage round against a dead stage
                    raise ShuffleStreamAborted(
                        f"shuffle stream {self.stage_key} aborted: "
                        f"{probe['aborted']}") from e
                newer = {m for m, g, *_ in probe.get("events") or []
                         if g > dict(metas).get(m, g)}
                if not newer:
                    raise e
                continue
            for (map_id, gen), buf in zip(metas, bufs):
                tables[map_id] = pa.ipc.open_stream(
                    pa.py_buffer(buf)).read_all()
                gens[map_id] = gen
            dur = time.perf_counter() - t0
            stats["rounds"] += 1
            if tail_live:
                stats["overlap_s"] += dur
        return concat_or_empty([tables[i] for i in range(self.num_maps)],
                               self.schema)


@dataclass
class SlicedRefSource(Step):  # carries-refs: parts
    """Row-range slices of store refs: ``(ref, offset, length)`` triples.

    Used by the balanced sharding path (``divide_blocks``) where a rank takes only
    part of a block (reference utils.py:149-222 returns per-block sample counts).
    """

    parts: List[Tuple[ObjectRef, int, int]]

    def load(self) -> pa.Table:
        client = get_client()
        tables = []
        for ref, offset, length in self.parts:
            t = client.get(ref)
            tables.append(t.slice(offset, length))
        return pa.concat_tables(tables, promote_options="permissive")


@dataclass
class CachedSource(Step):  # carries-refs: recover
    """Executor-local cached block, with a recovery recipe on miss.

    Parity: BlockManager read in ``getRDDPartition`` with recache-then-retry on
    miss (RayDPExecutor.scala:312-355). ``recover`` is the lineage task that
    recomputes the partition from first principles.
    """

    cache_key: str
    recover: Optional["Task"] = None

    def load(self) -> pa.Table:
        from raydp_tpu.etl.executor import current_block_cache
        cache = current_block_cache()
        table = cache.get(self.cache_key)
        if table is None:
            if self.recover is None:
                raise KeyError(f"block {self.cache_key} lost and no lineage recipe")
            table = run_task_body(self.recover)
            cache.put(self.cache_key, table)
        return table


# ==== transforms ===================================================================
@dataclass
class ProjectStep(Step):
    """Output exactly these (name, expr) columns — select / withColumn / drop."""

    columns: List[Tuple[str, Expr]]

    def run(self, table: pa.Table) -> pa.Table:
        arrays, names = [], []
        for name, expr in self.columns:
            arrays.append(evaluate_to_array(expr, table))
            names.append(name)
        return pa.table(dict(zip(names, arrays)))


@dataclass
class FilterStep(Step):
    predicate: Expr

    def run(self, table: pa.Table) -> pa.Table:
        mask = evaluate_to_array(self.predicate, table)
        return table.filter(pc.fill_null(mask, False))


@dataclass
class DropNaStep(Step):
    subset: Optional[List[str]] = None

    def run(self, table: pa.Table) -> pa.Table:
        cols = self.subset or table.column_names
        mask = None
        for c in cols:
            valid = pc.is_valid(table.column(c))
            mask = valid if mask is None else pc.and_(mask, valid)
        return table.filter(mask) if mask is not None else table


@dataclass
class SampleStep(Step):
    fraction: float
    seed: Optional[int] = None
    partition_index: int = 0

    def run(self, table: pa.Table) -> pa.Table:
        seed = (self.seed if self.seed is not None else 0) + self.partition_index
        rng = np.random.RandomState(seed)
        mask = rng.random_sample(table.num_rows) < self.fraction
        return table.filter(pa.array(mask))


@dataclass
class SplitSelectStep(Step):
    """Deterministic random split: keep rows whose draw lands in [lo, hi).

    Powers ``random_split`` (reference utils.py:67-90): every sibling frame uses
    the same seed with a different band, so splits are disjoint and exhaustive.
    """

    lo: float
    hi: float
    seed: int
    partition_index: int = 0

    def run(self, table: pa.Table) -> pa.Table:
        rng = np.random.RandomState(self.seed + self.partition_index)
        draws = rng.random_sample(table.num_rows)
        return table.filter(pa.array((draws >= self.lo) & (draws < self.hi)))


@dataclass
class LocalShuffleStep(Step):
    """Uniform random permutation of the rows of one partition — the reduce
    side of the distributed ``random_shuffle`` (map side: :func:`random_buckets`).
    Runs on the executors; the driver never sees row data."""

    seed: int

    def run(self, table: pa.Table) -> pa.Table:
        if table.num_rows <= 1:
            return table
        rng = np.random.RandomState(self.seed)
        return table.take(pa.array(rng.permutation(table.num_rows)))


@dataclass
class LimitStep(Step):
    n: int

    def run(self, table: pa.Table) -> pa.Table:
        return table.slice(0, self.n)


@dataclass
class DistinctStep(Step):
    """First row per key (``subset``; None → all columns). Globally correct
    when rows were hash-shuffled by the same keys: equal keys share a bucket.
    Keeps original row order of the surviving first occurrences
    (parity surface: Spark ``distinct``/``dropDuplicates``,
    reference examples/data_process.py)."""

    subset: Optional[List[str]] = None

    def run(self, table: pa.Table) -> pa.Table:
        keys = self.subset or table.column_names
        if table.num_rows == 0:
            return table
        row_col = "__rdt_row__"
        # dedupe on normalized keys (±0.0 group together) but keep the
        # surviving rows' ORIGINAL values via the row-index take below
        aug = normalize_group_keys(table, keys).append_column(
            row_col, pa.array(np.arange(table.num_rows, dtype=np.int64)))
        firsts = aug.group_by(keys).aggregate([(row_col, "min")])
        take = firsts.column(f"{row_col}_min").combine_chunks()
        take = take.take(pc.sort_indices(take))  # preserve original order
        return table.take(take)


def window_output_type(fn: str, arg_type=None) -> pa.DataType:
    """Static output type of a window function — used by the empty-bucket
    path AND the frame's derived schema, so both agree with what the
    non-empty pandas/numpy compute actually produces (e.g. lag/lead over
    integers yields float64: pandas shift introduces NaN holes)."""
    if fn in ("row_number", "rank", "dense_rank", "count"):
        return pa.int64()
    if fn == "mean":
        return pa.float64()
    if fn in ("lag", "lead"):
        if arg_type is not None and pa.types.is_integer(arg_type):
            return pa.float64()
        return arg_type if arg_type is not None else pa.float64()
    # sum/min/max keep the argument's type
    return arg_type if arg_type is not None else pa.float64()


@dataclass
class WindowStep(Step):
    """Evaluate one window function over a bucket that holds every row of its
    partitions (guaranteed by the hash shuffle on the partition keys).

    Rows are sorted by (partition, order) keys; group/tie boundaries are
    computed positionally (factorized codes — null-safe, any dtype), ranks by
    numpy index arithmetic, lag/lead/aggregates by a pandas groupby on the
    integer partition id (dtype-preserving: the computed column is appended
    to the ORIGINAL arrow table, none of its columns round-trip)."""

    part_keys: List[str]
    order_keys: List[Tuple[str, str]]
    out_name: str
    fn: str
    arg_col: Optional[str] = None
    offset: int = 1
    default: object = None

    def run(self, table: pa.Table) -> pa.Table:
        import pandas as pd

        n = table.num_rows
        if n == 0:
            arg_t = (table.schema.field(self.arg_col).type
                     if self.arg_col and self.arg_col != "*" else None)
            typ = window_output_type(self.fn, arg_t)
            return table.append_column(self.out_name, pa.array([], typ))
        sort_spec = ([(k, "ascending") for k in self.part_keys]
                     + list(self.order_keys))
        tbl = table.sort_by(sort_spec) if sort_spec else table

        def change_mask(keys) -> np.ndarray:
            mask = np.zeros(n, dtype=bool)
            mask[0] = True
            for k in keys:
                codes, _ = pd.factorize(tbl.column(k).to_pandas(),
                                        use_na_sentinel=True)
                mask[1:] |= codes[1:] != codes[:-1]
            return mask

        idx = np.arange(n, dtype=np.int64)
        group_start = change_mask(self.part_keys) if self.part_keys \
            else (idx == 0)
        grp_first = np.maximum.accumulate(np.where(group_start, idx, 0))

        fn = self.fn
        if fn == "row_number":
            out = pa.array(idx - grp_first + 1)
        elif fn in ("rank", "dense_rank"):
            tie_start = group_start | change_mask(
                [k for k, _ in self.order_keys])
            if fn == "rank":
                tie_first = np.maximum.accumulate(np.where(tie_start, idx, 0))
                out = pa.array(tie_first - grp_first + 1)
            else:
                ties = np.cumsum(tie_start)
                out = pa.array(ties - ties[grp_first] + 1)
        elif fn == "count" and self.arg_col in (None, "*"):
            part_id = np.cumsum(group_start)
            if self.order_keys:
                # running row count (RANGE frame: order-key peers share it)
                rows = idx - grp_first + 1
                out = pa.array(self._range_frame(rows, group_start,
                                                 change_mask, n))
            else:
                # count("*") = partition row count broadcast to every row
                out = pa.array(np.bincount(part_id)[part_id].astype(np.int64))
        else:
            if self.arg_col is None or self.arg_col == "*":
                raise ValueError(f"window function {fn!r} needs a column")
            part_id = np.cumsum(group_start)
            series = tbl.column(self.arg_col).to_pandas()
            g = series.groupby(part_id)
            if fn in ("sum", "mean", "min", "max", "count"):
                if self.order_keys:
                    # Spark's default frame WITH orderBy is unboundedPreceding
                    # ..currentRow — a RUNNING aggregate whose RANGE frame
                    # includes order-key peers (ties share the value). Nulls
                    # are ignored within the frame (pandas cumulatives emit
                    # NaN AT a null row while continuing past it — the
                    # forward fill gives those rows the prior running value;
                    # an all-null prefix correctly stays null)
                    def _ffill(s):
                        return s.groupby(part_id).ffill()

                    if fn == "sum":
                        out_s = _ffill(g.cumsum())
                    elif fn == "min":
                        out_s = _ffill(g.cummin())
                    elif fn == "max":
                        out_s = _ffill(g.cummax())
                    elif fn == "count":
                        out_s = series.notna().astype("int64") \
                            .groupby(part_id).cumsum()
                    else:  # mean
                        nn_cum = series.notna().astype("int64") \
                            .groupby(part_id).cumsum()
                        out_s = _ffill(g.cumsum()) / nn_cum.where(nn_cum > 0)
                    out_s = pd.Series(self._range_frame(
                        out_s.to_numpy(), group_start, change_mask, n))
                else:
                    out_s = g.transform(fn)
            elif fn in ("lag", "lead"):
                shift = self.offset if fn == "lag" else -self.offset
                out_s = g.shift(shift)
                if self.default is not None:
                    out_s = out_s.where(out_s.notna(), self.default)
            else:
                raise ValueError(f"unknown window function {fn!r}")
            out = pa.Array.from_pandas(out_s)
        return tbl.append_column(self.out_name, out)

    def _range_frame(self, rows_cumulative: np.ndarray,
                     group_start: np.ndarray, change_mask, n: int
                     ) -> np.ndarray:
        """ROWS-frame running values → RANGE frame: every row takes the value
        of the LAST row of its order-key tie group (Spark's default frame
        includes current-row peers)."""
        import pandas as pd

        tie_start = group_start | change_mask([k for k, _ in self.order_keys])
        tie_id = np.cumsum(tie_start)
        return pd.Series(rows_cumulative).groupby(tie_id) \
            .transform("last").to_numpy()


@dataclass
class DescribeStep(Step):
    """Per-partition moment partials for ``describe``: one row of
    count/sum/sumsq/min/max per column. The driver merges these K tiny rows —
    never the data."""

    cols: List[str]

    def run(self, table: pa.Table) -> pa.Table:
        out = {}
        for c in self.cols:
            v = pc.cast(table.column(c).drop_null(), pa.float64(), safe=False)
            s = pc.sum(v).as_py()
            sq = pc.sum(pc.multiply(v, v)).as_py()
            out[f"{c}:count"] = [len(v)]
            out[f"{c}:sum"] = [0.0 if s is None else float(s)]
            out[f"{c}:sumsq"] = [0.0 if sq is None else float(sq)]
            out[f"{c}:min"] = [pc.min(v).as_py()]
            out[f"{c}:max"] = [pc.max(v).as_py()]
        return pa.table(out)


@dataclass
class LocalSortStep(Step):
    keys: List[Tuple[str, str]]  # (column, "ascending"|"descending")

    def run(self, table: pa.Table) -> pa.Table:
        return table.sort_by(self.keys)


def normalize_group_keys(table: pa.Table, keys: Sequence[str]) -> pa.Table:
    """-0.0 → +0.0 in float key columns. Arrow's hash grouper (like our
    ``hash_buckets``) distinguishes the two bit patterns even though the keys
    compare equal, so a groupby/distinct would emit duplicate key rows.
    Adding a typed zero flips only -0.0 (NaN/inf/null unchanged)."""
    for k in keys:
        i = table.schema.get_field_index(k)
        column = table.column(i)
        if pa.types.is_floating(column.type):
            zero = pa.scalar(0.0, type=column.type)
            table = table.set_column(i, k, pc.add(column, zero))
    return table


@dataclass
class GroupAggStep(Step):
    """Local hash aggregation; correct as a whole when rows were shuffled by key."""

    keys: List[str]
    aggs: List[Tuple[str, str, str]]  # (input_col, agg_fn, output_name)

    def run(self, table: pa.Table) -> pa.Table:
        table = normalize_group_keys(table, self.keys)
        agg_spec = [(c, f) for c, f, _ in self.aggs]
        out = table.group_by(self.keys).aggregate(agg_spec)
        # rename pyarrow's <col>_<fn> outputs to requested names
        rename = {}
        for c, f, name in self.aggs:
            rename[f"{c}_{f}"] = name
        new_names = [rename.get(n, n) for n in out.column_names]
        return out.rename_columns(new_names)


def decompose_aggs(aggs: List[Tuple[str, str, str]]
                   ) -> Tuple[List[Tuple[str, str, str]],
                              List[Tuple[str, str, List[str]]]]:
    """Split decomposable aggregates into map-side partials + a reduce-side
    merge plan (two-phase aggregation).

    Returns ``(partials, merges)``: ``partials`` are ``(col, fn, partial_name)``
    specs computed per map task BEFORE the shuffle (deduped, so ``mean`` +
    ``sum`` over one column share a partial); ``merges`` are
    ``(out_name, kind, partial_names)`` where ``kind`` is how the reduce side
    combines partials — ``sum`` (also merges counts), ``min``/``max``, or
    ``mean`` (sum-of-sums / sum-of-counts with a float64 divide)."""
    partial_names: Dict[Tuple[str, str], str] = {}
    partials: List[Tuple[str, str, str]] = []

    def need(c: str, f: str) -> str:
        key = (c, f)
        if key not in partial_names:
            name = f"__rdt_p_{f}_{c}"
            partial_names[key] = name
            partials.append((c, f, name))
        return partial_names[key]

    merges: List[Tuple[str, str, List[str]]] = []
    for c, f, out in aggs:
        if f == "mean":
            merges.append((out, "mean", [need(c, "sum"), need(c, "count")]))
        elif f == "count":
            merges.append((out, "sum", [need(c, "count")]))
        elif f == "sum":
            merges.append((out, "sum", [need(c, "sum")]))
        elif f in ("min", "max"):
            merges.append((out, f, [need(c, f)]))
        else:
            raise ValueError(f"aggregate {f!r} is not decomposable")
    return partials, merges


@dataclass
class GroupAggPartialStep(Step):
    """Map-side partial aggregation: one row per (map task, key) crosses the
    shuffle instead of every input row — the shuffle-byte reduction of
    two-phase aggregation. Output columns: [keys..., partial names...].

    High-cardinality guard: when a sampled prefix shows the keys are mostly
    distinct, a hash aggregation would shrink nothing while paying a full
    grouping pass per map task (the committed bench recorded +47% wall on
    the 100k-cardinality config before this guard). In that case each row is
    emitted AS its own partial — computed vectorized, no hash table: the
    reduce-side merge is oblivious, a raw row is just a group of size 1."""

    keys: List[str]
    partials: List[Tuple[str, str, str]]  # (input_col, fn, partial_name)

    #: sampled-prefix size and the distinct-fraction above which grouping is
    #: judged not worth a per-map hash pass
    SAMPLE_ROWS = 2048
    DISTINCT_FRACTION = 0.5

    def run(self, table: pa.Table) -> pa.Table:
        table = normalize_group_keys(table, self.keys)
        if self.keys and table.num_rows >= 256:
            sample = table.select(self.keys).slice(0, self.SAMPLE_ROWS)
            distinct = sample.group_by(self.keys).aggregate([]).num_rows
            if distinct > self.DISTINCT_FRACTION * sample.num_rows:
                return self._rowwise(table)
        spec = [(c, f) for c, f, _ in self.partials]
        out = table.group_by(self.keys).aggregate(spec)
        rename = {f"{c}_{f}": name for c, f, name in self.partials}
        return out.rename_columns(
            [rename.get(n, n) for n in out.column_names])

    def _rowwise(self, table: pa.Table) -> pa.Table:
        """Per-row partials in the exact schema the grouped path emits (an
        empty-slice group_by probes the aggregate output types, so e.g. an
        int32 sum partial correctly widens to int64)."""
        spec = [(c, f) for c, f, _ in self.partials]
        probe = table.slice(0, 0).group_by(self.keys).aggregate(spec)
        arrays = [table.column(k) for k in self.keys]
        names = list(self.keys)
        for c, f, name in self.partials:
            typ = probe.schema.field(f"{c}_{f}").type
            if f == "count":
                # count of one value: 1 when valid, 0 when null (never null)
                arr = pc.cast(pc.is_valid(table.column(c)), typ)
            else:
                # sum/min/max of one value is the value (null stays null, so
                # the merge-side aggregate skips it, exactly like grouping)
                arr = pc.cast(table.column(c), typ, safe=False)
            arrays.append(arr)
            names.append(name)
        return pa.table(arrays, names=names)


@dataclass
class GroupAggMergeStep(Step):
    """Reduce-side merge of map-side partials. Emits exactly the schema the
    single-phase :class:`GroupAggStep` would: keys first, then one column per
    requested aggregate, in order."""

    keys: List[str]
    merges: List[Tuple[str, str, List[str]]]  # (out_name, kind, partial_names)

    def run(self, table: pa.Table) -> pa.Table:
        spec, seen = [], set()
        for _, kind, ops in self.merges:
            pairs = ([(ops[0], "sum"), (ops[1], "sum")] if kind == "mean"
                     else [(ops[0], kind)])
            for p in pairs:
                if p not in seen:
                    seen.add(p)
                    spec.append(p)
        merged = table.group_by(self.keys).aggregate(spec)
        arrays = [merged.column(k) for k in self.keys]
        names = list(self.keys)
        for out, kind, ops in self.merges:
            if kind == "mean":
                s = merged.column(f"{ops[0]}_sum")
                c = merged.column(f"{ops[1]}_sum")
                arr = pc.divide(pc.cast(s, pa.float64(), safe=False),
                                pc.cast(c, pa.float64(), safe=False))
            else:
                arr = merged.column(f"{ops[0]}_{kind}")
            arrays.append(arr)
            names.append(out)
        return pa.table(arrays, names=names)


@dataclass
class GroupAggPartialMergeStep(Step):
    """Merge map-side partials INTO partials (same schema in, same schema
    out): the intermediate level of a skew-split aggregation. A hot bucket's
    byte-ranges split across k reduce tasks, each running this step over its
    portion; the outputs stay in partial form (count partials re-sum, sums
    sum, min/min max/max) so the combining task's ordinary
    :class:`GroupAggMergeStep` finishes them exactly as if the bucket had
    never been split — mean still divides only once, at the end."""

    keys: List[str]
    partials: List[Tuple[str, str, str]]  # (input_col, fn, partial_name)

    def run(self, table: pa.Table) -> pa.Table:
        spec = [(name, "sum" if f in ("count", "sum") else f)
                for _, f, name in self.partials]
        out = table.group_by(self.keys).aggregate(spec)
        rename = {f"{name}_{fn}": name for (_, _, name), (_, fn)
                  in zip(self.partials, spec)}
        return out.rename_columns(
            [rename.get(n, n) for n in out.column_names])


@dataclass
class HashJoinStep(Step):  # carries-refs: right_refs, right_parts, right_stream
    """Join the incoming (left bucket) table against the right bucket refs.

    ``right_parts`` (byte-range triples) carries the right side when it was
    shuffled through consolidated map outputs; ``right_stream`` when the
    right map stage is PIPELINED (the build side accumulates from seal
    notifications while both map stages still run); otherwise ``right_refs``
    holds whole-blob refs, exactly as before."""

    right_refs: List[ObjectRef]
    keys: List[str]
    right_keys: List[str]
    how: str = "inner"
    right_schema: Optional[bytes] = None
    right_parts: Optional[List[Tuple[ObjectRef, int, int]]] = None
    right_stream: Optional[StreamingRangeSource] = None

    def run(self, table: pa.Table) -> pa.Table:
        if self.right_stream is not None:
            right = self.right_stream.load()
        elif self.right_parts is not None:
            right = RangeRefSource(self.right_parts,
                                   schema=self.right_schema).load()
        else:
            right = ArrowRefSource(self.right_refs,
                                   schema=self.right_schema).load()
        return table.join(right, keys=self.keys, right_keys=self.right_keys,
                          join_type=self.how)


#: join types for which each broadcast side is semantically safe: the
#: STREAMED side's rows are partitioned (each row seen exactly once), so its
#: unmatched rows surface correctly; the BROADCAST side's unmatched rows
#: would be emitted once per probe partition, so any join type that keeps
#: them ("full outer", the broadcast side's own outer) is excluded.
BROADCAST_RIGHT_JOIN_TYPES = frozenset(
    ("inner", "left outer", "left semi", "left anti"))
BROADCAST_LEFT_JOIN_TYPES = frozenset(
    ("inner", "right outer", "right semi", "right anti"))


@dataclass
class BroadcastJoinStep(Step):  # carries-refs: parts
    """Broadcast-hash join: stream this task's partition against an
    executor-local hash table of the (small) broadcast side.

    ``parts`` are ``(ref, offset, size)`` byte ranges of the broadcast
    side's store blobs — replication IS the ranged-fetch plane: the first
    task on each executor pulls every range in one batched fetch
    (:class:`RangeRefSource`) and the built table is kept in the executor's
    bounded broadcast cache, so sibling partitions probe it for free.
    ``broadcast_side`` says which logical side the cached table plays:
    ``"right"`` probes the incoming (left) partition against it, ``"left"``
    streams right-side partitions. Either way the output schema matches the
    bucketed :class:`HashJoinStep` exactly (left columns, then the right's
    non-key columns)."""

    parts: List[Tuple[ObjectRef, int, int]]
    keys: List[str]
    right_keys: List[str]
    how: str = "inner"
    broadcast_side: str = "right"
    schema: Optional[bytes] = None  # broadcast side's serialized schema

    def _load_small(self) -> pa.Table:
        from raydp_tpu.etl.executor import broadcast_cache
        key = (tuple((r.id, int(o), int(s)) for r, o, s in self.parts),
               self.schema)
        return broadcast_cache().get_or_load(
            key, lambda: RangeRefSource(list(self.parts),
                                        schema=self.schema).load())

    def run(self, table: pa.Table) -> pa.Table:
        small = self._load_small()
        if self.broadcast_side == "right":
            return table.join(small, keys=self.keys,
                              right_keys=self.right_keys, join_type=self.how)
        return small.join(table, keys=self.keys,
                          right_keys=self.right_keys, join_type=self.how)


@dataclass
class RenameStep(Step):
    mapping: Dict[str, str]

    def run(self, table: pa.Table) -> pa.Table:
        return table.rename_columns(
            [self.mapping.get(c, c) for c in table.column_names])


# ==== task =========================================================================
@dataclass
class Task:
    task_id: str
    source: Step
    steps: List[Step] = field(default_factory=list)
    output: str = RETURN_REF
    # SHUFFLE parameters
    num_buckets: int = 0
    shuffle_keys: Optional[List[str]] = None      # None → round-robin repartition
    shuffle_seed: Optional[int] = None            # set → seeded random bucketing
    # CACHE parameter
    cache_key: Optional[str] = None
    # range-partition spec for sort (overrides hash bucketing):
    # (key, boundaries, nulls_high); legacy 2-tuples are tolerated
    range_key: Optional[Tuple[str, List, bool]] = None
    owner: Optional[str] = None                   # object-store owner for outputs
    # how many TRAILING steps are shuffle-side (e.g. map-side partial
    # aggregation): the executor measures rows/bytes entering the shuffle
    # stage BEFORE these run, so the in/out counters show the reduction
    shuffle_pre_steps: int = 0
    # SHUFFLE output writes all buckets as ONE consolidated blob (back-to-back
    # IPC streams + per-bucket index) sealed with a single RPC; decided by the
    # driver per action (RDT_SHUFFLE_CONSOLIDATE) so a mid-session toggle
    # never splits one stage across the two formats
    shuffle_consolidate: bool = False
    # the shuffle-stage label this task READS (set on reduce tasks): its
    # store-RPC counters are attributed to that stage's ledger entry
    consumes_stage: Optional[str] = None
    # the UNIQUE stream stage_key this task reads when that stage is
    # PIPELINED — labels repeat within one action (a.join(b).join(c) runs
    # "join-left" twice), so the driver's attribution/wait logic must key
    # on this, never the label
    consumes_stream: Optional[str] = None

    def with_output(self, **kw) -> "Task":
        d = self.__dict__.copy()
        d.update(kw)
        return Task(**d)


def run_task_body(task: Task) -> pa.Table:
    src = task.source
    table = src.load()
    for step in task.steps:
        table = step.run(table)
    return table


# ==== pipelined-shuffle helpers ====================================================
def stream_sources_of(task: Task) -> List[StreamingRangeSource]:
    """Every :class:`StreamingRangeSource` a task reads through — its source,
    a join step's streamed build side, or a cached recipe's nested task. The
    executor routes tasks with any of these onto dedicated stream threads
    (they WAIT on seal notifications, and parking a bounded dispatcher
    thread on that wait could deadlock the very map tasks being waited on)."""
    out: List[StreamingRangeSource] = []

    def _step(step: Step) -> None:
        if isinstance(step, StreamingRangeSource):
            out.append(step)
        rs = getattr(step, "right_stream", None)
        if isinstance(rs, StreamingRangeSource):
            out.append(rs)
        if isinstance(step, CachedSource) and step.recover is not None:
            out.extend(stream_sources_of(step.recover))

    _step(task.source)
    for s in task.steps:
        _step(s)
    return out


def collect_stream_stats(task: Task) -> Dict[str, float]:
    """Fold the per-source ``stream_stats`` left behind by a streamed read
    into the result keys the driver's stage ledger aggregates."""
    srcs = [s for s in stream_sources_of(task)
            if getattr(s, "stream_stats", None) is not None]
    if not srcs:
        return {}
    out: Dict[str, float] = {
        "stream_overlap_s": sum(s.stream_stats["overlap_s"] for s in srcs),
        "stream_rounds": sum(s.stream_stats["rounds"] for s in srcs),
    }
    firsts = [s.stream_stats["first_fetch_ts"] for s in srcs
              if s.stream_stats["first_fetch_ts"] is not None]
    if firsts:
        out["stream_first_fetch_ts"] = min(firsts)
    return out


def resolve_stream_sources(task: Task, resolver) -> Task:
    """Rewrite a task's streaming reads into concrete
    :class:`RangeRefSource` reads — ``resolver(stage_key, bucket)`` returns
    the final ``(ref, off, size)`` parts once the stage's maps have ALL
    sealed. Used before a task is serialized to OUTLIVE its action (cache()
    recover recipes): the stream ledger closes with the action, so a recipe
    kept in streaming form would be permanently unreadable."""
    import dataclasses

    def _res(step: Step) -> Step:
        if isinstance(step, StreamingRangeSource):
            return RangeRefSource(resolver(step.stage_key, step.bucket),
                                  schema=step.schema)
        if isinstance(step, HashJoinStep) \
                and isinstance(step.right_stream, StreamingRangeSource):
            rs = step.right_stream
            return dataclasses.replace(
                step, right_stream=None,
                right_parts=resolver(rs.stage_key, rs.bucket),
                right_schema=step.right_schema or rs.schema)
        if isinstance(step, CachedSource) and step.recover is not None:
            recover = resolve_stream_sources(step.recover, resolver)
            if recover is not step.recover:
                return dataclasses.replace(step, recover=recover)
        return step

    source = _res(task.source)
    steps = [_res(s) for s in task.steps]
    if source is task.source \
            and all(a is b for a, b in zip(steps, task.steps)):
        return task
    return task.with_output(source=source, steps=steps)


# ==== lineage-recovery ref surgery =================================================
def task_input_ids(task: Task) -> List[str]:
    """Object ids a task reads — the refs lineage recovery must keep alive
    (or regenerate) for the task to run."""
    ids: List[str] = []

    def _step(step: Step) -> None:
        if isinstance(step, ArrowRefSource):
            ids.extend(r.id for r in step.refs)
        elif isinstance(step, (SlicedRefSource, RangeRefSource)):
            ids.extend(r.id for r, _, _ in step.parts)
        elif isinstance(step, HashJoinStep):
            ids.extend(r.id for r in step.right_refs)
            if step.right_parts is not None:
                ids.extend(r.id for r, _, _ in step.right_parts)
        elif isinstance(step, BroadcastJoinStep):
            ids.extend(r.id for r, _, _ in step.parts)
        elif isinstance(step, CachedSource) and step.recover is not None:
            ids.extend(task_input_ids(step.recover))

    _step(task.source)
    for s in task.steps:
        _step(s)
    return ids


def _patch_step_refs(step: Step, mapping: Dict[str, ObjectRef]) -> Step:
    import dataclasses
    if isinstance(step, ArrowRefSource):
        refs = [mapping.get(r.id, r) for r in step.refs]
        if refs != step.refs:
            return dataclasses.replace(step, refs=refs)
    elif isinstance(step, (SlicedRefSource, RangeRefSource)):
        # offsets/sizes survive the swap: producer reruns are deterministic,
        # so a regenerated consolidated blob is byte-identical and the
        # bucket index still addresses it
        parts = [(mapping.get(r.id, r), o, n) for r, o, n in step.parts]
        if parts != step.parts:
            return dataclasses.replace(step, parts=parts)
    elif isinstance(step, HashJoinStep):
        refs = [mapping.get(r.id, r) for r in step.right_refs]
        parts = step.right_parts
        if parts is not None:
            new_parts = [(mapping.get(r.id, r), o, n) for r, o, n in parts]
            if new_parts != parts:
                parts = new_parts
        if refs != step.right_refs or parts is not step.right_parts:
            return dataclasses.replace(step, right_refs=refs,
                                       right_parts=parts)
    elif isinstance(step, BroadcastJoinStep):
        # regenerated broadcast blobs are byte-identical (deterministic
        # producer reruns), so offsets/sizes survive — and the fresh ids
        # change the executor-side broadcast-cache key, forcing a refetch
        parts = [(mapping.get(r.id, r), o, n) for r, o, n in step.parts]
        if parts != step.parts:
            return dataclasses.replace(step, parts=parts)
    elif isinstance(step, CachedSource) and step.recover is not None:
        recover = patch_task_refs(step.recover, mapping)
        if recover is not step.recover:
            return dataclasses.replace(step, recover=recover)
    return step


def patch_task_refs(task: Task, mapping: Dict[str, ObjectRef]) -> Task:
    """Rewrite a task to read regenerated blobs: every ObjectRef whose id is
    in ``mapping`` (old id → fresh ref) is swapped, everywhere a task can hold
    refs. Returns the original task object when nothing matched."""
    if not mapping:
        return task
    source = _patch_step_refs(task.source, mapping)
    steps = [_patch_step_refs(s, mapping) for s in task.steps]
    if source is task.source and all(a is b for a, b in zip(steps, task.steps)):
        return task
    return task.with_output(source=source, steps=steps)


def split_by_bucket(table: pa.Table, bucket: np.ndarray,
                    num_buckets: int) -> List[pa.Table]:
    """One-pass bucket split: a single stable argsort + ``take`` + zero-copy
    slices, replacing the per-bucket ``table.filter`` loop that scanned the
    whole table once PER bucket (O(rows × buckets) passes). The stable sort
    preserves original row order within each bucket, exactly like the
    sequential filters did."""
    order = np.argsort(bucket, kind="stable")
    counts = np.bincount(bucket, minlength=num_buckets)
    arranged = table.take(pa.array(order))
    out, off = [], 0
    for c in counts:
        out.append(arranged.slice(off, int(c)))
        off += int(c)
    return out


def _hash_string_like(arr: pa.Array) -> np.ndarray:
    """Vectorized hash for string/other non-numeric key columns: dictionary-
    encode (a single C++ pass), hash each DISTINCT value once, then gather by
    index — the old path called ``str(v)`` + crc32 on every ROW via
    ``to_pylist``. Dictionary-typed columns use their existing dictionary
    directly instead of falling into the per-row slow path."""
    if pa.types.is_dictionary(arr.type):
        dict_arr = arr
    else:
        try:
            dict_arr = pc.dictionary_encode(arr)
        except pa.ArrowException:
            # not dictionary-encodable (e.g. nested struct/list keys): keep
            # the per-row path the pre-vectorized code used
            return np.array([hash_bytes(str(v)) for v in arr.to_pylist()],
                            dtype=np.uint64)
    if isinstance(dict_arr, pa.ChunkedArray):
        dict_arr = dict_arr.combine_chunks()
    distinct = dict_arr.dictionary.to_pylist()
    # one extra slot for nulls: fill_null routes null indices there, and the
    # sentinel hashes like str(None) did on the old per-row path
    h = np.empty(len(distinct) + 1, dtype=np.uint64)
    for i, v in enumerate(distinct):
        h[i] = hash_bytes(str(v))
    h[len(distinct)] = hash_bytes(str(None))
    idx = np.asarray(pc.fill_null(pc.cast(dict_arr.indices, pa.int64()),
                                  len(distinct)))
    return h[idx]


def hash_buckets(table: pa.Table, keys: Sequence[str], num_buckets: int) -> List[pa.Table]:
    """Deterministic hash partitioning on key columns.

    Uses a stable numpy-side hash over the key columns so map tasks on different
    executors agree — Python's ``hash`` is salted per process and unusable here.
    The sentinel key list ``["*"]`` means "all columns" (used by ``distinct``,
    whose key set is the full row and unknown until the table is loaded).
    """
    if list(keys) == ["*"]:
        keys = table.column_names
    if table.num_rows == 0:
        return [table] * num_buckets
    acc = np.zeros(table.num_rows, dtype=np.uint64)
    for k in keys:
        arr = table.column(k).combine_chunks()
        if pa.types.is_integer(arr.type) or pa.types.is_floating(arr.type):
            vals = np.asarray(pc.cast(arr, pa.float64(), safe=False).fill_null(np.nan))
            # -0.0 == 0.0 but their bit patterns differ: equal keys must hash
            # equal or a groupby emits duplicate key rows
            vals = np.where(vals == 0.0, 0.0, vals)
            h = vals.view(np.uint64).copy()
        else:
            h = _hash_string_like(arr)
        acc = acc * np.uint64(1000003) + h
    # avalanche finalizer (murmur3 fmix64): the raw accumulator's LOW bits
    # are degenerate for numeric keys — a small integer's float64 bit
    # pattern ends in zero mantissa bits, so ``acc % 2^k`` put EVERY
    # integer-keyed row in bucket 0 whenever the bucket count was a power
    # of two (the default ``min(8, 2×executors)`` always is). Mixing the
    # high bits down gives the uniform spread the skew detector and the
    # per-bucket size index assume. Deterministic across executors, like
    # the accumulator itself.
    acc = acc ^ (acc >> np.uint64(33))
    acc = acc * np.uint64(0xFF51AFD7ED558CCD)
    acc = acc ^ (acc >> np.uint64(33))
    bucket = (acc % np.uint64(num_buckets)).astype(np.int64)
    return split_by_bucket(table, bucket, num_buckets)


def hash_bytes(s: str) -> int:
    import zlib
    return zlib.crc32(s.encode()) & 0xFFFFFFFF


def random_buckets(table: pa.Table, num_buckets: int,
                   seed: int) -> List[pa.Table]:
    """Seeded uniform random bucket assignment — the map side of the
    distributed ``random_shuffle``. Deterministic per (seed, partition), so a
    recomputed map task lands every row in the same bucket."""
    if table.num_rows == 0:
        return [table] * num_buckets
    rng = np.random.RandomState(seed)
    bucket = rng.randint(0, num_buckets, size=table.num_rows)
    return split_by_bucket(table, bucket, num_buckets)


def round_robin_buckets(table: pa.Table, num_buckets: int,
                        start: int = 0) -> List[pa.Table]:
    if table.num_rows == 0:
        return [table] * num_buckets
    idx = (np.arange(table.num_rows) + start) % num_buckets
    return split_by_bucket(table, idx, num_buckets)


def range_buckets_multi(table: pa.Table, keys: List[Tuple[str, str]],
                        boundaries: List[Tuple]) -> List[pa.Table]:
    """Range partitioning on a COMPOSITE sort key.

    ``keys`` are ``(column, "ascending"|"descending")`` pairs; ``boundaries``
    are key tuples drawn from a sorted sample. A row's bucket is the number of
    boundaries it sorts AFTER — lexicographic comparison honoring each key's
    direction, with null keys sorting last (matching ``sort_by``'s ``at_end``
    placement) — so buckets come out already in global sort order for any
    direction mix, no reversal step. Single-key skew is why this exists: with
    a low-cardinality first key, per-key boundaries collapse and only the
    composite key can spread rows."""
    bucket = np.zeros(table.num_rows, dtype=np.int64)
    cols = {name: table.column(name).combine_chunks() for name, _ in keys}
    nan_masks = {}
    for name, _ in keys:
        arr = cols[name]
        if pa.types.is_floating(arr.type):
            nan_masks[name] = pc.fill_null(pc.is_nan(arr), False)
    for bvals in boundaries:
        after = None
        # build lexicographic "sorts after boundary" from the LAST key back:
        # after_k = gt_k OR (eq_k AND after_{k+1})
        for (name, order), b in reversed(list(zip(keys, bvals))):
            arr = cols[name]
            cmp = pc.less if order == "descending" else pc.greater
            gt = pc.fill_null(cmp(arr, pa.scalar(b)), True)  # nulls sort last
            nan = nan_masks.get(name)
            if nan is not None and order != "descending":
                # Arrow orders NaN above every number: ascending sorts place
                # it after any boundary (pc.greater says False there);
                # descending already gets bucket 0 from pc.less = False
                gt = pc.or_(gt, nan)
            if after is None:
                after = gt
            else:
                eq = pc.fill_null(pc.equal(arr, pa.scalar(b)), False)
                after = pc.or_(gt, pc.and_(eq, after))
        if after is not None:
            bucket += np.asarray(after, dtype=np.int64)
    return split_by_bucket(table, bucket, len(boundaries) + 1)


def range_buckets(table: pa.Table, key: str, boundaries: List,
                  nulls_high: bool = False) -> List[pa.Table]:
    """Partition rows by boundary values using Arrow comparisons — works for any
    orderable type (ints, floats, strings, timestamps), no numeric cast.

    ``nulls_high`` routes null keys to the LAST bucket instead of the first:
    ``sort_by`` places nulls at_end within each bucket, so a globally correct
    ascending sort needs them in the final bucket (descending sorts reverse
    the bucket list, so there nulls stay in bucket 0 which becomes last)."""
    col_arr = table.column(key).combine_chunks()
    bucket = np.zeros(table.num_rows, dtype=np.int64)
    for b in boundaries:
        gt = pc.fill_null(pc.greater(col_arr, pa.scalar(b)), nulls_high)
        bucket += np.asarray(gt, dtype=np.int64)
    return split_by_bucket(table, bucket, len(boundaries) + 1)
