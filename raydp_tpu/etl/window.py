"""Window functions over partitioned, ordered row frames.

PySpark-compatible surface (the reference gets these from Spark SQL):

    from raydp_tpu.etl.window import Window
    from raydp_tpu.etl import functions as F

    w = Window.partitionBy("user").orderBy("ts")
    df = df.withColumn("visit", F.row_number().over(w))
    df = df.withColumn("prev_amt", F.lag("amount", 1, 0.0).over(w))
    df = df.withColumn("user_total", F.sum("amount").over(
        Window.partitionBy("user")))

Execution is distributed: rows hash-shuffle by the partition keys (equal keys
share a bucket, so per-bucket evaluation is globally exact), each bucket sorts
by (partition, order) keys and computes the function executor-side
(:class:`raydp_tpu.etl.tasks.WindowStep`). A spec with no ``partitionBy``
evaluates on a single partition — correct but unparallel, exactly Spark's
"No Partition Defined" behavior.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union


class WindowSpec:
    """Immutable (partition_keys, order_keys) pair."""

    def __init__(self, partition_keys: Tuple[str, ...] = (),
                 order_keys: Tuple[Tuple[str, str], ...] = ()):
        self.partition_keys = tuple(partition_keys)
        self.order_keys = tuple(order_keys)

    def partitionBy(self, *cols: str) -> "WindowSpec":
        return WindowSpec(tuple(_names(cols)), self.order_keys)

    def orderBy(self, *cols) -> "WindowSpec":
        return WindowSpec(self.partition_keys, tuple(_order_keys(cols)))

    partition_by = partitionBy
    order_by = orderBy


def _names(cols) -> List[str]:
    out = []
    for c in cols:
        out.append(c if isinstance(c, str) else c._name())
    return out


def _order_keys(cols) -> List[Tuple[str, str]]:
    keys = []
    for c in cols:
        if isinstance(c, tuple):
            name, order = c
            keys.append((name if isinstance(name, str) else name._name(),
                         order))
        else:
            keys.append((c if isinstance(c, str) else c._name(), "ascending"))
    return keys


class Window:
    """Entry point, Spark-style: ``Window.partitionBy(...).orderBy(...)``."""

    @staticmethod
    def partitionBy(*cols: str) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowSpec:
        return WindowSpec().orderBy(*cols)

    partition_by = partitionBy
    order_by = orderBy


#: window functions that need ``orderBy`` to mean anything
_ORDER_REQUIRED = {"row_number", "rank", "dense_rank", "lag", "lead"}


class WindowExpr:
    """A window function bound to a spec; assign via ``df.withColumn``."""

    def __init__(self, fn: str, spec: WindowSpec,
                 arg_col: Optional[str] = None, offset: int = 1,
                 default=None, name: Optional[str] = None):
        if fn in _ORDER_REQUIRED and not spec.order_keys:
            raise ValueError(f"window function {fn!r} requires an orderBy")
        self.fn = fn
        self.spec = spec
        self.arg_col = arg_col
        self.offset = offset
        self.default = default
        self.name = name or (f"{fn}({arg_col})" if arg_col else f"{fn}()")

    def _name(self) -> str:
        return self.name

    def alias(self, name: str) -> "WindowExpr":
        return WindowExpr(self.fn, self.spec, self.arg_col, self.offset,
                          self.default, name)


class WindowFunction:
    """An unbound window function: ``F.row_number()`` → ``.over(spec)``."""

    def __init__(self, fn: str, arg_col: Optional[str] = None,
                 offset: int = 1, default=None):
        self.fn = fn
        self.arg_col = arg_col
        self.offset = offset
        self.default = default

    def over(self, spec: WindowSpec) -> WindowExpr:
        return WindowExpr(self.fn, spec, self.arg_col, self.offset,
                          self.default)
