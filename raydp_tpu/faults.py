"""Deterministic fault-injection plane.

The test matrix of the reference provokes failures by killing processes ad hoc
(``Executor.crash()``, node removal); that proves recovery *can* happen but not
that a given schedule of failures yields correct results. This module gives the
repo a seeded, declarative injection plane so a chaos test (or a CI leg) can
state *exactly* which call dies, and replay it:

- rules come from the ``RDT_FAULTS`` env spec (inherited by every spawned actor
  / rank process) or the programmatic :func:`inject` API (this process only);
- schedules are deterministic: ``nth=N`` (the Nth matching call in a process),
  ``every=N``, or seeded-PRNG ``p=0.3`` — never wall-clock;
- ``once=<path>`` makes a rule fire at most once across ALL processes (an
  O_EXCL sentinel file), which is what keeps a ``crash`` rule from also killing
  the restarted actor that inherits the same env.

Spec grammar (documented in doc/fault_tolerance.md)::

    RDT_FAULTS = rule (';' rule)*
    rule       = site ':' action (':' key '=' value)*

    sites   : executor.run_task | shuffle.write | shuffle.fetch | store.get
              | store.spill | rpc.call | estimator.epoch | serve.predict
              | pool.drain | pool.scale | stream.epoch
              (env specs must name a KNOWN_SITES entry)
    actions : crash | delay | raise | drop | connloss   (interpreted by the site)
    keys    : nth= every= p= times= seed= match= once= ms= ms_per_mb= bucket=

Example — crash the executor on its 3rd task, exactly once in the session::

    RDT_FAULTS="executor.run_task:crash:nth=3:once=/tmp/crash.sentinel"

The ``executor.run_task`` key is ``"<executor name>|<task id>"``, so
``match=`` can pin a rule to ONE executor — the seeded-straggler schedule
the speculation bench uses (delay every task entering a single executor)::

    RDT_FAULTS="executor.run_task:delay:ms=1500:match=rdt-executor-app-0|"

This module must stay importable everywhere (actor bootstrap, rank workers,
the RPC client): stdlib only, no raydp_tpu imports.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

ENV_FAULTS = "RDT_FAULTS"
ENV_SEED = "RDT_FAULTS_SEED"

#: every action any site interprets; parse_spec rejects anything else so a
#: typo'd action fails loudly instead of firing (claiming its once-sentinel)
#: while injecting nothing
KNOWN_ACTIONS = frozenset(("crash", "delay", "raise", "drop", "connloss"))

#: every site the runtime actually arms (the ``faults.check(...)`` call
#: sites). parse_spec rejects env-spec sites outside this registry — a chaos
#: schedule aimed at a renamed/typo'd site used to arm nothing, silently.
#: The programmatic :func:`inject` stays permissive: unit tests arm synthetic
#: sites (``unit.site``) to test the plane itself. Kept in sync with code,
#: doc/fault_tolerance.md's site table, and test specs by rdtlint's
#: ``fault-site-sync`` rule.
KNOWN_SITES = frozenset((
    "executor.run_task",
    "shuffle.write",
    "shuffle.fetch",
    "store.get",
    "store.spill",
    "rpc.call",
    "estimator.epoch",
    "serve.predict",
    "pool.drain",
    "pool.scale",
    "pool.fork",
    "store.budget",
    "stream.epoch",
))

#: the site-specific actions and the only call sites that interpret them —
#: crash/delay/raise are generic (any site routes them through apply());
#: a drop armed at rpc.call would claim its sentinel and inject nothing,
#: the same silent-no-op the action-name check exists to prevent
SITE_SPECIFIC_ACTIONS = {
    "drop": ("shuffle.write", "store.get", "shuffle.fetch", "store.spill",
             "stream.epoch"),
    "connloss": ("rpc.call",),
}

#: exit code of an injected crash — same code the ad-hoc ``Executor.crash()``
#: used, so supervisors/tests keyed on it keep working
CRASH_EXIT_CODE = 23

#: flight-recorder hook: ``cb(site, key, action)`` called for every fired
#: rule. This module is stdlib-only by contract, so it cannot import the
#: telemetry plane — ``raydp_tpu/profiler.py`` arms the hook at ITS import
#: (any process running runtime code), and bootstrap-only processes simply
#: record nothing. Failures in the hook never mask the injected fault.
_fire_hook = None


def set_fire_hook(cb) -> None:
    global _fire_hook
    _fire_hook = cb


def _notify_fire(site: str, key: str, action: str) -> None:
    if _fire_hook is None:
        return
    try:
        _fire_hook(site, key, action)
    except Exception:  # noqa: BLE001 - telemetry must never break injection
        pass


@dataclass
class FaultRule:
    """One armed fault. ``check()`` decides *whether* it fires; the call site
    interprets ``action`` (a store knows ``drop``, an RPC client ``connloss``;
    ``crash``/``delay``/``raise`` are generic via :func:`apply`)."""

    site: str
    action: str
    nth: Optional[int] = None      # fire on exactly the Nth matching call
    every: Optional[int] = None    # fire on every Nth matching call
    p: Optional[float] = None      # fire with this probability (seeded PRNG)
    times: Optional[int] = None    # stop after this many fires (this process)
    seed: int = 0
    match: Optional[str] = None    # substring filter on the call key
    once: Optional[str] = None     # sentinel path: at most one fire, ALL procs
    ms: float = 50.0               # delay duration for action=delay
    #: extra delay per MiB the call site reports moving (sites that pass
    #: ``nbytes`` to :func:`apply` — e.g. ``shuffle.fetch``); models a slow
    #: data plane whose cost scales with payload size. 0 = fixed delay only.
    ms_per_mb: float = 0.0
    bucket: int = 0                # which output bucket a shuffle drop targets
    #: registry position — part of the PRNG stream so two stacked rules with
    #: identical (seed, site, action) still draw independent p= schedules;
    #: spec order is stable, so runs stay reproducible
    index: int = 0
    # runtime state (per process)
    calls: int = 0
    fires: int = 0
    _rng: random.Random = field(default=None, repr=False)  # type: ignore

    def __post_init__(self):
        # the same loud-failure contract as parse_spec, for the programmatic
        # path too: a typo'd action would fire-and-claim (rule.fires grows,
        # once-sentinels get consumed) while injecting nothing
        if self.action not in KNOWN_ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r} "
                f"(known: {', '.join(sorted(KNOWN_ACTIONS))})")
        sites = SITE_SPECIFIC_ACTIONS.get(self.action)
        if sites is not None and self.site not in sites:
            raise ValueError(
                f"action {self.action!r} is only interpreted at "
                f"{'/'.join(sites)}, not {self.site!r}")
        if self._rng is None:
            # per-rule stream: independent of firing order at other sites
            self._rng = random.Random(
                repr((self.seed, self.site, self.action, self.index)))

    def _schedule_fires(self) -> bool:
        if self.nth is not None:
            return self.calls == self.nth
        if self.every is not None:
            return self.every > 0 and self.calls % self.every == 0
        if self.p is not None:
            return self._rng.random() < self.p
        return True  # no schedule: every matching call

    def register_call(self, key: str) -> bool:
        """Count the call; True when the schedule selects it. No claim yet —
        a rule that loses to an earlier same-site rule must NOT consume its
        ``once`` sentinel or ``times`` budget for a fire that never happened."""
        if self.match is not None and self.match not in key:
            return False
        self.calls += 1
        if self.times is not None and self.fires >= self.times:
            return False
        return self._schedule_fires()

    def claim(self) -> bool:
        """Commit a selected fire: atomically claims the ``once`` sentinel so
        exactly one process (and one call) wins."""
        if self.once is not None and not _claim_sentinel(self.once):
            return False
        self.fires += 1
        return True

    def should_fire(self, key: str) -> bool:
        """Count the call and decide, claiming on success."""
        return self.register_call(key) and self.claim()


def _claim_sentinel(path: str) -> bool:
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    except OSError as exc:
        # an unwritable/nonexistent once= path would otherwise permanently
        # and silently disarm the rule — the exact failure mode this module
        # promises to surface loudly; the schedule stays disarmed (firing in
        # every process is worse) but the disarm is now visible in logs
        logger.warning(
            "fault once= sentinel %s is unusable (%s); rule will not fire",
            path, exc)
        return False
    os.write(fd, str(os.getpid()).encode())
    os.close(fd)
    return True


def parse_spec(spec: str, default_seed: int = 0,
               start_index: int = 0) -> List[FaultRule]:
    """Parse the ``RDT_FAULTS`` grammar; raises ValueError on a bad rule so a
    typo fails loudly instead of silently disarming the chaos schedule.
    ``start_index`` offsets the per-rule PRNG ``index`` so env rules parsed
    into a registry that already holds inject()-ed rules (reset() keeps
    them) don't reuse an existing rule's stream."""
    rules: List[FaultRule] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) < 2:
            raise ValueError(f"fault rule needs site:action, got {raw!r}")
        site, action = parts[0].strip(), parts[1].strip()
        if site not in KNOWN_SITES:
            raise ValueError(
                f"unknown fault site {site!r} (known: "
                f"{', '.join(sorted(KNOWN_SITES))}) in rule {raw!r}")
        kw: Dict[str, object] = {"seed": default_seed,
                                 "index": start_index + len(rules)}
        for opt in parts[2:]:
            if "=" not in opt:
                raise ValueError(f"fault option {opt!r} is not key=value")
            k, v = opt.split("=", 1)
            k = k.strip()
            if k in ("nth", "every", "times", "seed", "bucket"):
                kw[k] = int(v)
            elif k in ("p", "ms", "ms_per_mb"):
                kw[k] = float(v)
            elif k in ("match", "once"):
                kw[k] = v
            else:
                raise ValueError(f"unknown fault option {k!r} in {raw!r}")
        try:
            # action-name and action/site validation live in
            # FaultRule.__post_init__ (shared with the programmatic path);
            # re-raise with the offending rule text for env-spec context
            rules.append(FaultRule(site=site, action=action, **kw))  # type: ignore
        except ValueError as e:
            raise ValueError(f"{e} (in rule {raw!r})") from None
    return rules


class FaultPlane:
    """Process-local registry: env rules (loaded once) + programmatic rules."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: List[FaultRule] = []
        #: inject()-armed rules — they survive reset() (only env rules are
        #: reloaded); wiping them there would be the silent-no-op failure
        #: mode this module otherwise rejects loudly
        self._prog_rules: List[FaultRule] = []
        self._env_loaded = False
        # lock-free hot-path gate: check() is wired into every RPC submit and
        # every store read, so the zero-rules case (production) must not
        # serialize all threads through the lock just to see an empty list
        self._armed = False

    def _ensure_env(self) -> None:
        if self._env_loaded:
            return
        with self._lock:
            if self._env_loaded:
                return
            # both knobs ARE declared in raydp_tpu/knobs.py, but this module
            # must stay stdlib-only and importable before the package
            # (actor bootstrap), so it reads the env directly; init()
            # re-arms from the current env
            # rdtlint: allow[knob-registry] bootstrap module, stdlib-only
            spec = os.environ.get(ENV_FAULTS, "")
            # rdtlint: allow[knob-registry] bootstrap module, stdlib-only
            seed = int(os.environ.get(ENV_SEED, "0") or 0)
            if spec:
                # after reset() the registry may still hold inject()-ed
                # rules whose indices were assigned against the OLD env
                # load; start past the highest survivor so an env rule with
                # the same (seed, site, action) draws an independent stream
                start = (max(r.index for r in self._rules) + 1
                         if self._rules else 0)
                self._rules.extend(
                    parse_spec(spec, default_seed=seed, start_index=start))
            self._armed = bool(self._rules)
            self._env_loaded = True

    def inject(self, site: str, action: str, **opts) -> FaultRule:
        """Arm a rule in THIS process (spawned processes only see the env)."""
        self._ensure_env()
        with self._lock:
            opts.setdefault("index", (max(r.index for r in self._rules) + 1
                                      if self._rules else 0))
            rule = FaultRule(site=site, action=action, **opts)
            self._rules.append(rule)
            self._prog_rules.append(rule)
            self._armed = True
        return rule

    def clear(self) -> None:
        """Disarm everything, including env-loaded rules (tests)."""
        with self._lock:
            self._rules = []
            self._prog_rules = []
            self._armed = False
            self._env_loaded = True

    def reset(self) -> None:
        """Re-arm from the CURRENT env on next use, keeping inject()-ed
        rules: a harness arms programmatically and then calls init() —
        silently disarming its rule would make the chaos run test nothing."""
        with self._lock:
            self._rules = list(self._prog_rules)
            self._armed = bool(self._rules)
            self._env_loaded = False

    def rules(self) -> List[FaultRule]:
        self._ensure_env()
        with self._lock:
            return list(self._rules)

    def check(self, site: str, key: str = "") -> Optional[FaultRule]:
        """The first armed rule for ``site`` whose schedule fires on this
        call, or None. Cheap when nothing is armed (the common case). Every
        same-site rule counts the call, so stacked rules keep independent
        schedules (an earlier rule firing never shifts a later rule's nth)."""
        self._ensure_env()
        if not self._armed:  # lock-free: bool read is atomic in CPython
            return None
        with self._lock:
            if not self._rules:
                return None
            fired: Optional[FaultRule] = None
            for rule in self._rules:
                if rule.site != site:
                    continue
                # register on every rule (independent schedules), but claim
                # only the winner — a loser keeps its once-sentinel unclaimed
                # so the missed fire is observable, not silently swallowed
                if rule.register_call(key) and fired is None and rule.claim():
                    fired = rule
        if fired is not None:
            # outside the lock: the hook may take the telemetry lock
            _notify_fire(site, key, fired.action)
        return fired


_plane = FaultPlane()

# module-level facade ---------------------------------------------------------
inject = _plane.inject
clear = _plane.clear
reset = _plane.reset
rules = _plane.rules
check = _plane.check


def active() -> bool:
    return bool(_plane.rules())


def crash_process(code: int = CRASH_EXIT_CODE) -> None:
    """Die abruptly, bypassing atexit/finally — the node-kill analogue."""
    os._exit(code)


def apply(rule: FaultRule, site: str = "", nbytes: int = 0) -> None:
    """Execute a generic action (``crash``/``delay``/``raise``). Site-specific
    actions (``drop``, ``connloss``) are interpreted by their call sites and
    ignored here, so a site can safely route every fired rule through apply()
    after handling its own. ``nbytes`` lets a data-plane site scale a delay
    by the payload it moves (``ms_per_mb=``)."""
    if rule.action == "crash":
        crash_process()
    elif rule.action == "delay":
        # an injected delay IS the fault: chaos schedules deliberately stall
        # the serving thread to model a slow peer, bounded by ms/ms_per_mb
        # rdtlint: allow[dispatcher-blocking] injected delay is the fault
        time.sleep((rule.ms + rule.ms_per_mb * nbytes / float(1 << 20))
                   / 1000.0)
    elif rule.action == "raise":
        raise InjectedFault(
            f"injected fault at {site or rule.site} (rule {rule.action})")


class InjectedFault(RuntimeError):
    """The generic ``raise`` action. Deliberately NOT in the engine's no-retry
    set: an injected raise models a transient fault, so task retry absorbs it."""
