"""Central registry of every ``RDT_*`` environment knob.

Knobs accumulated across the repo one PR at a time — opt-outs, thresholds,
budgets, grace periods — and each one carried its own ad-hoc ``os.environ``
read with its own parsing quirks and its own chance of doc drift. This module
is the single source of truth: every knob's **name, type, default, and read
scope** is declared here, every runtime read goes through :func:`get` (or
:func:`require` for framework-injected values that must exist), and the doc
tables in ``doc/etl.md`` / ``doc/training.md`` are GENERATED from this
registry (``python -m raydp_tpu.knobs --write-docs``).

The project linter (``raydp_tpu/tools/rdtlint``, rule ``knob-registry``)
enforces the contract statically:

- a direct ``os.environ`` read of an ``RDT_*`` name anywhere else in the
  package is a violation (the PR 3 ``RDT_FAULTS`` re-arm bug class started as
  exactly such a scattered read);
- reading a **per-action** knob at import time (module or class scope, or a
  function default) is a violation — per-action knobs exist so tests and
  benches can flip them at runtime, and an import-time cache silently pins
  the first value a process ever saw;
- the generated doc tables must match this registry byte-for-byte.

Read scopes:

- ``per-action`` — re-read from the environment at every use (every engine
  action, every feed/iterator construction, every stage). Flipping the env
  var mid-session takes effect on the next action.
- ``process-start`` — read once per process (at import, process bootstrap,
  or session init). Changing the env var requires a new process (for
  ``RDT_FAULTS``: a new :func:`raydp_tpu.init`, which re-arms the plane).

This module must stay stdlib-only with no ``raydp_tpu`` imports: it is read
by bootstrap paths (node agents, rank workers) and loaded standalone by the
linter without spinning up the runtime.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional

PER_ACTION = "per-action"
PROCESS_START = "process-start"

#: the truthiness convention every boolean knob shares (``RDT_X=0`` /
#: ``false`` / ``off`` / ``no`` disables; anything else — including the
#: conventional ``1`` — enables)
_FALSY = ("0", "false", "off", "no")


@dataclass(frozen=True)
class Knob:
    """One declared environment knob."""

    name: str
    type: str          # "bool" | "int" | "float" | "str"
    default: object    # typed default; None = unset (or computed at the site)
    scope: str         # PER_ACTION | PROCESS_START
    category: str      # "etl" | "training" | "serving" | "stream"
                       # | "runtime" | "faults" | "spmd"
    doc: str           # one-line description for the generated doc tables
    #: framework-injected IPC value (set by the head/agent/submit wrapper for
    #: child processes), not a user-facing tuning knob
    internal: bool = False
    #: display override for computed defaults (e.g. "sized from /dev/shm")
    default_doc: str = ""

    def parse(self, raw: str) -> object:
        if self.type == "bool":
            return raw.strip().lower() not in _FALSY
        if self.type == "int":
            # int(float(...)) so "8e6"-style and "2048.0"-style values work
            return int(float(raw))
        if self.type == "float":
            return float(raw)
        return raw


def _k(name: str, type: str, default: object, scope: str, category: str,
       doc: str, **kw) -> Knob:
    return Knob(name=name, type=type, default=default, scope=scope,
                category=category, doc=doc, **kw)


#: declaration order is presentation order in the generated tables
_ALL = [
    # ---- ETL engine ---------------------------------------------------------
    _k("RDT_ETL_OPTIMIZER", "bool", True, PER_ACTION, "etl",
       "Rule-based logical-plan optimizer (projection pruning + predicate "
       "pushdown); 0 preserves the naive compile-verbatim path."),
    _k("RDT_ETL_AQE", "bool", True, PER_ACTION, "etl",
       "Adaptive query execution: runtime re-planning from measured stage "
       "statistics (broadcast join, skew split, coalesce)."),
    _k("RDT_AQE_BROADCAST_MAX", "int", 8 << 20, PER_ACTION, "etl",
       "Broadcast-hash-join threshold: a join side whose measured bytes fit "
       "under this replicates instead of shuffling. 0 disables the rule."),
    _k("RDT_AQE_SKEW_FACTOR", "float", 4.0, PER_ACTION, "etl",
       "Skew trigger: a reduce bucket larger than this multiple of the "
       "(lower) median bucket splits across reduce tasks. 0 disables."),
    _k("RDT_AQE_COALESCE_MIN", "int", 1 << 20, PER_ACTION, "etl",
       "Coalescing target: adjacent reduce buckets fuse until their combined "
       "bytes reach this; also the floor under which a bucket never "
       "skew-splits. 0 disables."),
    _k("RDT_SHUFFLE_CONSOLIDATE", "bool", True, PER_ACTION, "etl",
       "Consolidated map outputs: one store blob per map task with a "
       "per-bucket byte-range index; 0 restores per-bucket blobs."),
    _k("RDT_SHUFFLE_PIPELINE", "bool", True, PER_ACTION, "etl",
       "Pipelined (push-based) shuffle: reducers stream ranges as maps seal. "
       "Needs the consolidated index, so RDT_SHUFFLE_CONSOLIDATE=0 disables "
       "it too."),
    _k("RDT_LINEAGE_RECOVERY", "bool", True, PER_ACTION, "etl",
       "Lineage rebuild of lost intermediates; 0 surfaces losses as stage "
       "failures."),
    _k("RDT_LINEAGE_ROUNDS", "int", 4, PER_ACTION, "etl",
       "Recovery rounds per stage (each round may regenerate several "
       "blobs)."),
    _k("RDT_LINEAGE_DEPTH", "int", 4, PER_ACTION, "etl",
       "Max transitive producer-of-producer regeneration depth."),
    _k("RDT_EXECUTOR_WAIT_S", "float", 60.0, PER_ACTION, "etl",
       "Wall-clock grace a stage keeps probing for a reachable executor "
       "(sized for restart spawn + jax import) before failing."),
    _k("RDT_SPECULATION", "bool", True, PER_ACTION, "etl",
       "Speculative backup tasks for stragglers; first finisher wins, the "
       "loser's outputs are freed."),
    _k("RDT_SPECULATION_QUANTILE", "float", 0.75, PER_ACTION, "etl",
       "Completion fraction a stage must reach before backups are "
       "considered."),
    _k("RDT_SPECULATION_MULTIPLIER", "float", 1.5, PER_ACTION, "etl",
       "A pending attempt is a straggler past this multiple of the "
       "completed-task median runtime."),
    _k("RDT_SPECULATION_MIN_S", "float", 1.0, PER_ACTION, "etl",
       "Floor on the straggler threshold: sub-second stages never "
       "speculate."),
    # ---- elastic executor pool ----------------------------------------------
    _k("RDT_POOL_MIN", "int", 1, PER_ACTION, "etl",
       "Autoscale floor: the controller never drains the pool below this "
       "many live executors."),
    _k("RDT_POOL_MAX", "int", 0, PER_ACTION, "etl",
       "Autoscale ceiling: the controller never grows past this. 0 keeps "
       "the pool fixed at its session size (autoscaling must be asked for "
       "explicitly via Session.autoscale(max_size=...))."),
    _k("RDT_POOL_SCALE_INTERVAL_S", "float", 1.0, PER_ACTION, "etl",
       "Autoscale controller tick period (load is sampled once per tick)."),
    _k("RDT_POOL_SCALE_UP_S", "float", 2.0, PER_ACTION, "etl",
       "Sustained queue-depth window before the controller grows the pool "
       "(a single recovery-induced spike never spawns an executor)."),
    _k("RDT_POOL_IDLE_S", "float", 10.0, PER_ACTION, "etl",
       "Sustained fully-idle window before the controller drains an "
       "executor back out."),
    _k("RDT_POOL_COOLDOWN_S", "float", 5.0, PER_ACTION, "etl",
       "Hysteresis: no further scale decision for this long after any "
       "grow/shrink event."),
    _k("RDT_DRAIN_REHOME", "bool", True, PER_ACTION, "etl",
       "Graceful drain re-homes a retiring executor's cached blocks onto "
       "survivors (rebuilt from their lineage recipes); 0 abandons them to "
       "on-read lineage recovery instead."),
    _k("RDT_DRAIN_TIMEOUT_S", "float", 30.0, PER_ACTION, "etl",
       "How long a drain waits for the retiring executor's in-flight tasks "
       "before abandoning them to the normal retry/recovery machinery."),
    # ---- multi-tenant overload robustness -----------------------------------
    _k("RDT_POOL_TENANT_WEIGHT", "float", 1.0, PER_ACTION, "etl",
       "Fair-share weight of this action's tenant: under contention each "
       "tenant's in-flight share tracks weight/sum(weights). Engine-level "
       "tenant_weight= overrides per tenant."),
    _k("RDT_POOL_MAX_QUEUED", "int", 0, PER_ACTION, "etl",
       "Admission bound on the pool's queued (admitted, not yet in-flight) "
       "backlog: an action that would push past it parks at admission — "
       "visible to the autoscaler — instead of flooding dispatch. 0 "
       "disables admission control."),
    _k("RDT_ADMIT_TIMEOUT_S", "float", 30.0, PER_ACTION, "etl",
       "How long an action parks at admission before failing with the "
       "typed, no-retry AdmissionRejected."),
    _k("RDT_STORE_HIGH_WATERMARK", "float", 1.25, PER_ACTION, "etl",
       "Memory backpressure trip point: dispatch to a host whose store "
       "shm use exceeds this fraction of its budget pauses (spill is not "
       "keeping up). <= 0 disables backpressure."),
    _k("RDT_STORE_LOW_WATERMARK", "float", 0.95, PER_ACTION, "etl",
       "Memory backpressure release point: a paused host re-enters "
       "dispatch once its shm use drops below this fraction of its "
       "budget."),
    # ---- data-gravity scheduling / AQE-fed store budgets --------------------
    _k("RDT_LOCALITY_SPILLED_WEIGHT", "float", 0.5, PER_ACTION, "etl",
       "Locality weight multiplier for bytes whose local copy is SPILLED "
       "to disk: a spilled-local host scores between in-memory-local (1.0) "
       "and remote (0) — reading spilled bytes pays a fault-in wherever "
       "the task lands, so disk-local placement is a smaller win. 0 makes "
       "spilled bytes count as absent; 1 restores tier-blind weighting."),
    _k("RDT_LOCALITY_REMOTE_WEIGHT", "float", 0.25, PER_ACTION, "etl",
       "Locality weight multiplier for a task's bytes held on OTHER "
       "dispatchable hosts (remote in-memory residency tier): every live "
       "host is credited remote bytes x this, so when the byte-holding "
       "host is draining or backpressured the ranking still prefers a "
       "real host instead of returning no preference. 0 restores the "
       "holder-only ranking; 1 scores remote copies like local ones "
       "(distance-blind)."),
    _k("RDT_STORE_STAGE_HINTS", "bool", True, PER_ACTION, "etl",
       "Stage-aware eviction: each stage pins its input blobs in the "
       "store for its duration and demotes them to evict-first when it "
       "completes, so LRU only breaks ties among blobs no stage is "
       "reading. 0 restores pure-LRU spill order."),
    _k("RDT_STORE_AQE_BUDGET", "bool", True, PER_ACTION, "etl",
       "Re-derive per-host store budgets from the AQE plane's measured "
       "stage bytes (clamped to the statically configured capacity), so "
       "cold bytes spill ahead of demand when the measured working set is "
       "smaller than the static budget. 0 keeps static budgets only."),
    _k("RDT_STORE_BUDGET_HEADROOM", "float", 1.5, PER_ACTION, "etl",
       "Multiplier on the measured per-stage bytes when deriving store "
       "budgets (derived = min(static capacity, measured x headroom))."),
    _k("RDT_POOL_BYTES_PER_EXEC", "int", 0, PER_ACTION, "etl",
       "Predictive autoscale: measured per-stage bytes each executor is "
       "expected to carry; a grow decision targets ceil(measured stage "
       "bytes / this) executors (capped by RDT_POOL_MAX). 0 disables the "
       "byte-driven component (parked-demand sizing stays on)."),
    # ---- training / feed ----------------------------------------------------
    _k("RDT_PREFETCH_TO_DEVICE", "int", 2, PER_ACTION, "training",
       "Already-device_put batches the streaming feed keeps ahead of the "
       "train step (0 = place synchronously)."),
    _k("RDT_FEED_CACHE_MB", "float", 2048.0, PER_ACTION, "training",
       "Per-iterator budget (MiB) for the decoded-block host cache reused "
       "across epochs."),
    _k("RDT_DEVICE_CACHE", "bool", True, PER_ACTION, "training",
       "Device-resident dataset cache opt-out (0 always streams batches)."),
    _k("RDT_DEVICE_CACHE_MB", "float", 2048.0, PER_ACTION, "training",
       "HBM budget (MiB) under which a dataset is eligible for full "
       "device residency."),
    _k("RDT_STAGE_THREADS", "int", 1, PER_ACTION, "training",
       "Column fan-out threads of the native staging core (host decode)."),
    _k("RDT_TRAIN_SHARD_ROLES", "bool", True, PER_ACTION, "training",
       "Role-driven parameter sharding (embeddings over fsdp×tensor, "
       "kernels over fsdp/tensor by dimension, biases replicated) for "
       "leaves no param_rules entry matches; 0 restores the legacy "
       "largest-divisible-dim fsdp fallback."),
    _k("RDT_TRAIN_PAD_TAIL", "bool", True, PER_ACTION, "training",
       "Pad-and-mask the ragged final batch under a >1 data extent (or a "
       ">1 stage extent — the pipelined forward reshapes every batch into "
       "microbatches): zero rows square the batch and a mask drops them "
       "from losses/metrics. 0 restores the silent tail drop."),
    _k("RDT_TRAIN_ACCUM_STEPS", "int", 1, PER_ACTION, "training",
       "Gradient-accumulation microbatches per optimizer step: each global "
       "batch splits into this many slices scanned through the forward/"
       "backward before one update, dividing peak activation bytes by the "
       "same factor. Must divide batch_size; the estimator accum_steps= "
       "argument overrides."),
    _k("RDT_TRAIN_REMAT", "str", "none", PER_ACTION, "training",
       "Rematerialization policy for the train-step forward (jax.checkpoint "
       "placement by role, parallel/roles.py): a global mode — 'dots' keeps "
       "MXU products (kernel/embedding contractions) and recomputes "
       "elementwise glue; 'full' recomputes everything; 'none' saves all "
       "residuals — or a per-role 'role=mode,...' map over the param roles "
       "('embedding=none,kernel=dots,default=full'), chosen per segment by "
       "its dominant parameter role; a bare mode is the default policy for "
       "every role. Validated eagerly, before any compile."),
    # ---- serving plane ------------------------------------------------------
    _k("RDT_SERVE_MAX_BATCH", "int", 64, PER_ACTION, "serving",
       "Micro-batch row cap: concurrent predict() requests coalesce into "
       "one replica dispatch up to this many rows. Read at serving-session "
       "construction."),
    _k("RDT_SERVE_BATCH_TIMEOUT_MS", "float", 5.0, PER_ACTION, "serving",
       "Latency budget a partially-filled micro-batch waits for more rows "
       "before dispatching anyway."),
    _k("RDT_SERVE_MAX_INFLIGHT", "int", 2, PER_ACTION, "serving",
       "Per-replica in-flight dispatch cap; dispatches queue driver-side "
       "once every ready replica is at its cap."),
    _k("RDT_SERVE_HEDGE", "bool", True, PER_ACTION, "serving",
       "Hedged requests: a dispatch older than the hedge deadline is "
       "duplicated onto a second replica; first responder wins, the "
       "loser's result is discarded and counted."),
    _k("RDT_SERVE_HEDGE_QUANTILE", "float", 0.9, PER_ACTION, "serving",
       "Completed-batch latency quantile the hedge deadline is computed "
       "from."),
    _k("RDT_SERVE_HEDGE_MULTIPLIER", "float", 3.0, PER_ACTION, "serving",
       "Hedge deadline = this multiple of the latency quantile."),
    _k("RDT_SERVE_HEDGE_MIN_MS", "float", 20.0, PER_ACTION, "serving",
       "Floor under the hedge deadline: dispatches younger than this "
       "never hedge."),
    _k("RDT_SERVE_REROUTE_GRACE_S", "float", 60.0, PER_ACTION, "serving",
       "Wall-clock grace a failed/unroutable dispatch keeps re-routing "
       "across replicas (sized for an executor restart + replica reload) "
       "before failing the request."),
    _k("RDT_SERVE_PREFETCH", "int", 2, PER_ACTION, "serving",
       "Staged batches a replica keeps decoded + device-placed ahead of "
       "its jitted apply (the DevicePrefetcher depth). Read at replica "
       "load."),
    _k("RDT_SERVE_MAX_QUEUE", "int", 1024, PER_ACTION, "serving",
       "Overload bound on outstanding (accepted, unfinished) requests: "
       "past it predict_async sheds with the typed retriable "
       "ServingOverloaded instead of growing the dispatcher queue, and "
       "hedging is suppressed while saturated. 0 disables shedding. Read "
       "at serving-session construction."),
    _k("RDT_SERVE_SWAP_DRAIN_S", "float", 30.0, PER_ACTION, "serving",
       "How long a hot-swap's background retirement waits for the OLD "
       "servable's in-flight dispatches to drain before unloading it "
       "anyway (in-flight requests on it still complete; the registry "
       "entry just goes away)."),
    _k("RDT_SERVE_CANARY_WEIGHT", "float", 0.1, PER_ACTION, "serving",
       "Traffic share a guarded rollout gives the canary version the "
       "moment it loads (the first ramp step). Read per rollout."),
    _k("RDT_SERVE_ROLLOUT_RAMP", "str", "0.25,0.5,1.0", PER_ACTION,
       "serving",
       "Comma-separated non-decreasing weight schedule a rollout ramps "
       "the canary through after the initial canary weight, each step "
       "judged healthy before the next."),
    _k("RDT_SERVE_ROLLOUT_STEP_S", "float", 30.0, PER_ACTION, "serving",
       "Longest a rollout holds one ramp step waiting for the judgment "
       "window to fill; a step that times out without evidence either "
       "way advances (insufficient traffic is not a regression)."),
    _k("RDT_SERVE_ROLLOUT_MIN_SAMPLES", "int", 32, PER_ACTION, "serving",
       "Step-local requests BOTH the canary and the baseline must have "
       "answered before a health verdict is allowed — a one-request "
       "blip must not kill a deploy."),
    _k("RDT_SERVE_ROLLOUT_ERR_TOL", "float", 0.02, PER_ACTION, "serving",
       "Absolute error-rate margin the canary may exceed the baseline "
       "by within a ramp step before the rollout rolls back."),
    _k("RDT_SERVE_ROLLOUT_P99_FACTOR", "float", 2.0, PER_ACTION,
       "serving",
       "Multiple of the baseline's per-version p99 the canary's p99 "
       "must exceed (with full windows on both sides) before the "
       "rollout rolls back on latency."),
    _k("RDT_SERVE_MIN_REPLICAS", "int", 1, PER_ACTION, "serving",
       "Serving-autoscaler floor on per-version replica count."),
    _k("RDT_SERVE_MAX_REPLICAS", "int", 4, PER_ACTION, "serving",
       "Serving-autoscaler ceiling on per-version replica count."),
    _k("RDT_SERVE_SCALE_INTERVAL_S", "float", 1.0, PER_ACTION, "serving",
       "Seconds between serving-autoscaler ticks (each tick reads one "
       "serving_report and decides at most one scale event)."),
    _k("RDT_SERVE_SCALE_UP_S", "float", 3.0, PER_ACTION, "serving",
       "Sustained dispatch pressure (queue depth beyond replica "
       "capacity, or the admission queue half full) required before the "
       "serving autoscaler adds a replica — a momentary spike never "
       "scales by itself."),
    _k("RDT_SERVE_SCALE_IDLE_S", "float", 30.0, PER_ACTION, "serving",
       "Sustained full idleness (zero queued, zero outstanding) before "
       "the serving autoscaler drains a replica back."),
    _k("RDT_SERVE_SCALE_COOLDOWN_S", "float", 10.0, PER_ACTION,
       "serving",
       "Hysteresis after any serving scale event: no further scale "
       "decisions until it passes (sustained windows keep accumulating "
       "through it)."),
    # ---- continuous pipelines -----------------------------------------------
    _k("RDT_STREAM_RETAIN", "int", 64, PER_ACTION, "stream",
       "Epochs of replay state a continuous pipeline keeps: the source "
       "journal and the published epoch blobs of the newest N epochs stay "
       "available for exactly-once replay / late ranged-fetch; older "
       "epochs are freed as the stream advances."),
    _k("RDT_STREAM_REPLAY_ROUNDS", "int", 4, PER_ACTION, "stream",
       "Replay rounds a window merge (or epoch-stream fetch) attempts when "
       "an epoch blob is lost (ObjectLostError): each round re-derives the "
       "lost epochs from the source journal and re-seals them."),
    _k("RDT_STREAM_POLL_TIMEOUT_S", "float", 10.0, PER_ACTION, "stream",
       "Longest a pipeline step blocks on its source before re-checking "
       "for stop/close (idle tick; the source may return rows sooner)."),
    _k("RDT_STREAM_EXPORT_EVERY", "int", 0, PER_ACTION, "stream",
       "Default epochs between partial_fit servable exports (and hot-swaps "
       "when a serving session is attached). 0 disables the cadence; the "
       "partial_fit export_every= argument overrides."),
    _k("RDT_STREAM_MAX_PARTITIONS", "int", 0, PER_ACTION, "stream",
       "Partitions each micro-batch epoch is split into before its engine "
       "action (0 = auto: min(executors, rows))."),
    _k("RDT_STREAM_ROLLOUT", "bool", False, PER_ACTION, "stream",
       "Ship partial_fit exports through a guarded rollout (canary ramp "
       "+ auto-rollback, doc/serving.md) instead of an immediate "
       "hot_swap. The partial_fit rollout= argument overrides; rollouts "
       "block on serving traffic, so the default stays the atomic "
       "swap."),
    # ---- runtime ------------------------------------------------------------
    _k("RDT_LOG_LEVEL", "str", "INFO", PROCESS_START, "runtime",
       "Log level of spawned processes (node agents, SPMD rank workers)."),
    _k("RDT_DRIVER_REAP_S", "float", 60.0, PROCESS_START, "runtime",
       "Heartbeat silence after which an attached driver's actors and owned "
       "objects are reaped by the head."),
    _k("RDT_ARENA_FREE_GRACE_S", "float", 60.0, PROCESS_START, "runtime",
       "Seconds an arena-resident payload stays mapped after its free "
       "(borrowed zero-copy views may still be live)."),
    _k("RDT_PROFILER_MAX_SPANS", "int", 100000, PROCESS_START, "runtime",
       "Bound on retained trace spans per process."),
    _k("RDT_FLIGHT_MAX_EVENTS", "int", 1024, PROCESS_START, "runtime",
       "Bound on the per-process flight-recorder event ring "
       "(doc/observability.md); evictions are counted, never silent."),
    _k("RDT_STORE_ISOLATED", "bool", False, PROCESS_START, "runtime",
       "Force a node agent to host its own payload plane even on the head's "
       "machine (the multi-host store topology, in tests)."),
    _k("RDT_NODE_SHM_BUDGET", "int", None, PROCESS_START, "runtime",
       "Shared-memory budget (bytes) of an isolated node's store host; "
       "objects past it LRU-spill to disk.",
       default_doc="node arena size (1 GiB fallback)"),
    _k("RDT_NODE_ARENA_SIZE", "int", None, PROCESS_START, "runtime",
       "Size (bytes) of an isolated node's store arena.",
       default_doc="sized from /dev/shm"),
    _k("RDT_STORE_HOST_ID", "str", "head", PROCESS_START, "runtime",
       "Which machine's payload plane this process writes to.",
       internal=True),
    _k("RDT_STORE_PAYLOAD_ADDR", "str", None, PROCESS_START, "runtime",
       "RPC address of this machine's payload server (None = the head).",
       internal=True),
    _k("RDT_STORE_ARENA", "str", None, PROCESS_START, "runtime",
       "Shared-memory segment name of the machine-local store arena.",
       internal=True),
    _k("RDT_SUBMIT_ARGS", "str", None, PROCESS_START, "runtime",
       "JSON config packaged by rdt-submit; fills init() arguments left at "
       "their defaults.", internal=True),
    # ---- warm-start executors -----------------------------------------------
    _k("RDT_WARM_FORK", "bool", False, PER_ACTION, "runtime",
       "Fork new workers from a pre-imported prototype process instead of "
       "cold-spawning a fresh interpreter: scale-up readiness goes from "
       "~seconds of jax/pyarrow import to process-fork-fast. Any warm-fork "
       "failure degrades loudly to the cold-spawn path."),
    _k("RDT_WARM_IMPORTS", "str", "pyarrow,pandas,numpy,cloudpickle,jax",
       PROCESS_START, "runtime",
       "Comma-separated modules the warm-fork prototype pre-imports; a "
       "module that fails to import is skipped with a warning (the fork "
       "still works, just colder)."),
    _k("RDT_WARM_FORK_WAIT_S", "float", 15.0, PER_ACTION, "runtime",
       "How long a spawn waits for the warm-fork prototype's readiness "
       "handshake before falling back to cold spawn."),
    _k("RDT_WARM_FORK_RETRIES", "int", 2, PER_ACTION, "runtime",
       "Supervised prototype restarts after a warm-fork plane failure: a "
       "latched-failed plane re-warms a fresh prototype on the next fork "
       "request, up to this many times per manager (0 keeps the "
       "latch-permanent pre-r20 behavior). Each re-warm emits a warm_fork "
       "re-warm event and counts pool_warm_refreshes_total."),
    _k("RDT_WARM_REFRESH_COOLDOWN_S", "float", 30.0, PER_ACTION, "runtime",
       "Minimum seconds between warm-fork prototype restarts: fork "
       "requests inside the cooldown go straight to cold spawn instead of "
       "hammering a crashing prototype."),
    _k("RDT_WARM_FORKED", "bool", False, PROCESS_START, "runtime",
       "Set by the warm-fork plane in forked workers (telemetry reports "
       "it as spawn provenance).", internal=True),
    # ---- fault plane --------------------------------------------------------
    _k("RDT_FAULTS", "str", None, PROCESS_START, "faults",
       "Declarative fault-injection spec (doc/fault_tolerance.md); loaded "
       "once per process, re-armed by raydp_tpu.init()."),
    _k("RDT_FAULTS_SEED", "int", 0, PROCESS_START, "faults",
       "Global default PRNG seed for probability-scheduled fault rules."),
    # ---- SPMD gang plumbing -------------------------------------------------
    _k("RDT_SPMD_JOB_ID", "str", None, PROCESS_START, "spmd",
       "Gang job id of an SPMD rank worker.", internal=True),
    _k("RDT_SPMD_DRIVER", "str", None, PROCESS_START, "spmd",
       "RPC url of the gang driver a rank worker reports to.",
       internal=True),
    _k("RDT_SPMD_RANK", "int", None, PROCESS_START, "spmd",
       "This worker's rank in the gang.", internal=True),
    _k("RDT_SPMD_WORLD_SIZE", "int", None, PROCESS_START, "spmd",
       "Gang world size.", internal=True),
    _k("RDT_SPMD_COORDINATOR", "str", None, PROCESS_START, "spmd",
       "jax.distributed coordinator address override.", internal=True),
    _k("RDT_SPMD_JAX_DISTRIBUTED", "bool", False, PROCESS_START, "spmd",
       "Whether a rank worker calls jax.distributed.initialize().",
       internal=True),
]

KNOBS: Dict[str, Knob] = {k.name: k for k in _ALL}
assert len(KNOBS) == len(_ALL), "duplicate knob declaration"


def get(name: str):
    """The typed value of knob ``name`` read from the environment NOW, or
    its declared default when unset or empty (empty string = unset, so
    ``RDT_X= python ...`` behaves like an absent var, never a parse error).

    Call-time reads are what keep per-action semantics: call sites must not
    stash the result at import time (rule ``knob-registry`` flags it)."""
    knob = KNOBS[name]
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return knob.default
    return knob.parse(raw)


def get_raw(name: str) -> Optional[str]:
    """The raw environment string of a declared knob (None when unset).
    For sites that need the unparsed value (e.g. JSON payloads)."""
    KNOBS[name]  # unknown name must fail loudly, same as get()
    return os.environ.get(name)


def require(name: str):
    """Like :func:`get` but raises when the var is unset — for
    framework-injected values (SPMD rank plumbing) whose absence means the
    process was launched outside its harness."""
    knob = KNOBS[name]
    raw = os.environ.get(name)
    if raw is None:
        raise KeyError(
            f"{name} is not set — this process expects it injected by its "
            f"launcher ({knob.doc})")
    return knob.parse(raw)


# ---- generated doc tables ---------------------------------------------------

def _default_cell(knob: Knob) -> str:
    if knob.default is None:
        return knob.default_doc or "unset"
    if knob.type == "bool":
        return f"`{'1' if knob.default else '0'}`"
    return f"`{knob.default}`"


def generate_table(category: Optional[str] = None) -> str:
    """Markdown knob table for one category (None = the full registry).
    The doc blocks between ``rdtlint:knob-table`` markers are exactly this
    output; rule ``knob-registry`` fails on any drift."""
    rows = [k for k in _ALL if category is None or k.category == category]
    lines = ["| Knob | Type | Default | Read | Description |",
             "| --- | --- | --- | --- | --- |"]
    for k in rows:
        doc = k.doc + (" *(framework-injected)*" if k.internal else "")
        lines.append(f"| `{k.name}` | {k.type} | {_default_cell(k)} | "
                     f"{k.scope} | {doc} |")
    return "\n".join(lines)


#: which doc file carries which category's generated table; dev_lint.md
#: carries the full registry
DOC_TABLES = (
    ("doc/etl.md", "etl"),
    ("doc/training.md", "training"),
    ("doc/serving.md", "serving"),
    ("doc/streaming.md", "stream"),
    ("doc/dev_lint.md", None),
)

_BEGIN = "<!-- rdtlint:knob-table:begin {tag} -->"
_END = "<!-- rdtlint:knob-table:end -->"


def table_markers(category: Optional[str]) -> tuple:
    return _BEGIN.format(tag=category or "all"), _END


def render_block(category: Optional[str]) -> str:
    begin, end = table_markers(category)
    return f"{begin}\n{generate_table(category)}\n{end}"


def write_doc_tables(root: str) -> list:
    """Rewrite every marker block under ``root`` from the registry; returns
    the files changed. Used by ``python -m raydp_tpu.knobs --write-docs``."""
    changed = []
    for rel, category in DOC_TABLES:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            continue
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        begin, end = table_markers(category)
        if begin not in text or end not in text:
            continue
        head, rest = text.split(begin, 1)
        _, tail = rest.split(end, 1)
        new = head + render_block(category) + tail
        if new != text:
            with open(path, "w", encoding="utf-8") as f:
                f.write(new)
            changed.append(rel)
    return changed


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m raydp_tpu.knobs",
        description="print or regenerate the RDT_* knob tables")
    ap.add_argument("--write-docs", action="store_true",
                    help="rewrite the generated doc tables in place")
    ap.add_argument("--root", default=".",
                    help="repo root holding doc/ (default: cwd)")
    args = ap.parse_args(argv)
    if args.write_docs:
        for rel in write_doc_tables(args.root):
            print(f"rewrote {rel}")
        return 0
    print(generate_table())
    return 0


if __name__ == "__main__":  # pragma: no cover - thin CLI shim
    raise SystemExit(main())
