"""Structured logging.

The reference ships a whole logging-interop subsystem because Spark's log4j and
Ray's log4j2 collide inside one JVM (reference: core/agent/Agent.java:41-98,
versions.py:22-35, SparkOnRayConfigs.java:56-96). Our runtime is all-Python/C++ so
the equivalent is much simpler: one process-tagged formatter, per-actor log files
under the session log dir, and a ``:job_id:``-style prefix so log shippers can
attribute executor output to a session (Agent.java writes the same marker for Ray's
log monitor).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_FORMAT = "%(asctime)s %(levelname)s [%(raydp_role)s pid=%(process)d] %(name)s: %(message)s"


class _RoleFilter(logging.Filter):
    def __init__(self, role: str):
        super().__init__()
        self.role = role

    def filter(self, record):
        record.raydp_role = self.role
        return True


def init_logging(
    role: str = "driver",
    level: str = "INFO",
    log_dir: Optional[str] = None,
    session_id: Optional[str] = None,
) -> logging.Logger:
    """Configure the ``raydp_tpu`` logger tree for this process.

    ``role`` is e.g. ``driver``, ``master``, ``executor-3``, ``worker-0`` — the
    per-process tag that replaces the reference's ``raydp-java-worker`` log prefix
    (SparkOnRayConfigs.java:119-127).
    """
    logger = logging.getLogger("raydp_tpu")
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    logger.propagate = False
    for h in list(logger.handlers):
        logger.removeHandler(h)

    fmt = logging.Formatter(_FORMAT)
    flt = _RoleFilter(role)

    sh = logging.StreamHandler(sys.stderr)
    sh.setFormatter(fmt)
    sh.addFilter(flt)
    logger.addHandler(sh)

    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        fname = f"{role}-{os.getpid()}.log"
        fh = logging.FileHandler(os.path.join(log_dir, fname))
        fh.setFormatter(fmt)
        fh.addFilter(flt)
        logger.addHandler(fh)
        if session_id:
            # session marker for log shippers (parity: Agent.java ":job_id:" line)
            logger.info(":session_id:%s", session_id)
    return logger


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(f"raydp_tpu.{name}")
    if not logging.getLogger("raydp_tpu").handlers:
        init_logging()
    return logger
