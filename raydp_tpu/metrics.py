"""Typed telemetry registries + the failure flight recorder.

The per-subsystem report dicts (``op_counts()``, ``shuffle_stage_report()``,
``serving_report()``, estimator epoch timers) grew by accretion, one PR at a
time, each with its own naming and its own collection path. This module is
the designed replacement — the ``knobs.py`` pattern applied to telemetry:

- **Metrics registry** — every counter/gauge/histogram is declared here
  (name, kind, unit, owning subsystem, one-line doc). Process-local
  increments are a dict update under one lock; per-process state is
  harvested over the existing actor RPC plane through the
  ``__rdt_metrics__`` intrinsic (beside ``__rdt_spans__``), and
  :func:`metrics_report` merges driver, executors, and node agents into one
  view that subsumes the legacy report dicts (which remain as compatible
  views over the same counters).
- **Span registry** — every literal ``profiler.trace(...)`` span name is
  declared here too; dynamic families (``task:<Step>``) are declared as
  prefixes. The ``telemetry-registry`` rdtlint rule statically checks
  literal span/metric/event names against these registries, and the tables
  in ``doc/observability.md`` are GENERATED from them
  (``python -m raydp_tpu.metrics --write-docs``).
- **Flight recorder** — a bounded per-process ring of structured events
  (faults fired, object losses, recovery rounds, re-seals, executor
  down/up, hedges, aborts). When an action surfaces a ``StageError`` /
  ``ServingError`` the driver harvests every process's ring into a
  ``blackbox-<action>.json`` postmortem bundle (:func:`write_blackbox`), so
  chaos runs leave artifacts instead of log archaeology.

This module must stay **stdlib-only at import** (the same contract as
``knobs.py``): it is loaded standalone by the linter and imported by
bootstrap-adjacent paths. Anything that needs the runtime (report merging,
blackbox harvest) imports it lazily inside the function.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

#: histograms are summary-shaped (count/sum/min/max), not bucketed: every
#: producer is a wall-clock or size observation whose tails the driver can
#: read off max, and bucket layouts would be one more thing to keep in sync
_HIST_ZERO = {"count": 0, "sum": 0.0, "min": None, "max": None}


@dataclass(frozen=True)
class Metric:
    """One declared metric."""

    name: str
    kind: str          # COUNTER | GAUGE | HISTOGRAM
    unit: str          # "1", "s", "rows", "bytes" — doc only
    subsystem: str     # "scheduler" | "store" | "serving" | ...
    doc: str
    #: the single optional label dimension ("" = unlabeled)
    label: str = ""


@dataclass(frozen=True)
class Span:
    """One declared trace-span name (or a dynamic family prefix)."""

    name: str
    subsystem: str
    doc: str
    #: True = ``name`` is a prefix of runtime-formatted span names
    #: (f-strings); the linter only checks literal names, these rows exist
    #: so the doc table is the complete span vocabulary
    dynamic: bool = False


@dataclass(frozen=True)
class Event:
    """One declared flight-recorder event kind."""

    kind: str
    subsystem: str
    doc: str


def _m(name, kind, unit, subsystem, doc, label=""):
    return Metric(name=name, kind=kind, unit=unit, subsystem=subsystem,
                  doc=doc, label=label)


#: declaration order is presentation order in the generated tables
_ALL_METRICS = [
    # ---- scheduler / engine -------------------------------------------------
    _m("sched_tasks_dispatched_total", COUNTER, "1", "scheduler",
       "Task attempts submitted to executors (retries and speculative "
       "backups included).", label="executor"),
    _m("sched_speculated_total", COUNTER, "1", "scheduler",
       "Tasks that received a speculative backup."),
    _m("sched_speculation_won_total", COUNTER, "1", "scheduler",
       "Tasks whose speculative backup finished first."),
    _m("sched_executor_down_total", COUNTER, "1", "scheduler",
       "Times an executor was marked unreachable by task placement.",
       label="executor"),
    _m("sched_executor_up_total", COUNTER, "1", "scheduler",
       "Times a down-marked executor answered again and re-entered task "
       "placement (the executor_down symmetry).", label="executor"),
    _m("pool_size", GAUGE, "1", "scheduler",
       "Live executors in the elastic pool (draining members excluded)."),
    _m("pool_drains_total", COUNTER, "1", "scheduler",
       "Graceful executor drains started (retire_executor / autoscale "
       "scale-down)."),
    _m("pool_scaled_up_total", COUNTER, "1", "scheduler",
       "Executors the autoscale controller added to the pool."),
    _m("pool_scaled_down_total", COUNTER, "1", "scheduler",
       "Executors the autoscale controller drained out of the pool."),
    _m("sched_tenant_dispatched_total", COUNTER, "1", "scheduler",
       "Task attempts dispatched per tenant (the fair-share observability "
       "column: under contention the per-tenant rates track the "
       "configured weights).", label="tenant"),
    _m("pool_admission_parked_total", COUNTER, "1", "scheduler",
       "Actions that parked at admission because the pool's queued "
       "backlog exceeded RDT_POOL_MAX_QUEUED.", label="tenant"),
    _m("pool_admission_rejects_total", COUNTER, "1", "scheduler",
       "Actions failed with AdmissionRejected after parking past "
       "RDT_ADMIT_TIMEOUT_S.", label="tenant"),
    _m("pool_backpressure_total", COUNTER, "1", "scheduler",
       "Times dispatch to a host paused on the store high-watermark "
       "(memory backpressure trip transitions, not per-task skips).",
       label="host"),
    _m("sched_locality_hits_total", COUNTER, "1", "scheduler",
       "Task attempts dispatched to their locality-preferred executor "
       "(data-gravity scheduling landed the task where its bytes are)."),
    _m("pool_warm_forks_total", COUNTER, "1", "scheduler",
       "Workers spawned by forking the pre-imported warm-start prototype "
       "instead of cold-spawning a fresh interpreter."),
    _m("pool_warm_refreshes_total", COUNTER, "1", "scheduler",
       "Supervised warm-fork prototype restarts: a latched-failed plane "
       "re-warmed a fresh prototype (bounded by RDT_WARM_FORK_RETRIES) and "
       "returned to fork-fast scale-up."),
    _m("recovery_rounds_total", COUNTER, "1", "recovery",
       "Lineage-recovery rounds that re-executed producers."),
    _m("recovery_blobs_regenerated_total", COUNTER, "1", "recovery",
       "Lost store blobs rebuilt through lineage recovery."),
    _m("stage_aborts_total", COUNTER, "1", "scheduler",
       "Failing stages that ran the abort contract (drain + free)."),
    _m("stream_reseals_total", COUNTER, "1", "shuffle",
       "Pipelined-shuffle seals superseded by a regenerated producer "
       "(generation > 1)."),
    # ---- object store -------------------------------------------------------
    _m("store_ops_total", COUNTER, "1", "store",
       "Store control-plane table operations (a batch call counts one), "
       "per method — the registry view of ObjectStoreServer.op_counts().",
       label="op"),
    _m("store_objects_lost_total", COUNTER, "1", "store",
       "ObjectLostError raised: a blob was gone or unreachable at read."),
    _m("store_fault_in_total", COUNTER, "1", "store",
       "Spilled payloads faulted back into shared memory on read (the "
       "disk-read side of the spill plane)."),
    # ---- tracing / telemetry plane ------------------------------------------
    _m("profiler_spans_dropped_total", COUNTER, "1", "profiler",
       "Trace spans silently evicted from the bounded per-process ring "
       "(RDT_PROFILER_MAX_SPANS) — nonzero means the timeline is "
       "truncated."),
    _m("telemetry_skipped_processes_total", COUNTER, "1", "profiler",
       "Live processes a trace/metrics/blackbox harvest could not reach — "
       "nonzero means the merged view is missing lanes."),
    _m("flightrec_events_dropped_total", COUNTER, "1", "profiler",
       "Flight-recorder events evicted from the bounded ring "
       "(RDT_FLIGHT_MAX_EVENTS)."),
    # ---- fault plane --------------------------------------------------------
    _m("faults_injected_total", COUNTER, "1", "faults",
       "Fault-injection rules fired in this process, per site.",
       label="site"),
    # ---- serving plane ------------------------------------------------------
    _m("serve_requests_total", COUNTER, "1", "serving",
       "predict()/predict_async() requests accepted by the dispatcher."),
    _m("serve_batches_total", COUNTER, "1", "serving",
       "Coalesced micro-batches dispatched to replicas."),
    _m("serve_rows_total", COUNTER, "rows", "serving",
       "Rows dispatched across all micro-batches."),
    _m("serve_hedged_total", COUNTER, "1", "serving",
       "Dispatches duplicated onto a second replica past the hedge "
       "deadline."),
    _m("serve_hedge_won_total", COUNTER, "1", "serving",
       "Hedged dispatches whose second copy responded first."),
    _m("serve_hedge_lost_total", COUNTER, "1", "serving",
       "Duplicate responses discarded after the sibling copy won."),
    _m("serve_rerouted_total", COUNTER, "1", "serving",
       "Dispatches re-routed off a failed/unreachable replica."),
    _m("serve_failed_total", COUNTER, "1", "serving",
       "Requests failed after every replica refused within the re-route "
       "grace (ServingError)."),
    _m("serve_shed_total", COUNTER, "1", "serving",
       "Requests refused at admission with the typed retriable "
       "ServingOverloaded (outstanding queue at RDT_SERVE_MAX_QUEUE)."),
    _m("serve_queue_depth", GAUGE, "1", "serving",
       "Pending + in-flight dispatcher work per serving session, refreshed "
       "on every dispatcher loop pass (an idle session reads 0).",
       label="session"),
    _m("serve_batch_occupancy_rows", HISTOGRAM, "rows", "serving",
       "Rows per dispatched micro-batch (coalescing effectiveness)."),
    _m("serve_request_seconds", HISTOGRAM, "s", "serving",
       "Per-request latency from enqueue to demuxed completion."),
    _m("serve_hot_swaps_total", COUNTER, "1", "serving",
       "Servable hot-swaps completed by a serving session (new version "
       "loaded beside the old, traffic shifted, old retired; guarded-"
       "rollout promotions count here too)."),
    _m("serve_version_requests_total", COUNTER, "1", "serving",
       "Requests answered per live servable version (label "
       "'<session>:v<N>') — the rollout judgment's traffic counter.",
       label="version"),
    _m("serve_version_failed_total", COUNTER, "1", "serving",
       "Requests failed per servable version (the rollout judgment's "
       "error-rate numerator).", label="version"),
    _m("serve_version_request_seconds", HISTOGRAM, "s", "serving",
       "Per-request latency per servable version — the per-version p99 "
       "window a guarded rollout judges the canary on.", label="version"),
    _m("serve_version_weight", GAUGE, "1", "serving",
       "Current dispatch-traffic weight of each live servable version "
       "(0 after a drop/rollback).", label="version"),
    _m("serve_version_replicas", GAUGE, "1", "serving",
       "Replica count of each live servable version (the serving "
       "autoscaler's actuator target).", label="version"),
    _m("serve_unload_failed_total", COUNTER, "1", "serving",
       "Retired replicas that still refused serve_unload at the retry "
       "deadline — their servable's weights stay pinned in that "
       "executor's RAM (loud leak counter; see the unload_failed "
       "event)."),
    _m("serve_rollouts_total", COUNTER, "1", "serving",
       "Guarded rollouts started (RolloutController.run)."),
    _m("serve_rollouts_rolled_back_total", COUNTER, "1", "serving",
       "Guarded rollouts auto-rolled-back on an unhealthy verdict (or "
       "timeout); the complement promoted."),
    _m("serve_scaled_up_total", COUNTER, "1", "serving",
       "Serving-autoscaler replica additions (every live version grows "
       "together)."),
    _m("serve_scaled_down_total", COUNTER, "1", "serving",
       "Serving-autoscaler replica drains after sustained idleness."),
    # ---- continuous pipelines -----------------------------------------------
    _m("stream_epochs_total", COUNTER, "1", "stream",
       "Micro-batch epochs a continuous pipeline completed (transform ran, "
       "result sealed + published to the epoch ledger)."),
    _m("stream_rows_total", COUNTER, "rows", "stream",
       "Input rows ingested across all continuous-pipeline epochs."),
    _m("stream_epoch_seconds", HISTOGRAM, "s", "stream",
       "Wall-clock of one micro-batch epoch (source rows in hand to sealed "
       "+ published result)."),
    _m("stream_windows_total", COUNTER, "1", "stream",
       "Windowed aggregations closed (tumbling/sliding merges over epoch "
       "partials)."),
    _m("stream_replays_total", COUNTER, "1", "stream",
       "Lost epoch blobs re-derived from the source journal "
       "(exactly-once replay rounds; each replayed epoch counts once)."),
    # ---- data feed / training -----------------------------------------------
    _m("feed_phase_seconds", HISTOGRAM, "s", "feed",
       "Feed-pipeline phase walls (decode / stage / h2d), one observation "
       "per timed section — the registry twin of PipelineTimings.",
       label="phase"),
    _m("train_epoch_seconds", HISTOGRAM, "s", "training",
       "Wall-clock of one training epoch (both estimators)."),
    _m("train_param_bytes_per_process", GAUGE, "bytes", "training",
       "Params + optimizer state resident on this process's devices after "
       "sharded placement (replicated leaves count one copy per device) — "
       "the fsdp-vs-replicated HBM headroom measure."),
    _m("train_padded_rows_total", COUNTER, "rows", "training",
       "Zero rows appended by pad-and-mask feeds to square a ragged final "
       "batch; each padded row is masked out of losses and metrics."),
    _m("train_accum_steps", GAUGE, "1", "training",
       "Gradient-accumulation microbatches per optimizer step this fit is "
       "running with (1 = unaccumulated; the RDT_TRAIN_ACCUM_STEPS / "
       "accum_steps= setting after validation)."),
    _m("train_activation_bytes_per_process", GAUGE, "bytes", "training",
       "Compiled peak temporary (activation) bytes of the train step on "
       "this process's devices, read off XLA's memory_analysis — the "
       "activation-residency measure accumulation/remat/seq-sharding "
       "drive down."),
    _m("train_pipeline_stages", GAUGE, "1", "training",
       "Pipeline stages the current fit's GPipe schedule runs over (the "
       "mesh's stage extent; set only when training a PipelineModel — the "
       "accum microbatches double as its pipeline microbatches)."),
]

METRICS: Dict[str, Metric] = {m.name: m for m in _ALL_METRICS}
assert len(METRICS) == len(_ALL_METRICS), "duplicate metric declaration"


def _s(name, subsystem, doc, dynamic=False):
    return Span(name=name, subsystem=subsystem, doc=doc, dynamic=dynamic)


_ALL_SPANS = [
    # ---- driver -------------------------------------------------------------
    _s("etl:action", "engine",
       "Root span of one engine action (collect/count/cache/materialize/"
       "random-shuffle; the action label rides in args). Mints the "
       "trace_id every downstream span of the action inherits."),
    _s("stage:run", "engine",
       "One stage dispatch: covers submits, retries, speculation, and "
       "lineage-recovery rounds — executor task spans parent here."),
    _s("shuffle:", "engine",
       "Per-stage shuffle totals, one span per wide-op stage "
       "(shuffle:<label>).", dynamic=True),
    _s("aqe:replan", "engine",
       "An adaptive-execution rule re-planned a stage."),
    _s("recover:lineage", "engine",
       "One lineage-recovery rerun of lost producers; links back into the "
       "failing action's trace."),
    _s("speculate:submit", "engine",
       "A speculative backup was submitted for a straggling attempt."),
    _s("speculate:win", "engine",
       "A speculative backup finished before the original attempt."),
    # ---- executor -----------------------------------------------------------
    _s("task:", "executor",
       "One executor task body (task:<SourceType>); child of the driver's "
       "stage:run span across the process boundary.", dynamic=True),
    _s("shuffle:map-partial", "executor",
       "Map-side partial aggregation inside a shuffle map task."),
    _s("shuffle:bucket", "executor",
       "Bucketing a map task's output table."),
    _s("shuffle:write", "executor",
       "Sealing a map task's bucket blobs into the store."),
    _s("shuffle:fetch", "executor",
       "A reduce-side ranged fetch/decode of shuffle input."),
    # ---- serving ------------------------------------------------------------
    _s("serve:predict", "serving",
       "One serving request, enqueue to demuxed completion (driver side); "
       "the batch/hedge/apply spans of its dispatch parent here."),
    _s("serve:batch", "serving",
       "One coalesced micro-batch dispatch to a replica."),
    _s("serve:hedge", "serving",
       "The duplicate dispatch of a hedged micro-batch."),
    _s("serve:apply", "serving",
       "The replica-side jitted apply of one micro-batch."),
    # ---- continuous pipelines -----------------------------------------------
    _s("stream:epoch", "stream",
       "One micro-batch epoch of a continuous pipeline: ingest, transform "
       "action, seal + ledger publish, window partials."),
    _s("stream:window", "stream",
       "One windowed-aggregation merge over the epoch partials of a "
       "closing window (including any replay rounds)."),
    # ---- training -----------------------------------------------------------
    _s("train:place", "training",
       "Sharded placement of the train state onto the mesh (host → device "
       "under each leaf's PartitionSpec; covers the initial FSDP/TP scatter "
       "or replication)."),
    _s("train:accum", "training",
       "Compilation + activation-residency analysis of the accumulated "
       "train step (the lax.scan over microbatches; covers the "
       "memory_analysis read behind train_activation_bytes_per_process)."),
    _s("train:pipeline", "training",
       "Compilation + activation-residency analysis of the pipelined "
       "(stage-stacked shard_map GPipe) train step — the train:accum twin "
       "for stage>1 fits."),
]

SPANS: Dict[str, Span] = {s.name: s for s in _ALL_SPANS}
assert len(SPANS) == len(_ALL_SPANS), "duplicate span declaration"

#: exact names literal ``profiler.trace(...)`` calls may use (the linter's
#: check set); dynamic families are prefixes of runtime-formatted names
SPAN_NAMES = frozenset(s.name for s in _ALL_SPANS if not s.dynamic)
SPAN_PREFIXES = tuple(s.name for s in _ALL_SPANS if s.dynamic)


def _e(kind, subsystem, doc):
    return Event(kind=kind, subsystem=subsystem, doc=doc)


_ALL_EVENTS = [
    _e("fault_injected", "faults",
       "A fault-injection rule fired (site, key, action) — recorded in the "
       "process where the fault executed."),
    _e("object_lost", "store",
       "An ObjectLostError was raised (object id + detail) — the read-side "
       "view of a store loss."),
    _e("recovery_round", "recovery",
       "The engine re-executed producers for lost blobs (stage, producer "
       "and blob counts)."),
    _e("stream_reseal", "shuffle",
       "A regenerated map re-sealed its publication under the next "
       "generation."),
    _e("executor_down", "scheduler",
       "Task placement marked an executor unreachable."),
    _e("executor_up", "scheduler",
       "A down-marked executor answered again and re-entered task "
       "placement (restart re-admission; the executor_down symmetry)."),
    _e("executor_drain", "scheduler",
       "An executor began a graceful drain out of the pool (deliberate "
       "retirement, never a crash)."),
    _e("pool_scale", "scheduler",
       "The autoscale controller grew or shrank the executor pool "
       "(direction + resulting size)."),
    _e("warm_fork", "scheduler",
       "A worker spawn went through (or degraded out of) the warm-fork "
       "plane: forked pid, or the failure that fell back to cold spawn."),
    _e("store_budget", "store",
       "Per-host store budgets were re-derived from the AQE plane's "
       "measured stage bytes (or the derivation degraded to the static "
       "budgets on an injected store.budget fault)."),
    _e("store_fault_in", "store",
       "A spilled payload was faulted back into shared memory on read "
       "(object id + host)."),
    _e("stage_abort", "scheduler",
       "A failing stage ran the abort contract (drain + free)."),
    _e("admission_reject", "scheduler",
       "An action parked at admission timed out (RDT_ADMIT_TIMEOUT_S) and "
       "failed with the typed no-retry AdmissionRejected."),
    _e("backpressure", "scheduler",
       "Dispatch to a host paused on the store high-watermark, or resumed "
       "below the low-watermark (memory backpressure transitions)."),
    _e("action_failed", "engine",
       "An engine action surfaced a StageError; a blackbox bundle is "
       "written alongside."),
    _e("replica_down", "serving",
       "A serving replica left the rotation (connection lost or "
       "ReplicaNotLoaded)."),
    _e("replica_up", "serving",
       "A serving replica reloaded and rejoined the rotation."),
    _e("hedge", "serving",
       "A dispatch was hedged onto a second replica."),
    _e("request_failed", "serving",
       "A serving request failed on every replica within the re-route "
       "grace (ServingError)."),
    _e("overload_shed", "serving",
       "A serving request was refused at admission (ServingOverloaded) "
       "because the session's outstanding queue was at its bound."),
    _e("hot_swap", "serving",
       "A serving session atomically shifted traffic to a freshly loaded "
       "servable version (the old one retires in the background)."),
    _e("unload_failed", "serving",
       "A retired replica refused serve_unload through the whole retry "
       "window — its servable's weights stay pinned in that executor "
       "process (loud leak record: replica, executor, version, error)."),
    _e("rollout_promote", "serving",
       "A guarded rollout ramped its canary to full weight healthy and "
       "promoted it to primary through the swap/retire machinery."),
    _e("rollout_rollback", "serving",
       "A guarded rollout auto-rolled-back: the canary judged unhealthy "
       "(error-rate or p99 vs baseline) or the rollout timed out — "
       "weight to 0, canary unloaded, blackbox bundle written with the "
       "failing step's numbers."),
    _e("serve_scale", "serving",
       "The serving autoscaler changed (or failed to change) the "
       "per-version replica count (direction, replicas, reason)."),
    _e("stream_replay", "stream",
       "A continuous pipeline re-derived a lost epoch blob from its "
       "source journal (exactly-once replay; epoch + reason recorded)."),
]

EVENTS: Dict[str, Event] = {e.kind: e for e in _ALL_EVENTS}
assert len(EVENTS) == len(_ALL_EVENTS), "duplicate event declaration"


# ---- process-local state -----------------------------------------------------

_lock = threading.Lock()
_counters: Dict[str, Dict[str, float]] = {}  # guarded-by: _lock
_gauges: Dict[str, Dict[str, float]] = {}    # guarded-by: _lock
_hists: Dict[str, Dict[str, Dict[str, Any]]] = {}  # guarded-by: _lock
_events: Optional[collections.deque] = None  # guarded-by: _lock
_events_dropped = 0                          # guarded-by: _lock


def _event_cap() -> int:
    """The flight-recorder ring bound — read lazily so this module stays
    stdlib-only at import (the knob registry itself imports the package)."""
    try:
        from raydp_tpu import knobs
        return max(16, int(knobs.get("RDT_FLIGHT_MAX_EVENTS")))
    except Exception:  # noqa: BLE001 - standalone load (linter), bootstrap
        return 1024


def _metric(name: str, kind: str) -> Metric:
    m = METRICS[name]  # unknown name must fail loudly, same as knobs.get
    if m.kind != kind:
        raise ValueError(f"metric {name} is a {m.kind}, not a {kind}")
    return m


def inc(name: str, value: float = 1, label: str = "") -> None:
    """Add to a counter (cheap: one lock + dict update)."""
    _metric(name, COUNTER)
    with _lock:
        by_label = _counters.setdefault(name, {})
        by_label[label] = by_label.get(label, 0) + value


def set_gauge(name: str, value: float, label: str = "") -> None:
    _metric(name, GAUGE)
    with _lock:
        _gauges.setdefault(name, {})[label] = value


def observe(name: str, value: float, label: str = "") -> None:
    """Record one observation into a summary-shaped histogram."""
    _metric(name, HISTOGRAM)
    with _lock:
        h = _hists.setdefault(name, {}).setdefault(label, dict(_HIST_ZERO))
        h["count"] += 1
        h["sum"] += value
        h["min"] = value if h["min"] is None else min(h["min"], value)
        h["max"] = value if h["max"] is None else max(h["max"], value)


def record_event(kind: str, **fields) -> None:
    """Append one structured event to the bounded flight-recorder ring."""
    global _events, _events_dropped
    EVENTS[kind]  # unknown kind must fail loudly
    ev = {"ts": time.time(), "kind": kind}
    ev.update(fields)
    dropped = False
    with _lock:
        if _events is None:
            _events = collections.deque(maxlen=_event_cap())
        if len(_events) == _events.maxlen:
            _events_dropped += 1
            dropped = True
        _events.append(ev)
    if dropped:
        inc("flightrec_events_dropped_total")  # outside _lock: inc takes it


def events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events) if _events is not None else []


def snapshot() -> Dict[str, Any]:
    """This process's metric state: ``{"counters": {name: {label: v}},
    "gauges": ..., "hists": {name: {label: {count,sum,min,max}}}}``."""
    with _lock:
        return {
            "counters": {n: dict(d) for n, d in _counters.items()},
            "gauges": {n: dict(d) for n, d in _gauges.items()},
            "hists": {n: {lb: dict(h) for lb, h in d.items()}
                      for n, d in _hists.items()},
        }


def export_state() -> Dict[str, Any]:
    """The ``__rdt_metrics__`` intrinsic payload: metrics + the flight
    recorder ring + this process's wall clock (for offset alignment)."""
    with _lock:
        evs = list(_events) if _events is not None else []
        dropped = _events_dropped
    return {"metrics": snapshot(), "events": evs,
            "events_dropped": dropped, "clock_ns": time.time_ns(),
            "pid": os.getpid()}


def reset() -> None:
    """Wipe all process-local metric and event state (tests)."""
    global _events, _events_dropped
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _events = None
        _events_dropped = 0


# ---- merging -----------------------------------------------------------------

def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-process snapshots: counters and histogram components sum;
    gauges sum too (each process contributes its own level — per-process
    values stay readable under ``processes`` in :func:`metrics_report`)."""
    out = {"counters": {}, "gauges": {}, "hists": {}}
    for snap in snaps:
        for name, by_label in (snap.get("counters") or {}).items():
            tgt = out["counters"].setdefault(name, {})
            for lb, v in by_label.items():
                tgt[lb] = tgt.get(lb, 0) + v
        for name, by_label in (snap.get("gauges") or {}).items():
            tgt = out["gauges"].setdefault(name, {})
            for lb, v in by_label.items():
                tgt[lb] = tgt.get(lb, 0) + v
        for name, by_label in (snap.get("hists") or {}).items():
            tgt = out["hists"].setdefault(name, {})
            for lb, h in by_label.items():
                t = tgt.setdefault(lb, dict(_HIST_ZERO))
                t["count"] += h.get("count", 0)
                t["sum"] += h.get("sum", 0.0)
                for k, fn in (("min", min), ("max", max)):
                    v = h.get(k)
                    if v is not None:
                        t[k] = v if t[k] is None else fn(t[k], v)
    return out


def _collect_process_states(timeout: float = 10.0):
    """(states, skipped): every reachable process's ``export_state()`` —
    the driver itself, live actors via the ``__rdt_metrics__`` intrinsic,
    and node agents via their ``telemetry`` RPC."""
    states: Dict[str, Dict[str, Any]] = {"driver": export_state()}
    skipped = 0
    try:
        from raydp_tpu.runtime import head as head_mod
        if not head_mod.runtime_initialized():
            return states, skipped
        rt = head_mod.get_runtime()
        from raydp_tpu.runtime.actor import ActorHandle
        for aid, rec in list(rt.records.items()):
            if rec.state != "ALIVE":
                continue
            if not rec.ready.is_set():
                skipped += 1  # mid-restart: never park on the ready grace
                continue
            role = rec.spec.name or aid
            try:
                handle = ActorHandle(aid, rec.spec.name, rt.server.address)
                states[role] = handle.call("__rdt_metrics__",
                                           timeout=timeout)
            except Exception:  # noqa: BLE001 - a dying actor is skipped,
                skipped += 1   # counted, and reported — never silent
        for node_id, agent in list(getattr(rt, "node_agents", {}).items()):
            try:
                # metrics_state, NOT telemetry: the latter ships the whole
                # span ring, which this harvest would discard (and a
                # blackbox bundle would embed verbatim)
                states[f"agent-{node_id}"] = agent.call("metrics_state",
                                                        timeout=timeout)
            except Exception:  # noqa: BLE001 - same skip contract
                skipped += 1
    except Exception:  # noqa: BLE001 - no runtime: the driver state stands
        pass
    if skipped:
        inc("telemetry_skipped_processes_total", skipped)
        states["driver"] = export_state()  # re-snapshot with the skip count
    return states, skipped


def metrics_report(include_actors: bool = True) -> Dict[str, Any]:
    """The merged cross-process metrics view: ``merged`` (counters/hists
    summed, gauges summed), ``processes`` (role → that process's metrics),
    and ``skipped_processes`` (unreachable lanes — nonzero means the merge
    is incomplete). Subsumes the legacy per-subsystem reports:
    ``store_ops_total`` is ``op_counts()``, the ``serve_*`` counters are
    ``serving_report()``'s, the scheduler/recovery counters are the
    ``shuffle_stage_report`` columns."""
    if include_actors:
        states, skipped = _collect_process_states()
    else:
        states, skipped = {"driver": export_state()}, 0
    procs = {role: st.get("metrics", {}) for role, st in states.items()}
    return {"merged": merge_snapshots(list(procs.values())),
            "processes": procs,
            "skipped_processes": skipped}


# ---- prometheus / json dumps -------------------------------------------------

def _prom_name(name: str) -> str:
    return "rdt_" + name


def render_prometheus(merged: Dict[str, Any]) -> str:
    """Prometheus text exposition of one merged snapshot (histograms render
    as summary-style ``_count``/``_sum`` plus ``_max``)."""
    lines: List[str] = []

    def _sample(pname, label_name, label, value):
        tag = f'{{{label_name}="{label}"}}' if label else ""
        lines.append(f"{pname}{tag} {value}")

    for m in _ALL_METRICS:
        pname = _prom_name(m.name)
        if m.kind == COUNTER:
            data = merged.get("counters", {}).get(m.name)
        elif m.kind == GAUGE:
            data = merged.get("gauges", {}).get(m.name)
        else:
            data = merged.get("hists", {}).get(m.name)
        if not data:
            continue
        lines.append(f"# HELP {pname} {m.doc}")
        lines.append(f"# TYPE {pname} "
                     f"{'summary' if m.kind == HISTOGRAM else m.kind}")
        for lb in sorted(data):
            if m.kind == HISTOGRAM:
                h = data[lb]
                _sample(pname + "_count", m.label, lb, h["count"])
                _sample(pname + "_sum", m.label, lb, h["sum"])
                if h["max"] is not None:
                    _sample(pname + "_max", m.label, lb, h["max"])
            else:
                _sample(pname, m.label, lb, data[lb])
    return "\n".join(lines) + "\n"


def dump(out_dir: Optional[str] = None) -> Dict[str, str]:
    """Write the merged report as ``metrics.json`` + ``metrics.prom`` into
    ``out_dir`` (default: ``<session_dir>/metrics``); returns the paths."""
    if out_dir is None:
        out_dir = os.path.join(_session_dir(), "metrics")
    os.makedirs(out_dir, exist_ok=True)
    report = metrics_report()
    json_path = os.path.join(out_dir, "metrics.json")
    prom_path = os.path.join(out_dir, "metrics.prom")
    with open(json_path, "w") as fh:
        json.dump(report, fh, indent=2, default=str)
    with open(prom_path, "w") as fh:
        fh.write(render_prometheus(report["merged"]))
    return {"json": json_path, "prom": prom_path}


def _session_dir() -> str:
    try:
        from raydp_tpu.runtime import head as head_mod
        if head_mod.runtime_initialized():
            return head_mod.get_runtime().session_dir
    except Exception:  # noqa: BLE001 - no runtime: the default dir stands
        pass
    return "/tmp/raydp_tpu"


# ---- flight-recorder blackbox bundles ---------------------------------------

#: bundles written per action label this session — a chaos storm failing the
#: same action in a loop must not fill the disk with identical postmortems
_BLACKBOX_CAP_PER_ACTION = 5
_blackbox_counts: Dict[str, int] = {}  # guarded-by: _lock


def write_blackbox(action: str, error: Optional[BaseException] = None,
                   extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Harvest every reachable process's flight-recorder ring (plus its
    metrics snapshot) into ``<session_dir>/blackbox/blackbox-<action>[-n]
    .json``; returns the path (None past the per-action cap). Called by the
    engine when an action surfaces ``StageError`` and by the serving
    session on ``ServingError`` — best-effort by contract: a failed harvest
    must never mask the error that triggered it."""
    safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in action)
    with _lock:
        n = _blackbox_counts.get(safe, 0)
        if n >= _BLACKBOX_CAP_PER_ACTION:
            return None
        _blackbox_counts[safe] = n + 1
    states, skipped = _collect_process_states()
    bundle = {
        "action": action,
        "ts": time.time(),
        "error": None if error is None else str(error),
        "exc_type": None if error is None else type(error).__name__,
        "skipped_processes": skipped,
        "processes": states,
    }
    if extra:
        bundle["extra"] = extra
    out_dir = os.path.join(_session_dir(), "blackbox")
    os.makedirs(out_dir, exist_ok=True)
    suffix = "" if n == 0 else f"-{n}"
    path = os.path.join(out_dir, f"blackbox-{safe}{suffix}.json")
    with open(path, "w") as fh:
        json.dump(bundle, fh, indent=2, default=str)
    return path


# ---- generated doc tables ----------------------------------------------------

def generate_table(tag: str) -> str:
    """Markdown table for one registry (``spans`` / ``metrics`` /
    ``events``). The blocks between ``rdtlint:telemetry-table`` markers in
    ``doc/observability.md`` are exactly this output; rule
    ``telemetry-registry`` fails on any drift."""
    if tag == "metrics":
        lines = ["| Metric | Kind | Unit | Label | Subsystem | Description |",
                 "| --- | --- | --- | --- | --- | --- |"]
        for m in _ALL_METRICS:
            lines.append(
                f"| `{m.name}` | {m.kind} | {m.unit} | "
                f"{('`' + m.label + '`') if m.label else '—'} | "
                f"{m.subsystem} | {m.doc} |")
    elif tag == "spans":
        lines = ["| Span | Subsystem | Description |",
                 "| --- | --- | --- |"]
        for s in _ALL_SPANS:
            name = f"`{s.name}…` *(dynamic)*" if s.dynamic else f"`{s.name}`"
            lines.append(f"| {name} | {s.subsystem} | {s.doc} |")
    elif tag == "events":
        lines = ["| Event | Subsystem | Description |",
                 "| --- | --- | --- |"]
        for e in _ALL_EVENTS:
            lines.append(f"| `{e.kind}` | {e.subsystem} | {e.doc} |")
    else:
        raise ValueError(f"unknown telemetry table {tag!r}")
    return "\n".join(lines)


DOC_FILE = "doc/observability.md"
DOC_TAGS = ("spans", "metrics", "events")

_BEGIN = "<!-- rdtlint:telemetry-table:begin {tag} -->"
_END = "<!-- rdtlint:telemetry-table:end -->"


def table_markers(tag: str) -> tuple:
    return _BEGIN.format(tag=tag), _END


def render_block(tag: str) -> str:
    begin, end = table_markers(tag)
    return f"{begin}\n{generate_table(tag)}\n{end}"


def write_doc_tables(root: str) -> list:
    """Rewrite the telemetry table blocks in ``doc/observability.md`` from
    the registries; returns the files changed."""
    path = os.path.join(root, DOC_FILE)
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    orig = text
    for tag in DOC_TAGS:
        begin, end = table_markers(tag)
        if begin not in text or end not in text:
            continue
        head, rest = text.split(begin, 1)
        _, tail = rest.split(end, 1)
        text = head + render_block(tag) + tail
    if text != orig:
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)
        return [DOC_FILE]
    return []


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m raydp_tpu.metrics",
        description="print or regenerate the telemetry registry tables")
    ap.add_argument("--write-docs", action="store_true",
                    help="rewrite the generated doc tables in place")
    ap.add_argument("--root", default=".",
                    help="repo root holding doc/ (default: cwd)")
    args = ap.parse_args(argv)
    if args.write_docs:
        changed = write_doc_tables(args.root)
        for rel in changed:
            print(f"rewrote {rel}")
        if not changed:
            print("telemetry tables already fresh")
        return 0
    for tag in DOC_TAGS:
        print(f"## {tag}\n{generate_table(tag)}\n")
    return 0


if __name__ == "__main__":  # pragma: no cover - thin CLI shim
    raise SystemExit(main())
