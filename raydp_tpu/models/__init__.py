"""raydp_tpu.models — the model families exercised by the reference's examples.

- :mod:`mlp` — the NYCTaxi fare-regression MLP (examples/pytorch_nyctaxi.py:69-92).
- :mod:`dlrm` — Criteo DLRM with sharded embedding tables
  (examples/pytorch_dlrm.ipynb: bottom MLP 512-256-64-16, 26 embeddings, top MLP).
- :mod:`transformer` — a long-context transformer with ring attention /
  sequence-parallel sharding (the capability the TPU build adds beyond the
  reference's tabular models; SURVEY.md §5 long-context note).
"""

from raydp_tpu.models.mlp import MLP, NYCTaxiModel
from raydp_tpu.models.dlrm import DLRM, criteo_batch_preprocessor, dlrm_param_rules
from raydp_tpu.models.gbdt import GBDTModel, fit_gbdt
from raydp_tpu.models.transformer import (
    TransformerLM, lm_loss, transformer_param_rules,
)

__all__ = ["MLP", "NYCTaxiModel", "DLRM", "criteo_batch_preprocessor",
           "dlrm_param_rules", "GBDTModel", "fit_gbdt", "TransformerLM",
           "lm_loss", "transformer_param_rules"]
