"""DLRM (Criteo click-through) with shardable embedding tables.

Architecture parity with the reference's notebook model
(examples/pytorch_dlrm.ipynb: 13 dense features → bottom MLP [512,128,32],
26 categorical embeddings of dim 32, pairwise dot interaction with the padded
tril flattening, top MLP [1024,1024,512,256,1], BCEWithLogits loss).

TPU-first design: the interaction is a batched matmul that tiles onto the MXU;
embedding tables are the memory hog, so each ``Embed`` kernel can be sharded
row-wise over the mesh's ``expert`` axis via
:func:`raydp_tpu.models.dlrm.dlrm_param_rules` — XLA turns the lookups into
gathers with the appropriate collectives, which is the reference's
"sparse embeddings want a model axis even for DP" hard part (SURVEY.md §7
step 5) solved by sharding annotation instead of a parameter server.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


def _tril_indices(n: int) -> Tuple[np.ndarray, np.ndarray]:
    rows = np.array([i for i in range(n) for _ in range(i)], dtype=np.int32)
    cols = np.array([j for i in range(n) for j in range(i)], dtype=np.int32)
    return rows, cols


class DotInteraction(nn.Module):
    """Pairwise dot products among the (1 + num_tables) feature vectors,
    concatenated with the bottom-MLP output and one zero pad (multiple-of-8
    width — also the MXU-friendly choice)."""

    @nn.compact
    def __call__(self, vectors: jnp.ndarray, bottom_out: jnp.ndarray):
        # vectors: [B, 1 + T, D]; bottom_out: [B, D]
        b, n, _ = vectors.shape
        inter = jnp.einsum("bnd,bmd->bnm", vectors, vectors)
        rows, cols = _tril_indices(n)
        flat = inter[:, rows, cols]                       # [B, n(n-1)/2]
        pad = jnp.zeros((b, 1), dtype=flat.dtype)
        return jnp.concatenate([bottom_out, flat, pad], axis=1)


class DLRM(nn.Module):
    categorical_sizes: Sequence[int]
    num_dense: int = 13
    embedding_dim: int = 32
    bottom_mlp: Sequence[int] = (512, 128, 32)
    top_mlp: Sequence[int] = (1024, 1024, 512, 256, 1)
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, inputs: Dict[str, jnp.ndarray]):
        dense = inputs["dense"]          # [B, num_dense] float
        sparse = inputs["sparse"]        # [B, num_tables] int
        dtype = self.dtype or dense.dtype
        x = dense.astype(dtype)
        for w in self.bottom_mlp:
            x = nn.relu(nn.Dense(w, dtype=dtype)(x))
        bottom_out = x                   # [B, D] where D == embedding_dim

        embs = []
        for i, vocab in enumerate(self.categorical_sizes):
            table = nn.Embed(vocab, self.embedding_dim, dtype=dtype,
                             name=f"embedding_{i}")
            embs.append(table(sparse[:, i]))
        vectors = jnp.stack([bottom_out] + embs, axis=1)  # [B, 1+T, D]

        z = DotInteraction()(vectors, bottom_out)
        for w in self.top_mlp[:-1]:
            z = nn.relu(nn.Dense(w, dtype=dtype)(z))
        logit = nn.Dense(self.top_mlp[-1], dtype=dtype)(z)
        return logit.astype(jnp.float32)  # [B, 1] logits (BCE-with-logits loss)


def dlrm_param_rules(axis: str = "expert"):
    """Sharding rules: embedding tables row-sharded over ``axis``; MLPs
    replicated (pass to FlaxEstimator(param_rules=...))."""
    return [("embedding", (axis, None))]


def criteo_batch_preprocessor(num_dense: int = 13):
    """Split the estimator's flat batch into DLRM's dense/sparse dict.

    Matches the reference's column layout (_c1.._c13 dense float,
    _c14.._c39 categorical int, label _c0)."""

    def prep(batch):
        feats = batch["features"]
        dense = feats[:, :num_dense].astype(jnp.float32)
        sparse = feats[:, num_dense:].astype(jnp.int32)
        return {"dense": dense, "sparse": sparse}, batch["label"]

    return prep
