"""Histogram gradient-boosted decision trees, XLA-native.

The reference's third estimator wraps distributed XGBoost (Rabit collectives)
over Ray Train (reference: xgboost/estimator.py:54-81,
examples/xgboost_ray_nyctaxi.py:60-75). A TPU-native build cannot ride a CPU
tree library, so this module implements the algorithm the way the hardware
wants it — as dense, static-shape array programs:

- features are **quantile-binned once** on the host (the standard histogram
  trick); training sees only an ``int32 [n, f]`` bin matrix;
- trees grow **level-wise with a fixed max_depth**, so every per-level buffer
  (histograms ``[nodes, features, bins]``, split tables, leaf tables) has a
  static shape — no data-dependent control flow, one XLA compilation;
- per-level split finding is two ``segment_sum`` scatter-adds (gradient and
  hessian histograms) + a cumulative-sum gain scan + an argmax — all fusable,
  all data-parallel over rows, so sharding the row dimension over a mesh makes
  XLA insert ``psum``s for the histograms exactly where XGBoost's Rabit
  allreduce sits;
- the boosting loop is a ``lax.scan`` over rounds, carrying predictions and
  stacking per-tree tables.

A "no split" is represented as threshold ``num_bins - 1`` (every row routes
left), which lets gain-negative nodes degrade gracefully without ragged trees.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class GBDTModel:
    """A fitted forest: per-tree split/leaf tables + binning for inference."""

    split_feature: np.ndarray   # [T, 2**depth - 1] int32
    split_bin: np.ndarray       # [T, 2**depth - 1] int32
    leaf_value: np.ndarray      # [T, 2**depth] float32
    bin_edges: np.ndarray       # [f, num_bins - 1] float32
    base_score: float
    max_depth: int
    objective: str

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]

    def predict(self, X: np.ndarray, output_margin: bool = False) -> np.ndarray:
        Xb = apply_bins(np.asarray(X, dtype=np.float32), self.bin_edges)
        margin = np.asarray(_predict_binned_jit(
            jnp.asarray(Xb), jnp.asarray(self.split_feature),
            jnp.asarray(self.split_bin), jnp.asarray(self.leaf_value),
            self.max_depth) + self.base_score)
        if self.objective == "binary:logistic" and not output_margin:
            return 1.0 / (1.0 + np.exp(-margin))
        return margin


def make_bins(X: np.ndarray, num_bins: int = 256) -> np.ndarray:
    """Per-feature quantile bin edges ``[f, num_bins - 1]`` (host side, once)."""
    qs = np.linspace(0, 1, num_bins + 1)[1:-1]
    return np.quantile(X, qs, axis=0).T.astype(np.float32)


def apply_bins(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """float features → int32 bin indices in ``[0, num_bins)``."""
    out = np.empty(X.shape, dtype=np.int32)
    for j in range(X.shape[1]):
        out[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    return out


def _grad_hess(pred, y, objective: str):
    if objective == "binary:logistic":
        p = jax.nn.sigmoid(pred)
        return p - y, p * (1.0 - p)
    # reg:squarederror — ½(pred − y)²
    return pred - y, jnp.ones_like(pred)


@partial(jax.jit, static_argnames=(
    "num_trees", "max_depth", "num_bins", "objective"))
def _fit_binned(Xb, y, *, num_trees: int, max_depth: int, num_bins: int,
                learning_rate: float, reg_lambda: float, min_child_weight: float,
                base_score: float, objective: str):
    n, f = Xb.shape
    num_internal = 2 ** max_depth - 1
    num_leaves = 2 ** max_depth
    rows = jnp.arange(n)
    feat_ids = jnp.arange(f)

    def build_tree(pred):
        g, h = _grad_hess(pred, y, objective)
        node = jnp.zeros(n, dtype=jnp.int32)  # level-local node index
        split_feature = jnp.zeros(num_internal, dtype=jnp.int32)
        split_bin = jnp.full(num_internal, num_bins - 1, dtype=jnp.int32)

        for depth in range(max_depth):  # static unroll: buffers double per level
            level_nodes = 2 ** depth
            offset = level_nodes - 1
            # histograms over (node, feature, bin) via one scatter-add each
            seg = (node[:, None] * f + feat_ids[None, :]) * num_bins + Xb
            num_segments = level_nodes * f * num_bins
            hist_g = jax.ops.segment_sum(
                jnp.broadcast_to(g[:, None], (n, f)).ravel(), seg.ravel(),
                num_segments=num_segments).reshape(level_nodes, f, num_bins)
            hist_h = jax.ops.segment_sum(
                jnp.broadcast_to(h[:, None], (n, f)).ravel(), seg.ravel(),
                num_segments=num_segments).reshape(level_nodes, f, num_bins)

            GL = jnp.cumsum(hist_g, axis=-1)
            HL = jnp.cumsum(hist_h, axis=-1)
            Gt = GL[..., -1:]
            Ht = HL[..., -1:]
            GR = Gt - GL
            HR = Ht - HL
            gain = (GL * GL / (HL + reg_lambda)
                    + GR * GR / (HR + reg_lambda)
                    - Gt * Gt / (Ht + reg_lambda))
            ok = (HL >= min_child_weight) & (HR >= min_child_weight)
            gain = jnp.where(ok, gain, -jnp.inf)
            # bin B-1 keeps everything left — the canonical "no split"
            gain = gain.at[..., num_bins - 1].set(0.0)

            flat = gain.reshape(level_nodes, f * num_bins)
            best = jnp.argmax(flat, axis=1)
            best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
            bf = (best // num_bins).astype(jnp.int32)
            bb = (best % num_bins).astype(jnp.int32)
            no_split = best_gain <= 0.0
            bf = jnp.where(no_split, 0, bf)
            bb = jnp.where(no_split, num_bins - 1, bb)

            idx = offset + jnp.arange(level_nodes)
            split_feature = split_feature.at[idx].set(bf)
            split_bin = split_bin.at[idx].set(bb)

            go_right = Xb[rows, bf[node]] > bb[node]
            node = node * 2 + go_right.astype(jnp.int32)

        leaf_g = jax.ops.segment_sum(g, node, num_segments=num_leaves)
        leaf_h = jax.ops.segment_sum(h, node, num_segments=num_leaves)
        leaf_value = (-leaf_g / (leaf_h + reg_lambda)
                      * learning_rate).astype(jnp.float32)
        return split_feature, split_bin, leaf_value, leaf_value[node]

    def boost(pred, _):
        split_feature, split_bin, leaf_value, update = build_tree(pred)
        return pred + update, (split_feature, split_bin, leaf_value)

    pred0 = jnp.full(n, base_score, dtype=jnp.float32)
    final_pred, trees = jax.lax.scan(boost, pred0, None, length=num_trees)
    return trees, final_pred


@partial(jax.jit, static_argnames=("max_depth",))
def _predict_binned_jit(Xb, split_feature, split_bin, leaf_value,
                        max_depth: int):
    n = Xb.shape[0]
    rows = jnp.arange(n)

    def one_tree(pred, tree):
        sf, sb, leaves = tree
        node = jnp.zeros(n, dtype=jnp.int32)
        for depth in range(max_depth):
            offset = 2 ** depth - 1
            feat = sf[offset + node]
            thr = sb[offset + node]
            go_right = Xb[rows, feat] > thr
            node = node * 2 + go_right.astype(jnp.int32)
        return pred + leaves[node], None

    pred0 = jnp.zeros(n, dtype=jnp.float32)
    pred, _ = jax.lax.scan(one_tree, pred0,
                           (split_feature, split_bin, leaf_value))
    return pred


def fit_gbdt(
    X: np.ndarray,
    y: np.ndarray,
    *,
    num_trees: int = 100,
    max_depth: int = 6,
    num_bins: int = 256,
    learning_rate: float = 0.3,
    reg_lambda: float = 1.0,
    min_child_weight: float = 1.0,
    objective: str = "reg:squarederror",
    bin_edges: Optional[np.ndarray] = None,
) -> Tuple[GBDTModel, np.ndarray]:
    """Fit a forest; returns (model, final training margins)."""
    if objective not in ("reg:squarederror", "binary:logistic"):
        raise ValueError(f"unsupported objective {objective!r}")
    X = np.asarray(X, dtype=np.float32)
    y = np.asarray(y, dtype=np.float32)
    if bin_edges is None:
        bin_edges = make_bins(X, num_bins)
    Xb = apply_bins(X, bin_edges)

    if objective == "binary:logistic":
        p = float(np.clip(y.mean(), 1e-6, 1 - 1e-6))
        base_score = float(np.log(p / (1 - p)))
    else:
        base_score = float(y.mean())

    trees, final_pred = _fit_binned(
        jnp.asarray(Xb), jnp.asarray(y), num_trees=num_trees,
        max_depth=max_depth, num_bins=num_bins, learning_rate=learning_rate,
        reg_lambda=reg_lambda, min_child_weight=min_child_weight,
        base_score=base_score, objective=objective)
    split_feature, split_bin, leaf_value = (np.asarray(t) for t in trees)
    model = GBDTModel(split_feature=split_feature, split_bin=split_bin,
                      leaf_value=leaf_value, bin_edges=bin_edges,
                      base_score=base_score, max_depth=max_depth,
                      objective=objective)
    return model, np.asarray(final_pred)
