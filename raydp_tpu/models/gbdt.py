"""Histogram gradient-boosted decision trees, XLA-native.

The reference's third estimator wraps distributed XGBoost (Rabit collectives)
over Ray Train (reference: xgboost/estimator.py:54-81,
examples/xgboost_ray_nyctaxi.py:60-75). A TPU-native build cannot ride a CPU
tree library, so this module implements the algorithm the way the hardware
wants it — as dense, static-shape array programs:

- features are **quantile-binned once** on the host (the standard histogram
  trick); training sees only an ``int32 [n, f]`` bin matrix;
- trees grow **level-wise with a fixed max_depth**, so every per-level buffer
  (histograms ``[nodes, features, bins]``, split tables, leaf tables) has a
  static shape — no data-dependent control flow, one XLA compilation;
- per-level split finding is two ``segment_sum`` scatter-adds (gradient and
  hessian histograms) + a cumulative-sum gain scan + an argmax — all fusable,
  all data-parallel over rows, so sharding the row dimension over a mesh makes
  XLA insert ``psum``s for the histograms exactly where XGBoost's Rabit
  allreduce sits;
- the boosting loop is a ``lax.scan`` over rounds, carrying predictions and
  stacking per-tree tables; with eval sets / early stopping the scan runs in
  host-stepped chunks so per-round metrics come out without recompiling;
- multiclass (``multi:softmax`` / ``multi:softprob``) builds K one-vs-rest
  trees per round by ``vmap``-ing tree construction over the class axis of the
  softmax gradients — K trees for the price of one compilation;
- instance weights scale (g, h) before the histograms, xgboost-style.

A "no split" is represented as threshold ``num_bins - 1`` (every row routes
left), which lets gain-negative nodes degrade gracefully without ragged trees.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class GBDTModel:
    """A fitted forest: per-tree split/leaf tables + binning for inference.

    Table shapes: ``[T, nodes]`` for single-output objectives;
    ``[T, K, nodes]`` for multiclass (K trees per boosting round).
    """

    split_feature: np.ndarray   # [T, 2**depth - 1] or [T, K, 2**depth - 1]
    split_bin: np.ndarray       # same leading shape
    leaf_value: np.ndarray      # [T, 2**depth] or [T, K, 2**depth]
    bin_edges: np.ndarray       # [f, num_bins - 1] float32
    base_score: np.ndarray      # scalar, or [K] for multiclass
    max_depth: int
    objective: str
    best_iteration: Optional[int] = None   # set when early stopping fired

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]

    @property
    def num_class(self) -> int:
        return self.leaf_value.shape[1] if self.leaf_value.ndim == 3 else 1

    def predict(self, X: np.ndarray, output_margin: bool = False) -> np.ndarray:
        Xb = apply_bins(np.asarray(X, dtype=np.float32), self.bin_edges)
        margin = predict_binned(Xb, self.split_feature, self.split_bin,
                                self.leaf_value, self.max_depth)
        margin = margin + self.base_score
        if output_margin:
            return margin
        if self.objective == "binary:logistic":
            return 1.0 / (1.0 + np.exp(-margin))
        if self.objective == "multi:softprob":
            e = np.exp(margin - margin.max(axis=1, keepdims=True))
            return e / e.sum(axis=1, keepdims=True)
        if self.objective == "multi:softmax":
            return margin.argmax(axis=1).astype(np.float32)
        return margin


def make_bins(X: np.ndarray, num_bins: int = 256) -> np.ndarray:
    """Per-feature quantile bin edges ``[f, num_bins - 1]`` (host side, once)."""
    qs = np.linspace(0, 1, num_bins + 1)[1:-1]
    return np.quantile(X, qs, axis=0).T.astype(np.float32)


def apply_bins(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """float features → int32 bin indices in ``[0, num_bins)``."""
    out = np.empty(X.shape, dtype=np.int32)
    for j in range(X.shape[1]):
        out[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    return out


def _grad_hess(pred, y, objective: str):
    """(g, h) per row — shape [n] (single-output) or [n, K] (multiclass)."""
    if objective == "binary:logistic":
        p = jax.nn.sigmoid(pred)
        return p - y, p * (1.0 - p)
    if objective.startswith("multi:"):
        K = pred.shape[1]
        p = jax.nn.softmax(pred, axis=-1)
        onehot = jax.nn.one_hot(y.astype(jnp.int32), K, dtype=pred.dtype)
        return p - onehot, p * (1.0 - p)
    # reg:squarederror — ½(pred − y)²
    return pred - y, jnp.ones_like(pred)


def _build_tree(Xb, g, h, *, max_depth: int, num_bins: int,
                learning_rate: float, reg_lambda: float,
                min_child_weight: float):
    """One tree for one (g, h) target; returns (split tables, leaf values,
    per-row update)."""
    n, f = Xb.shape
    num_internal = 2 ** max_depth - 1
    num_leaves = 2 ** max_depth
    rows = jnp.arange(n)
    feat_ids = jnp.arange(f)

    node = jnp.zeros(n, dtype=jnp.int32)  # level-local node index
    split_feature = jnp.zeros(num_internal, dtype=jnp.int32)
    split_bin = jnp.full(num_internal, num_bins - 1, dtype=jnp.int32)

    for depth in range(max_depth):  # static unroll: buffers double per level
        level_nodes = 2 ** depth
        offset = level_nodes - 1
        # histograms over (node, feature, bin) via one scatter-add each
        seg = (node[:, None] * f + feat_ids[None, :]) * num_bins + Xb
        num_segments = level_nodes * f * num_bins
        hist_g = jax.ops.segment_sum(
            jnp.broadcast_to(g[:, None], (n, f)).ravel(), seg.ravel(),
            num_segments=num_segments).reshape(level_nodes, f, num_bins)
        hist_h = jax.ops.segment_sum(
            jnp.broadcast_to(h[:, None], (n, f)).ravel(), seg.ravel(),
            num_segments=num_segments).reshape(level_nodes, f, num_bins)

        GL = jnp.cumsum(hist_g, axis=-1)
        HL = jnp.cumsum(hist_h, axis=-1)
        Gt = GL[..., -1:]
        Ht = HL[..., -1:]
        GR = Gt - GL
        HR = Ht - HL
        gain = (GL * GL / (HL + reg_lambda)
                + GR * GR / (HR + reg_lambda)
                - Gt * Gt / (Ht + reg_lambda))
        ok = (HL >= min_child_weight) & (HR >= min_child_weight)
        gain = jnp.where(ok, gain, -jnp.inf)
        # bin B-1 keeps everything left — the canonical "no split"
        gain = gain.at[..., num_bins - 1].set(0.0)

        flat = gain.reshape(level_nodes, f * num_bins)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        bf = (best // num_bins).astype(jnp.int32)
        bb = (best % num_bins).astype(jnp.int32)
        no_split = best_gain <= 0.0
        bf = jnp.where(no_split, 0, bf)
        bb = jnp.where(no_split, num_bins - 1, bb)

        idx = offset + jnp.arange(level_nodes)
        split_feature = split_feature.at[idx].set(bf)
        split_bin = split_bin.at[idx].set(bb)

        go_right = Xb[rows, bf[node]] > bb[node]
        node = node * 2 + go_right.astype(jnp.int32)

    leaf_g = jax.ops.segment_sum(g, node, num_segments=num_leaves)
    leaf_h = jax.ops.segment_sum(h, node, num_segments=num_leaves)
    leaf_value = (-leaf_g / (leaf_h + reg_lambda)
                  * learning_rate).astype(jnp.float32)
    return split_feature, split_bin, leaf_value, leaf_value[node]


def _boost_round(Xb, y, w, pred, build, objective: str):
    """ONE boosting round — the single copy of the per-round tree math every
    scan body shares (g/h weighting, the multiclass vmap, the margin
    update): returns ``(new_pred, (sf, sb, lv))``."""
    g, h = _grad_hess(pred, y, objective)
    if g.ndim == 2:  # multiclass: K trees via vmap over the class axis
        g = g * w[:, None]
        h = h * w[:, None]
        sf, sb, lv, upd = jax.vmap(
            lambda gk, hk: build(Xb, gk, hk),
            in_axes=1, out_axes=0)(g, h)     # tables [K, ...], upd [K, n]
        return pred + upd.T, (sf, sb, lv)
    sf, sb, lv, upd = build(Xb, g * w, h * w)
    return pred + upd, (sf, sb, lv)


def _route(Xb, sf, sb, leaves, max_depth: int):
    """Route every row of a binned matrix through one tree — the single
    routing walk (also the in-scan eval predictor)."""
    n = Xb.shape[0]
    rows = jnp.arange(n)
    node = jnp.zeros(n, dtype=jnp.int32)
    for depth in range(max_depth):
        offset = 2 ** depth - 1
        feat = sf[offset + node]
        thr = sb[offset + node]
        node = node * 2 + (Xb[rows, feat] > thr).astype(jnp.int32)
    return leaves[node]


@partial(jax.jit, static_argnames=(
    "chunk", "max_depth", "num_bins", "objective"))
def _boost_chunk(Xb, y, w, pred, *, chunk: int, max_depth: int, num_bins: int,
                 learning_rate: float, reg_lambda: float,
                 min_child_weight: float, objective: str):
    """``chunk`` boosting rounds from ``pred``; returns (stacked trees, pred).

    Compiled once per (shape, chunk); the host loop re-invokes it between
    eval/early-stop checks without recompiling.
    """
    build = partial(_build_tree, max_depth=max_depth, num_bins=num_bins,
                    learning_rate=learning_rate, reg_lambda=reg_lambda,
                    min_child_weight=min_child_weight)

    def boost(pred, _):
        return _boost_round(Xb, y, w, pred, build, objective)

    pred, trees = jax.lax.scan(boost, pred, None, length=chunk)
    return trees, pred


def _eval_metric_value(margin, y, objective: str):
    """In-jit twin of :func:`eval_metric`'s value (same formulas, jnp ops) —
    what the fused train+eval scan accumulates per round.

    KEEP IN SYNC with :func:`eval_metric` (host numpy/float64): the
    early-stopping path consumes that host version, and the two histories
    are pinned together by tests/test_gbdt.py's fused-eval parity test
    (rtol 1e-5) — edit both or that test fails."""
    if objective == "binary:logistic":
        p = 1.0 / (1.0 + jnp.exp(-margin))
        eps = 1e-7
        return -jnp.mean(y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps))
    if objective.startswith("multi:"):
        e = jnp.exp(margin - margin.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        rows = jnp.arange(y.shape[0])
        return -jnp.mean(jnp.log(p[rows, y.astype(jnp.int32)] + 1e-7))
    return jnp.sqrt(jnp.mean((margin - y) ** 2))


@partial(jax.jit, static_argnames=(
    "chunk", "max_depth", "num_bins", "objective"))
def _boost_chunk_eval(Xb, y, w, pred, eXb, ey, eval_margin, *, chunk: int,
                      max_depth: int, num_bins: int, learning_rate: float,
                      reg_lambda: float, min_child_weight: float,
                      objective: str):
    """``chunk`` rounds with the per-round eval-set metric computed ON
    DEVICE: one dispatch covers the whole train+eval history. The host
    per-round loop this replaces (still used for early stopping, whose
    keep/stop decision is host semantics) paid a tree-table fetch plus an
    eval dispatch every round — dominant on a remote-tunnel backend."""
    build = partial(_build_tree, max_depth=max_depth, num_bins=num_bins,
                    learning_rate=learning_rate, reg_lambda=reg_lambda,
                    min_child_weight=min_child_weight)

    def boost(carry, _):
        pred, emargin = carry
        pred, (sf, sb, lv) = _boost_round(Xb, y, w, pred, build, objective)
        if sf.ndim == 2:  # multiclass: [K, nodes] tables → [en, K] margins
            emargin = emargin + jax.vmap(
                lambda s, b, l: _route(eXb, s, b, l, max_depth))(
                    sf, sb, lv).T
        else:
            emargin = emargin + _route(eXb, sf, sb, lv, max_depth)
        value = _eval_metric_value(emargin, ey, objective)
        return (pred, emargin), (sf, sb, lv, value)

    (pred, _), (sf, sb, lv, values) = jax.lax.scan(
        boost, (pred, eval_margin), None, length=chunk)
    return (sf, sb, lv), pred, values


@partial(jax.jit, static_argnames=("max_depth",))
def _predict_binned_jit(Xb, split_feature, split_bin, leaf_value,
                        max_depth: int):
    n = Xb.shape[0]

    def route(sf, sb, leaves):
        return _route(Xb, sf, sb, leaves, max_depth)

    def one_tree(pred, tree):
        sf, sb, leaves = tree
        if sf.ndim == 2:  # multiclass: [K, nodes] tables → [n, K] margins
            return pred + jax.vmap(route)(sf, sb, leaves).T, None
        return pred + route(sf, sb, leaves), None

    if split_feature.ndim == 3:
        pred0 = jnp.zeros((n, split_feature.shape[1]), dtype=jnp.float32)
    else:
        pred0 = jnp.zeros(n, dtype=jnp.float32)
    pred, _ = jax.lax.scan(one_tree, pred0,
                           (split_feature, split_bin, leaf_value))
    return pred


def predict_binned(Xb, split_feature, split_bin, leaf_value,
                   max_depth: int) -> np.ndarray:
    return np.asarray(_predict_binned_jit(
        jnp.asarray(Xb), jnp.asarray(split_feature), jnp.asarray(split_bin),
        jnp.asarray(leaf_value), max_depth))


def eval_metric(margin: np.ndarray, y: np.ndarray,
                objective: str) -> Tuple[str, float]:
    """The objective's default metric (xgboost naming).

    KEEP IN SYNC with :func:`_eval_metric_value` (the in-jit jnp/float32
    twin the fused boosting scan accumulates); the parity test in
    tests/test_gbdt.py pins the pair at rtol 1e-5."""
    if objective == "binary:logistic":
        p = 1.0 / (1.0 + np.exp(-margin))
        eps = 1e-7
        return "logloss", float(-np.mean(
            y * np.log(p + eps) + (1 - y) * np.log(1 - p + eps)))
    if objective.startswith("multi:"):
        e = np.exp(margin - margin.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        rows = np.arange(len(y))
        return "mlogloss", float(-np.mean(
            np.log(p[rows, y.astype(np.int64)] + 1e-7)))
    return "rmse", float(np.sqrt(np.mean((margin - y) ** 2)))


def fit_gbdt(
    X: np.ndarray,
    y: np.ndarray,
    *,
    num_trees: int = 100,
    max_depth: int = 6,
    num_bins: int = 256,
    learning_rate: float = 0.3,
    reg_lambda: float = 1.0,
    min_child_weight: float = 1.0,
    objective: str = "reg:squarederror",
    num_class: Optional[int] = None,
    sample_weight: Optional[np.ndarray] = None,
    evals: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    early_stopping_rounds: Optional[int] = None,
    bin_edges: Optional[np.ndarray] = None,
    mesh=None,
) -> Tuple[GBDTModel, np.ndarray, Dict[str, List[float]]]:
    """Fit a forest; returns (model, final train margins, evals_result).

    ``evals_result`` holds per-round eval metrics (reference behavior: the
    wrapped xgboost reports eval sets every boosting round,
    xgboost/estimator.py:54-81); empty when no ``evals`` given. With
    ``early_stopping_rounds`` the loop stops once the eval metric has not
    improved for that many rounds and the forest is truncated to the best
    iteration (recorded on ``model.best_iteration``).

    ``mesh`` shards the ROW dimension over the mesh's data axes: the
    per-level histograms become partial scatter-adds on each device with XLA
    inserting the cross-device reduction — the exact spot XGBoost's Rabit
    allreduce sits in the reference's distributed trainer. Split finding and
    tree tables stay replicated.
    """
    known = ("reg:squarederror", "binary:logistic", "multi:softmax",
             "multi:softprob")
    if objective not in known:
        raise ValueError(f"unsupported objective {objective!r}; have {known}")
    multi = objective.startswith("multi:")
    X = np.asarray(X, dtype=np.float32)
    y = np.asarray(y, dtype=np.float32)
    if bin_edges is None:
        bin_edges = make_bins(X, num_bins)
    Xb = apply_bins(X, bin_edges)
    w = (np.ones(len(y), np.float32) if sample_weight is None
         else np.asarray(sample_weight, np.float32))

    if multi:
        K = int(num_class or int(y.max()) + 1)
        counts = np.bincount(y.astype(np.int64), minlength=K) + 1.0
        base_score = np.log(counts / counts.sum()).astype(np.float32)
        pred = jnp.broadcast_to(jnp.asarray(base_score),
                                (len(y), K)).astype(jnp.float32)
    elif objective == "binary:logistic":
        p = float(np.clip(np.average(y, weights=w), 1e-6, 1 - 1e-6))
        base_score = np.float32(np.log(p / (1 - p)))
        pred = jnp.full(len(y), base_score, dtype=jnp.float32)
    else:
        base_score = np.float32(np.average(y, weights=w))
        pred = jnp.full(len(y), base_score, dtype=jnp.float32)

    kwargs = dict(max_depth=max_depth, num_bins=num_bins,
                  learning_rate=learning_rate, reg_lambda=reg_lambda,
                  min_child_weight=min_child_weight, objective=objective)
    n_orig = len(y)
    if mesh is not None:
        from raydp_tpu.parallel import batch_sharding
        from raydp_tpu.parallel.mesh import data_axes

        rows = batch_sharding(mesh)
        # static shapes: pad rows to the sharding divisor with zero-weight
        # rows (they contribute nothing to any histogram or leaf)
        total = int(np.prod([mesh.shape[a] for a in data_axes(mesh)]))
        pad = (-len(y)) % total
        if pad:
            Xb = np.concatenate([Xb, np.zeros((pad, Xb.shape[1]), Xb.dtype)])
            y = np.concatenate([y, np.zeros(pad, y.dtype)])
            w = np.concatenate([w, np.zeros(pad, w.dtype)])
            if multi:
                pred = jnp.concatenate(
                    [pred, jnp.broadcast_to(pred[0], (pad, pred.shape[1]))])
            else:
                pred = jnp.concatenate(
                    [pred, jnp.full(pad, pred[0], pred.dtype)])
        Xb_j = jax.device_put(jnp.asarray(Xb), rows)
        y_j = jax.device_put(jnp.asarray(y), rows)
        w_j = jax.device_put(jnp.asarray(w), rows)
        pred = jax.device_put(pred, rows)
    else:
        Xb_j, y_j, w_j = jnp.asarray(Xb), jnp.asarray(y), jnp.asarray(w)

    evals_result: Dict[str, List[float]] = {}
    if evals is None:
        # fast path: one scan over all rounds, no host round-trips
        trees, pred = _boost_chunk(Xb_j, y_j, w_j, pred, chunk=num_trees,
                                   **kwargs)
        tables = [np.asarray(t) for t in trees]
        best_iteration = None
    else:
        eX, ey = evals
        eXb = apply_bins(np.asarray(eX, np.float32), bin_edges)
        ey = np.asarray(ey, np.float32)
        if multi:
            eval_margin = np.broadcast_to(base_score,
                                          (len(ey), len(base_score))).copy()
        else:
            eval_margin = np.full(len(ey), base_score, np.float32)
        metric_name = eval_metric(eval_margin, ey, objective)[0]
        if early_stopping_rounds is None:
            # no host decisions between rounds: fuse training AND the
            # per-round eval into one device scan — one dispatch total
            trees, pred, values = _boost_chunk_eval(
                Xb_j, y_j, w_j, pred, jnp.asarray(eXb), jnp.asarray(ey),
                jnp.asarray(eval_margin), chunk=num_trees, **kwargs)
            tables = [np.asarray(t) for t in trees]
            history = [float(v) for v in np.asarray(values)]
            evals_result = {f"eval_{metric_name}": history}
            best_iteration = None
        else:
            # early stopping: the keep/stop decision is host semantics —
            # round-at-a-time with host metric checks
            parts: List[Tuple[np.ndarray, ...]] = []
            history: List[float] = []
            best, best_round = np.inf, -1
            for rnd in range(num_trees):
                trees, pred = _boost_chunk(Xb_j, y_j, w_j, pred, chunk=1,
                                           **kwargs)
                chunk_tables = tuple(np.asarray(t) for t in trees)
                parts.append(chunk_tables)
                eval_margin = eval_margin + predict_binned(
                    eXb, *chunk_tables, max_depth)
                _, value = eval_metric(eval_margin, ey, objective)
                history.append(value)
                if value < best - 1e-12:
                    best, best_round = value, rnd
                if rnd - best_round >= early_stopping_rounds:
                    break
            evals_result = {f"eval_{metric_name}": history}
            # a metric that never improves (NaN/inf) leaves best_round at -1:
            # keep at least the first round rather than an empty forest
            best_round = max(best_round, 0)
            keep = best_round + 1
            tables = [np.concatenate([p[i] for p in parts[:keep]], axis=0)
                      for i in range(3)]
            best_iteration = best_round
            if keep < len(parts):  # truncated: train margins must match
                pred = base_score + predict_binned(Xb, *tables, max_depth)

    model = GBDTModel(split_feature=tables[0], split_bin=tables[1],
                      leaf_value=tables[2], bin_edges=bin_edges,
                      base_score=np.asarray(base_score),
                      max_depth=max_depth, objective=objective,
                      best_iteration=best_iteration)
    return model, np.asarray(pred)[:n_orig], evals_result
