"""MLP models for tabular regression/classification.

``NYCTaxiModel`` mirrors the reference's fare-regression network layer for layer
(examples/pytorch_nyctaxi.py:69-92: Linear 256→128→64→16→1 with ReLU+BatchNorm),
expressed as Flax so XLA fuses the elementwise chain into the matmuls. bfloat16
compute is a constructor flag — tabular widths this small are latency-bound on
the VPU side, but bf16 halves HBM traffic on the batch and activations.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Generic MLP: hidden widths, optional batch-norm, single head."""

    features: Sequence[int]
    out_features: int = 1
    use_batch_norm: bool = True
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        dtype = self.dtype or x.dtype
        x = x.astype(dtype)
        for width in self.features:
            x = nn.Dense(width, dtype=dtype)(x)
            x = nn.relu(x)
            if self.use_batch_norm:
                x = nn.BatchNorm(use_running_average=not train, dtype=dtype)(x)
        x = nn.Dense(self.out_features, dtype=dtype)(x)
        return x.astype(jnp.float32)


def NYCTaxiModel(dtype: Optional[jnp.dtype] = None,
                 use_batch_norm: bool = True) -> MLP:
    """The reference's NYC_Model topology (pytorch_nyctaxi.py:69-92)."""
    return MLP(features=(256, 128, 64, 16), out_features=1,
               use_batch_norm=use_batch_norm, dtype=dtype)
