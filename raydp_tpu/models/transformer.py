"""Decoder-only Transformer LM — the long-context model family.

The reference exercises only MLPs/DLRM over tabular data and ships no
sequence parallelism (SURVEY.md §2.4, §5 "long-context: absent"); this model is
the capability the TPU build adds on top of parity. The attention layer
dispatches by configuration:

- ``attention="ring"`` — exact attention over a sequence-sharded batch via
  :func:`raydp_tpu.ops.ring_attention.ring_attention_sharded`: K/V blocks
  rotate around the mesh's ``seq`` axis with ``ppermute`` (ICI neighbor links),
  memory O(T / seq_devices) per device;
- ``attention="flash"`` — single-device memory-efficient attention via the
  first-party Pallas kernel (:mod:`raydp_tpu.ops.flash_attention`);
- ``attention="dense"`` — reference path for tests;
- ``attention="auto"`` — ring when the mesh has a ``seq`` axis > 1, else flash
  on TPU, else dense.

Architecture: pre-RMSNorm blocks, rotary position embeddings, SwiGLU MLP —
all plain dense ops XLA tiles onto the MXU; bf16-friendly throughout
(``dtype`` controls activations, params stay f32 for stable optimization).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


def rotary_embedding(x: jnp.ndarray, positions: jnp.ndarray,
                     base: float = 10000.0) -> jnp.ndarray:
    """Apply RoPE. x: [B, T, H, D]; positions: [T] global token positions."""
    d_half = x.shape[-1] // 2
    freqs = 1.0 / (base ** (np.arange(0, d_half) / d_half))
    angles = positions[:, None] * freqs[None, :]            # [T, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :d_half], x[..., d_half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        return (x * jax.lax.rsqrt(var + self.eps)).astype(x.dtype) * scale


class Attention(nn.Module):
    num_heads: int
    attention: str = "auto"
    mesh: Any = None
    dtype: Any = jnp.float32

    def _dispatch(self) -> str:
        from raydp_tpu.parallel.mesh import seq_extent

        if self.attention != "auto":
            return self.attention
        if self.mesh is not None and seq_extent(self.mesh) > 1:
            return "ring"
        return "flash" if jax.default_backend() == "tpu" else "dense"

    @nn.compact
    def __call__(self, x):
        from raydp_tpu.ops.flash_attention import flash_attention
        from raydp_tpu.ops.ring_attention import (
            dense_attention, ring_attention_sharded)

        b, t, dim = x.shape
        head_dim = dim // self.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim), axis=-1, name=name, dtype=self.dtype,
            use_bias=False)
        q, k, v = dense("q")(x), dense("k")(x), dense("v")(x)

        positions = jnp.arange(t)
        q = rotary_embedding(q, positions)
        k = rotary_embedding(k, positions)

        kind = self._dispatch()
        if kind == "ring":
            out = ring_attention_sharded(q, k, v, self.mesh, causal=True)
        elif kind == "flash":
            out = flash_attention(q, k, v, causal=True)
        else:
            out = dense_attention(q, k, v, causal=True)
        return nn.DenseGeneral(dim, axis=(-2, -1), name="o", dtype=self.dtype,
                               use_bias=False)(out)


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    attention: str = "auto"
    mesh: Any = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        dim = x.shape[-1]
        x = x + Attention(self.num_heads, self.attention, self.mesh,
                          self.dtype, name="attn")(RMSNorm(name="ln1")(x))
        h = RMSNorm(name="ln2")(x)
        hidden = self.mlp_ratio * dim
        # SwiGLU
        gate = nn.Dense(hidden, use_bias=False, dtype=self.dtype,
                        name="gate")(h)
        up = nn.Dense(hidden, use_bias=False, dtype=self.dtype, name="up")(h)
        down = nn.Dense(dim, use_bias=False, dtype=self.dtype,
                        name="down")(nn.silu(gate) * up)
        return x + down


class TransformerLM(nn.Module):
    """Causal LM: tokens [B, T] int32 → logits [B, T, vocab]."""

    vocab_size: int
    dim: int = 256
    num_heads: int = 4
    num_layers: int = 2
    mlp_ratio: int = 4
    attention: str = "auto"
    mesh: Any = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens, return_hidden: bool = False):
        """``return_hidden=True`` yields the post-norm hidden states [B,T,D]
        (the lm_head weight is still created so the param tree is identical);
        pair it with :func:`lm_loss_fused`, which applies the head per
        T-chunk so the [B,T,V] float32 logits never materialize — at 32k
        vocab and T=8192 those logits are ~2 GB per direction of pure HBM
        traffic, the single largest non-kernel cost in the train step."""
        x = nn.Embed(self.vocab_size, self.dim, name="embed",
                     dtype=self.dtype)(tokens)
        for i in range(self.num_layers):
            x = Block(self.num_heads, self.mlp_ratio, self.attention,
                      self.mesh, self.dtype, name=f"block_{i}")(x)
        x = RMSNorm(name="ln_f")(x)
        head = nn.Dense(self.vocab_size, use_bias=False, dtype=self.dtype,
                        name="lm_head")
        if return_hidden:
            head(x[:, :1])  # registers the kernel (result DCE'd); the head
            return x        # itself is applied chunk-wise by lm_loss_fused
        return head(x).astype(jnp.float32)


def lm_loss(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Next-token cross entropy (shifted); tokens [B, T], logits [B, T, V]."""
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], tokens[:, 1:]).mean()


def lm_loss_fused(hidden: jnp.ndarray, lm_head_kernel: jnp.ndarray,
                  tokens: jnp.ndarray, chunk: int = 1024,
                  remat: bool = True) -> jnp.ndarray:
    """Next-token cross entropy with the lm_head FUSED into the loss.

    The head matmul + softmax-CE run per T-chunk of ``chunk`` positions under
    ``jax.checkpoint`` inside a ``lax.scan``: forward keeps only the hidden
    states (already live) and per-chunk scalars, backward recomputes each
    chunk's logits — peak logits footprint is ``B×chunk×V`` instead of
    ``B×T×V`` f32 (64× smaller at T=8192/chunk=1024/f32), while each chunk
    matmul ``[B·chunk, D] @ [D, V]`` stays MXU-sized. This trades one extra
    head matmul (recompute) for ~4 GB of HBM round-trips per step at the
    bench shape, which is bandwidth the step actually runs out of — the
    round-2 gap between kernel MFU (51%) and e2e MFU (35%).

    ``hidden`` [B, T, D] from ``model(tokens, return_hidden=True)``;
    ``lm_head_kernel`` [D, V] = ``params["lm_head"]["kernel"]``.
    """
    import optax
    from jax import lax

    B, T, D = hidden.shape
    x = hidden[:, :-1]                   # predict positions 1..T-1
    y = tokens[:, 1:]
    n = T - 1
    pad = (-n) % chunk
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        y = jnp.pad(y, ((0, 0), (0, pad)))
    mask = (jnp.arange(n + pad) < n).astype(jnp.float32)[None, :]
    nchunks = (n + pad) // chunk
    xs = x.reshape(B, nchunks, chunk, D).swapaxes(0, 1)      # [N, B, C, D]
    ys = y.reshape(B, nchunks, chunk).swapaxes(0, 1)         # [N, B, C]
    ms = mask.reshape(1, nchunks, chunk).swapaxes(0, 1)      # [N, 1, C]

    def chunk_ce(total, xyz):
        xc, yc, mc = xyz
        logits = (xc @ lm_head_kernel.astype(xc.dtype)).astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, yc)
        return total + (ce * mc).sum(), None

    body = jax.checkpoint(chunk_ce) if remat else chunk_ce
    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (xs, ys, ms))
    return total / (B * n)


def transformer_param_rules(axis: str = "tensor"):
    """Megatron-style tensor-parallel sharding rules for :class:`TransformerLM`
    (for ``FlaxEstimator(param_rules=...)`` / ``param_sharding_rules``).

    Column-parallel up-projections (q/k/v over heads, gate/up over hidden) and
    row-parallel down-projections (o, down) — GSPMD then inserts exactly one
    all-reduce per attention block and one per MLP block, the classic split.
    Embedding and lm_head shard over the vocab/feature dim. The ``tensor``
    axis should be innermost on hardware so these per-layer collectives ride
    the fastest ICI links (raydp_tpu/parallel/mesh.py axis order).
    """
    return [
        ("attn/q/kernel", (None, axis, None)),
        ("attn/k/kernel", (None, axis, None)),
        ("attn/v/kernel", (None, axis, None)),
        ("attn/o/kernel", (axis, None, None)),
        ("gate/kernel", (None, axis)),
        ("up/kernel", (None, axis)),
        ("down/kernel", (axis, None)),
        ("embed/embedding", (None, axis)),
        ("lm_head/kernel", (None, axis)),
    ]
