"""Native (C++) tier of the runtime.

The reference's native tier is its JVM runtime (SURVEY.md §2.2-2.3); the
component on the data hot path that needs a true native equivalent here is the
shared-memory object store core (plasma analogue). ``arena`` builds and binds
``csrc/store/arena.cpp``.
"""

from raydp_tpu.native.arena import Arena, native_store_available

__all__ = ["Arena", "native_store_available"]
