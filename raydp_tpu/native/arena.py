"""ctypes binding + on-demand build of the C++ shared-memory arena.

The C core (``csrc/store/arena.cpp``) is compiled once per machine into
``raydp_tpu/native/_lib/librdtstore.so`` the first time a session needs it
(guarded by a file lock so concurrently-spawning actor processes don't race the
compiler). Readers of arena-resident objects do not need this library at all —
they attach the segment with :mod:`multiprocessing.shared_memory` and slice a
zero-copy memoryview; only writers (``rdt_alloc``) and the head's free path
(``rdt_free``) go through the native calls.
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
import threading
from typing import Dict, Optional, Tuple

from raydp_tpu.log import get_logger

logger = get_logger("native.arena")

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc", "store", "arena.cpp")
_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_lib")
_LIB = os.path.join(_LIB_DIR, "librdtstore.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build() -> None:
    os.makedirs(_LIB_DIR, exist_ok=True)
    lock_path = os.path.join(_LIB_DIR, ".build.lock")
    with open(lock_path, "w") as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            if os.path.exists(_LIB) and (
                    not os.path.exists(_SRC)  # prebuilt lib shipped sans csrc/
                    or os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
                return
            tmp = _LIB + ".tmp"
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 "-o", tmp, _SRC, "-lpthread", "-lrt"],
                check=True, capture_output=True, text=True)
            os.replace(tmp, _LIB)
            logger.info("built native store core -> %s", _LIB)
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            _build()
            lib = ctypes.CDLL(_LIB)
            lib.rdt_arena_create.restype = ctypes.c_void_p
            lib.rdt_arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
            lib.rdt_arena_attach.restype = ctypes.c_void_p
            lib.rdt_arena_attach.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
            lib.rdt_alloc.restype = ctypes.c_int64
            lib.rdt_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.rdt_free.restype = ctypes.c_int
            lib.rdt_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.rdt_stats.restype = None
            lib.rdt_stats.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
            lib.rdt_detach.restype = ctypes.c_int
            lib.rdt_detach.argtypes = [ctypes.c_void_p]
            lib.rdt_unlink.restype = ctypes.c_int
            lib.rdt_unlink.argtypes = [ctypes.c_char_p]
            _lib = lib
        except Exception as e:
            _lib_failed = True
            logger.warning("native store core unavailable (%s); "
                           "falling back to per-object segments", e)
        return _lib


def native_store_available() -> bool:
    return _load() is not None


class Arena:
    """One session-wide shared-memory arena holding all object payloads.

    ``segment`` is the Python-style segment name (no leading slash), the same
    name :class:`multiprocessing.shared_memory.SharedMemory` uses, so readers
    without the native library can still attach it.
    """

    def __init__(self, segment: str, base: int, size: int, owner: bool):
        self.segment = segment
        self.size = size
        self._base = base
        self._owner = owner
        self._closed = False

    # -- construction -------------------------------------------------------
    @classmethod
    def create(cls, segment: str, size: int) -> "Arena":
        lib = _load()
        if lib is None:
            raise RuntimeError("native store core unavailable")
        base = lib.rdt_arena_create(("/" + segment).encode(), size)
        if not base:
            raise RuntimeError(
                f"failed to create arena segment {segment} ({size} bytes)")
        return cls(segment, base, size, owner=True)

    @classmethod
    def attach(cls, segment: str) -> "Arena":
        lib = _load()
        if lib is None:
            raise RuntimeError("native store core unavailable")
        size = ctypes.c_uint64()
        base = lib.rdt_arena_attach(("/" + segment).encode(), ctypes.byref(size))
        if not base:
            raise RuntimeError(f"failed to attach arena segment {segment}")
        return cls(segment, base, size.value, owner=False)

    # -- allocation ---------------------------------------------------------
    def alloc(self, size: int) -> Optional[int]:
        """Payload offset for ``size`` bytes, or None if the arena is full."""
        off = _load().rdt_alloc(self._base, size)
        return None if off < 0 else off

    def free(self, offset: int) -> bool:
        return _load().rdt_free(self._base, offset) == 0

    def view(self, offset: int, size: int) -> memoryview:
        """Zero-copy writable view of the payload at ``offset``."""
        if offset < 0 or offset + size > self.size:
            raise ValueError(f"view [{offset}, {offset + size}) outside arena")
        if size == 0:
            return memoryview(b"")
        buf = (ctypes.c_ubyte * size).from_address(self._base + offset)
        return memoryview(buf).cast("B")

    def stats(self) -> Dict[str, int]:
        out = (ctypes.c_uint64 * 4)()
        _load().rdt_stats(self._base, out)
        return {"arena_size": out[0], "bytes_in_use": out[1],
                "num_allocs": out[2], "peak_bytes": out[3]}

    # -- lifetime -----------------------------------------------------------
    def detach(self) -> None:
        if not self._closed:
            self._closed = True
            _load().rdt_detach(self._base)

    def unlink(self) -> None:
        _load().rdt_unlink(("/" + self.segment).encode())

    def close(self) -> None:
        owner = self._owner
        self.detach()
        if owner:
            self.unlink()
