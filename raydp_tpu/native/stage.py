"""ctypes binding + on-demand build of the native host-feed staging kernel.

``csrc/feed/stage.cpp`` fuses the Arrow-column -> [rows, features] cast and
interleave into one pass per column (the numpy path pays astype + np.stack =
two passes and an intermediate per column). The streaming feed's
``_as_numpy`` calls :func:`stage_table` and silently falls back to numpy
whenever a column is ineligible (nulls, non-primitive, unsupported dtype) or
the toolchain is absent — behavior is identical either way, pinned by
tests/test_native_stage.py parity tests.

Float→int dtype pairs are DECLINED (here and in the kernel's own dispatch):
``static_cast`` from a float to an integer is undefined behavior in C++ for
NaN/out-of-range values, while numpy's astype has different,
platform-defined behavior — the byte-parity contract only holds for
float→float and (unsigned/signed) int→int pairs, so anything else falls
back to numpy (ADVICE r5 #2).

Threads: ``RDT_STAGE_THREADS`` fans columns out over a small pool (default 1:
the CI host exposes one schedulable core, and the feed already overlaps
device compute via the DeviceFeed prefetch thread).
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np
import pyarrow as pa

from raydp_tpu import knobs
from raydp_tpu.log import get_logger

logger = get_logger("native.stage")

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc", "feed", "stage.cpp")
_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_lib")
_LIB = os.path.join(_LIB_DIR, "librdtstage.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False

#: dtype codes shared with stage.cpp (keep in sync)
_DTYPE_CODES = {
    np.dtype(np.float32): 0, np.dtype(np.float64): 1,
    np.dtype(np.int8): 2, np.dtype(np.int16): 3,
    np.dtype(np.int32): 4, np.dtype(np.int64): 5,
    np.dtype(np.uint8): 6, np.dtype(np.uint16): 7,
    np.dtype(np.uint32): 8, np.dtype(np.uint64): 9,
}
#: destination dtypes the kernel writes
_DST_CODES = {np.dtype(np.float32): 0, np.dtype(np.float64): 1,
              np.dtype(np.int32): 4, np.dtype(np.int64): 5}

#: Arrow primitive types eligible as zero-copy sources
_ARROW_NUMERIC = {
    pa.float32(): np.dtype(np.float32), pa.float64(): np.dtype(np.float64),
    pa.int8(): np.dtype(np.int8), pa.int16(): np.dtype(np.int16),
    pa.int32(): np.dtype(np.int32), pa.int64(): np.dtype(np.int64),
    pa.uint8(): np.dtype(np.uint8), pa.uint16(): np.dtype(np.uint16),
    pa.uint32(): np.dtype(np.uint32), pa.uint64(): np.dtype(np.uint64),
}


def _build() -> None:
    os.makedirs(_LIB_DIR, exist_ok=True)
    lock_path = os.path.join(_LIB_DIR, ".build.lock")
    with open(lock_path, "w") as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            if os.path.exists(_LIB) and (
                    not os.path.exists(_SRC)  # prebuilt lib sans csrc/
                    or os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
                return
            tmp = _LIB + ".tmp"
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 "-o", tmp, _SRC, "-lpthread"],
                check=True, capture_output=True, text=True)
            os.replace(tmp, _LIB)
            logger.info("built native staging kernel -> %s", _LIB)
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            _build()
            lib = ctypes.CDLL(_LIB)
            lib.rdt_stage_cast.restype = ctypes.c_int
            lib.rdt_stage_cast.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64]
            lib.rdt_stage_columns.restype = ctypes.c_int
            lib.rdt_stage_columns.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int]
            _lib = lib
        except Exception as e:  # noqa: BLE001 - numpy fallback is complete
            _lib_failed = True
            logger.warning("native staging kernel unavailable (%s); "
                           "using the numpy decode path", e)
        return _lib


def native_stage_available() -> bool:
    return _load() is not None


def _chunk_ptr(chunk: pa.Array) -> Optional[int]:
    """Raw pointer to the chunk's data buffer, honoring the array offset;
    None when the chunk is not a clean zero-copy source."""
    if chunk.null_count:
        return None
    dtype = _ARROW_NUMERIC.get(chunk.type)
    if dtype is None:
        return None
    bufs = chunk.buffers()
    if len(bufs) != 2 or bufs[1] is None:
        return None
    return bufs[1].address + chunk.offset * dtype.itemsize


def stage_table(table: pa.Table, columns: Sequence[str],
                dtype: np.dtype) -> Optional[np.ndarray]:
    """``[rows, len(columns)]`` array of ``dtype`` decoded natively, or None
    when any column is ineligible (caller falls back to numpy)."""
    dtype = np.dtype(dtype)
    dst_code = _DST_CODES.get(dtype)
    if dst_code is None or len(columns) < 2:
        return None  # single column: numpy's cast is already one pass
    lib = _load()
    if lib is None:
        return None

    rows = table.num_rows
    # scan EVERY chunk for eligibility before allocating or casting anything:
    # discovering an ineligible chunk mid-decode would waste the whole pass
    # (numpy would then redo it) on every batch of a streaming feed
    dst_integral = dst_code in (4, 5)   # I32 / I64
    plans: List[List] = []   # per column: [(ptr, code, n_rows), ...]
    single_chunk = True
    for name in columns:
        col = table.column(name)
        if col.null_count:
            return None
        chunks = []
        for chunk in col.chunks:
            ptr = _chunk_ptr(chunk)
            if ptr is None:
                return None
            code = _DTYPE_CODES[_ARROW_NUMERIC[chunk.type]]
            if dst_integral and code in (0, 1):   # float source → int dst:
                return None                       # UB, declined (see module doc)
            chunks.append((ptr, code, len(chunk)))
        single_chunk = single_chunk and len(chunks) == 1
        plans.append(chunks)

    out = np.empty((rows, len(columns)), dtype)
    dst_ptr = out.ctypes.data

    # fast path: every column one clean chunk -> one native call with the
    # column fan-out (and optional threads) inside C++
    if single_chunk:
        n = len(plans)
        src_arr = (ctypes.c_void_p * n)(*[p[0][0] for p in plans])
        code_arr = (ctypes.c_int * n)(*[p[0][1] for p in plans])
        threads = int(knobs.get("RDT_STAGE_THREADS"))
        if lib.rdt_stage_columns(src_arr, code_arr, n, rows, dst_ptr,
                                 dst_code, threads):
            return None
        return out

    # chunked columns: per-(column, chunk) casts into the right row window
    for c, chunks in enumerate(plans):
        row0 = 0
        for ptr, code, n_rows in chunks:
            if lib.rdt_stage_cast(ptr, code, n_rows, dst_ptr, dst_code,
                                  len(columns), c, row0):
                return None
            row0 += n_rows
    return out
