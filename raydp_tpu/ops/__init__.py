"""raydp_tpu.ops — TPU kernels and collective ops.

The reference has no custom kernels (its compute is Spark + torch CPU ops); this
package is where the TPU build spends its hardware budget: ring attention for
sequence parallelism (:mod:`ring_attention`), and pallas flash-attention blocks
(:mod:`flash_attention`) for the local computation. Long-context is first-class:
the ring pattern streams K/V blocks around the ``seq`` axis over ICI while each
step's local attention runs on the MXU, overlapping transfer with compute.
"""

from raydp_tpu.ops.ring_attention import ring_attention, ring_attention_sharded

__all__ = ["ring_attention", "ring_attention_sharded"]
